"""Scheduling-as-a-service: one device, a fleet of tenant clusters.

The reference serves exactly one cluster per daemon (one C++ process,
one apiserver, one Firmament instance — PAPER.md §0); its throughput
ceiling is one cluster per deployment. Here ONE ``SchedulingService``
serves N heterogeneous tenants: each tenant keeps a fully isolated
``SchedulerBridge`` (own cluster state, stats, trace stream, decision
log, knowledge base — no tenant ever sees another's uids), while every
tenant's round solve routes through one shared ``BatchDispatcher``
(service/dispatch.py) that pads instances into shape buckets and
solves each bucket as one batched device program with one batched
fetch.

The front door is an async request queue: ``submit(tenant_id)``
enqueues one scheduling round and returns a ``concurrent.futures
.Future`` resolving to that tenant's ``RoundResult``. The driver (cli
``--serve``, bench config 11, or an embedding process) calls ``pump()``
to advance the double-buffered pipeline, the PR-1 begin/finish split
writ multi-tenant:

    pump k:   finish wave k-1 (join ITS fetch, deltas, stats)
              begin + launch wave k (builds, pricing, upload,
              dispatch, async fetch)                          ──┐
    driver:   actuate wave k-1's binding POSTs, observe the     │ overlap
              next tick, queue the next submissions           ◄─┘

so the driver's actuation and observe host work elapse while wave k's
batch is in flight on the device, and every tenant completes one
round per pump. Same-tick duplicate submissions for one tenant wait
for the next wave (one round in flight per tenant, the bridge's own
invariant).
"""

from __future__ import annotations

import collections
import concurrent.futures
import dataclasses
import logging
import time

from poseidon_tpu.bridge import SchedulerBridge
from poseidon_tpu.service.dispatch import BatchDispatcher, TenantSolver
from poseidon_tpu.trace import TraceGenerator

log = logging.getLogger(__name__)

# Prometheus label-cardinality bound: the first N registered tenants
# get their own label value, later ones collapse into "other" (the
# per-tenant series stay finite no matter how many tenants churn
# through a long-lived service).
MAX_TENANT_LABELS = 24


@dataclasses.dataclass
class TenantSession:
    """One tenant's isolated scheduling state inside the service."""

    tenant_id: str
    bridge: SchedulerBridge
    solver: TenantSolver
    trace: TraceGenerator
    label: str                      # bounded metrics label
    rounds: int = 0
    placed_total: int = 0
    last_round_ms: float = 0.0


@dataclasses.dataclass
class _Wave:
    """One in-flight dispatch wave: (session, InflightRound, future,
    t_submit) per member."""

    entries: list = dataclasses.field(default_factory=list)


class SchedulingService:
    """The multi-tenant front door. Single-threaded by contract on the
    pump side (every bridge/dispatcher call happens on the pump
    caller's thread); ``submit`` may be called from any thread — the
    deque append and the Future are the documented handoffs."""

    def __init__(
        self,
        *,
        alpha: int = 1024,
        max_rounds: int | None = None,
        oracle_fallback: bool = True,
        oracle_timeout_s: float = 1000.0,
        max_batch: int = 64,
        metrics=None,
    ):
        self.metrics = metrics
        self.dispatcher = BatchDispatcher(
            alpha=alpha,
            max_rounds=max_rounds,
            oracle_fallback=oracle_fallback,
            oracle_timeout_s=oracle_timeout_s,
            max_batch=max_batch,
            metrics=metrics,
        )
        self.sessions: dict[str, TenantSession] = {}
        # submissions: (tenant_id, Future, t_submit); deque append/pop
        # are atomic (GIL) — the cross-thread handoff for submit()
        self._submissions: collections.deque = collections.deque()
        self._inflight: _Wave | None = None
        self.waves = 0

    # ---- tenants -------------------------------------------------------

    def add_tenant(
        self,
        tenant_id: str,
        *,
        cost_model: str = "quincy",
        trace: TraceGenerator | None = None,
        enable_preemption: bool = False,
        migration_hysteresis: int = 20,
        max_migrations_per_round: int = 64,
        incremental_build: bool = True,
        max_tasks_per_machine: int = 10,
    ) -> TenantSession:
        """Register one tenant: its own bridge (isolated state, trace,
        decision log) wired to the shared dispatcher through a
        ``TenantSolver``. Per-tenant cost models and flag sets are the
        point — heterogeneity is batched, not normalized away."""
        if tenant_id in self.sessions:
            raise ValueError(f"tenant {tenant_id!r} already registered")
        solver = TenantSolver(tenant_id, self.dispatcher)
        tr = trace or TraceGenerator()
        # every event this session emits carries the tenant id,
        # so N tenants can share one trace sink and still be
        # reported individually (trace report --tenant)
        tr.tenant = tenant_id
        lifecycle = None
        if self.metrics is not None:
            # per-tenant lifecycle timelines close into the SHARED
            # e2c histogram under lane="service" (the bridge stamps
            # the lane from its own lane label)
            from poseidon_tpu.obs.lifecycle import LifecycleTracker

            lifecycle = LifecycleTracker(self.metrics)
        bridge = SchedulerBridge(
            cost_model=cost_model,
            max_tasks_per_machine=max_tasks_per_machine,
            trace=tr,
            enable_preemption=enable_preemption,
            migration_hysteresis=migration_hysteresis,
            max_migrations_per_round=max_migrations_per_round,
            incremental_build=incremental_build,
            solver=solver,
            lifecycle=lifecycle,
        )
        bridge.lane = "service"
        label = (
            tenant_id if len(self.sessions) < MAX_TENANT_LABELS
            else "other"
        )
        session = TenantSession(
            tenant_id=tenant_id, bridge=bridge, solver=solver,
            trace=tr, label=label,
        )
        self.sessions[tenant_id] = session
        return session

    # ---- the async front door ------------------------------------------

    def submit(self, tenant_id: str) -> concurrent.futures.Future:
        """Enqueue one scheduling round for a tenant; the Future
        resolves to its ``RoundResult`` after a later ``pump()``
        dispatches and finishes the wave containing it."""
        if tenant_id not in self.sessions:
            raise KeyError(f"unknown tenant {tenant_id!r}")
        fut: concurrent.futures.Future = concurrent.futures.Future()
        self._submissions.append((tenant_id, fut, time.perf_counter()))
        return fut

    def pump(self) -> list[tuple[str, object]]:
        """Advance the pipeline one wave: finish the previous wave
        (join ITS batched fetch), then begin + launch the next one
        from the queued submissions. Returns the finished wave's
        [(tenant_id, RoundResult)] (empty on the priming call).

        The overlap window is everything the caller does AFTER pump
        returns and before the next pump — actuating the returned
        wave's binding POSTs, observing the next tick — all of which
        elapses while the just-launched wave is in flight on the
        device. (Finishing BEFORE beginning is what lets every tenant
        complete one round per pump: the alternative ordering skips
        any tenant still in flight, halving throughput and growing
        the submission queue without bound under a steady driver.)
        """
        done = self._finish_wave(self._inflight)
        self._inflight = None
        wave = _Wave()
        skipped: list = []
        seen: set[str] = set()
        while self._submissions:
            tenant_id, fut, t_submit = self._submissions.popleft()
            if tenant_id in seen:
                # one round per tenant per wave: same-tick duplicate
                # submissions wait for the next wave (order preserved)
                skipped.append((tenant_id, fut, t_submit))
                continue
            seen.add(tenant_id)
            session = self.sessions[tenant_id]
            try:
                ir = session.bridge.begin_round()
            except Exception as e:  # a failed build must not kill the wave
                log.exception(
                    "tenant %s begin_round failed", tenant_id
                )
                fut.set_exception(e)
                continue
            if ir.result is not None:
                # empty round: completed synchronously
                self._account(session, ir.result, t_submit)
                fut.set_result(ir.result)
                continue
            wave.entries.append((session, ir, fut, t_submit))
        self._submissions.extendleft(reversed(skipped))
        if wave.entries:
            self.dispatcher.launch()
            self.waves += 1
            self._inflight = wave
        return done

    def flush(self) -> list[tuple[str, object]]:
        """Finish the in-flight wave (and any still-queued submissions)
        without starting a new one: pump until the pipeline drains."""
        out = self._finish_wave(self._inflight)
        self._inflight = None
        while self._submissions:
            out.extend(self.pump())
        out.extend(self._finish_wave(self._inflight))
        self._inflight = None
        return out

    def _finish_wave(self, wave: _Wave | None) -> list:
        if wave is None:
            return []
        done = []
        for session, ir, fut, t_submit in wave.entries:
            try:
                result = session.bridge.finish_round(ir)
            except Exception as e:
                log.exception(
                    "tenant %s finish_round failed",
                    session.tenant_id,
                )
                session.bridge.cancel_round(ir)
                fut.set_exception(e)
                continue
            self._account(session, result, t_submit)
            fut.set_result(result)
            done.append((session.tenant_id, result))
        return done

    def _account(self, session, result, t_submit: float) -> None:
        session.rounds += 1
        session.placed_total += len(result.bindings)
        session.last_round_ms = (
            time.perf_counter() - t_submit
        ) * 1000
        if self.metrics is not None:
            self.metrics.record_service_round(
                session.label, session.last_round_ms,
                len(result.bindings),
            )
