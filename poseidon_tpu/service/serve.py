"""The ``--serve`` driver: N tenant apiservers, one scheduling device.

Each tenant is a (client, session) pair: the loop polls every tenant's
apiserver, submits a round for each tenant with schedulable work, pumps
the service's double-buffered pipeline once, and actuates the finished
wave's deltas back against each tenant's own apiserver — so wave k's
binding POSTs overlap wave k+1's in-flight batch. Per-tenant isolation
holds end to end: every tenant has its own bridge, trace stream, and
decision log, and a binding only ever POSTs to the apiserver it was
observed from.

Tenant sources:

- ``--serve_apiservers=host:port,host:port,...`` — real endpoints, one
  tenant each (named ``tenant-<i>``);
- ``--serve_tenants=N`` — N in-process fake apiservers with
  heterogeneous synthetic workloads (distinct node/pod counts, cost
  models cycled across the registry, preemption enabled on every 4th
  tenant): the zero-dependency demo/smoke mode CI drives.

``--max_rounds`` counts dispatch cycles (0 = forever); the loop exits
early in fake mode once every tenant's pods are bound.
"""

from __future__ import annotations

import argparse
import contextlib
import logging
import sys
import time

from poseidon_tpu.apiclient.client import ApiError, K8sApiClient
from poseidon_tpu.service.service import SchedulingService
from poseidon_tpu.trace import TraceGenerator

log = logging.getLogger("poseidon_tpu.serve")

# fake-mode tenant heterogeneity: cost models cycled per tenant (the
# registry minus 'random', whose hashed costs make bit-identity sweeps
# noisy to read), preemption on every 4th tenant
_FAKE_MODELS = ("quincy", "coco", "octopus")


def _fake_tenants(n: int, stack: contextlib.ExitStack):
    """Spin up N in-process fake apiservers with heterogeneous synthetic
    workloads; returns [(tenant_id, server, cost_model, preemption)]."""
    from poseidon_tpu.apiclient.fake_server import FakeApiServer

    out = []
    for i in range(n):
        server = stack.enter_context(FakeApiServer())
        n_nodes = 4 + 3 * (i % 5)
        n_pods = 24 + 11 * (i % 7)
        for k in range(n_nodes):
            server.add_node(
                f"t{i}-n{k:03d}", cpu="16", memory="32Gi", pods=10,
                rack=f"t{i}-r{k % 3}",
            )
        for j in range(n_pods):
            prefs = (
                {f"t{i}-n{j % n_nodes:03d}": 40 + (j % 5) * 10}
                if j % 3 == 0 else None
            )
            server.add_pod(
                f"t{i}-pod-{j:04d}", cpu="100m", memory="64Mi",
                job=f"t{i}-job{j // 6}", data_prefs=prefs,
            )
        out.append((
            f"tenant-{i}", server, _FAKE_MODELS[i % len(_FAKE_MODELS)],
            i % 4 == 3,
        ))
    return out


def run_serve(args: argparse.Namespace) -> int:
    logging.basicConfig(
        level=logging.INFO,
        stream=sys.stderr if args.logtostderr else None,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    # observability (shared across tenants; per-tenant series carry a
    # bounded tenant label — see obs/metrics.py)
    obs_server = None
    health = None
    sched_metrics = None
    if args.metrics_port:
        from poseidon_tpu.obs import (
            HealthState,
            MetricsRegistry,
            ObsServer,
            SchedulerMetrics,
        )

        sched_metrics = SchedulerMetrics(MetricsRegistry())
        health = HealthState(ready_gauge=sched_metrics.ready)
        obs_server = ObsServer(
            sched_metrics.registry, health, port=args.metrics_port,
            host=args.metrics_host,
        )
    service = SchedulingService(
        oracle_timeout_s=args.max_solver_runtime / 1e6,
        max_batch=args.serve_max_batch,
        metrics=sched_metrics,
    )

    with contextlib.ExitStack() as stack:
        trace_fh = None
        if args.trace_log:
            trace_fh = stack.enter_context(open(args.trace_log, "a"))
        tenants: list[tuple[str, K8sApiClient]] = []
        fake = []
        if args.serve_apiservers:
            endpoints = [
                e for e in args.serve_apiservers.split(",") if e
            ]
            for i, ep in enumerate(endpoints):
                host, _, port = ep.partition(":")
                tid = f"tenant-{i}"
                service.add_tenant(
                    tid,
                    cost_model=args.flow_scheduling_cost_model,
                    trace=TraceGenerator(sink=trace_fh),
                    enable_preemption=args.enable_preemption == "true",
                    incremental_build=args.incremental_build == "true",
                    max_tasks_per_machine=args.max_tasks_per_pu,
                )
                tenants.append((
                    tid,
                    K8sApiClient(
                        host or "127.0.0.1", int(port or 8080),
                        args.k8s_api_version, timeout_s=10.0,
                    ),
                ))
        elif args.serve_tenants > 0:
            fake = _fake_tenants(args.serve_tenants, stack)
            for tid, server, model, preempt in fake:
                service.add_tenant(
                    tid,
                    cost_model=model,
                    trace=TraceGenerator(sink=trace_fh),
                    enable_preemption=preempt,
                    incremental_build=args.incremental_build == "true",
                    max_tasks_per_machine=args.max_tasks_per_pu,
                )
                tenants.append((
                    tid,
                    K8sApiClient("127.0.0.1", server.port,
                                 args.k8s_api_version, timeout_s=10.0),
                ))
        else:
            log.error(
                "--serve needs --serve_apiservers=h:p,... or "
                "--serve_tenants=N"
            )
            return 2
        clients = dict(tenants)

        def _observe(tid: str) -> bool:
            session = service.sessions[tid]
            try:
                nodes = clients[tid].all_nodes()
                pods = clients[tid].all_pods()
            except ApiError as e:
                log.error(
                    "tenant %s poll failed, skipping: %s", tid, e
                )
                return False
            session.bridge.observe_nodes(nodes)
            session.bridge.observe_pods(pods)
            return True

        def _actuate(tid: str, result) -> None:
            from poseidon_tpu.cli import (
                _actuate_rebalance,
                _post_bindings,
            )

            session = service.sessions[tid]
            client = clients[tid]
            if result.bindings:
                for uid, machine, outcome in _post_bindings(
                    client, session.bridge, result.bindings
                ):
                    if outcome == "ok":
                        session.bridge.confirm_binding(uid, machine)
                    else:
                        log.warning(
                            "tenant %s bind POST failed for %s; "
                            "re-queueing", tid, uid,
                        )
                        session.bridge.binding_failed(uid)
            if result.migrations or result.preemptions:
                _actuate_rebalance(
                    client, session.bridge, result.migrations,
                    result.preemptions, confirm=True,
                )

        if obs_server is not None:
            obs_server.start()
        try:
            cycles = 0
            while True:
                tick_start = time.perf_counter()
                observed = [t for t, _ in tenants if _observe(t)]
                if health is not None and observed:
                    health.mark_seeded()
                for tid in observed:
                    service.submit(tid)
                # one pipeline advance: finishes (and returns) the
                # previous wave, then launches this one — the returned
                # wave's binding POSTs below overlap the batch now in
                # flight
                for tid, result in service.pump():
                    _actuate(tid, result)
                    s = result.stats
                    log.info(
                        "%s round %d: pending=%d placed=%d cost=%d "
                        "backend=%s total=%.1fms", tid, s.round_num,
                        s.pods_pending, s.pods_placed, s.cost,
                        s.backend, s.total_ms,
                    )
                    if health is not None:
                        health.mark_round(s.backend)
                cycles += 1
                if args.max_rounds and cycles >= args.max_rounds:
                    break
                if fake and all(
                    len(server.bindings) >= len(server.pods)
                    for _, server, _, _ in fake
                ):
                    break
                elapsed = time.perf_counter() - tick_start
                time.sleep(
                    max(args.polling_frequency / 1e6 - elapsed, 0.0)
                )
            # drain: finish the last in-flight wave and POST its deltas
            for tid, result in service.flush():
                _actuate(tid, result)
        finally:
            if obs_server is not None:
                obs_server.stop()
    return 0
