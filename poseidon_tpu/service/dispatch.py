"""Shape-bucket router + batch dispatcher: N tenants, one device.

The solver seam of the multi-tenant service (see ``service/service.py``
for the front door). Each tenant's ``SchedulerBridge`` talks to a
``TenantSolver`` — the same ``begin_round``/``finish_round`` surface as
``ResidentSolver`` — but begin registers the tenant's priced instance
with the shared ``BatchDispatcher`` instead of dispatching it alone.
At launch, queued instances group into shape buckets keyed by their
padded (Tp, Mp, P) dims (each tenant pads to its OWN grow-only floors
— ``ops/resident.TenantWarmPool`` — so a tenant's in-bucket solve is
the same function as its solo solve, and steady-state dispatches hit
zero recompiles), and each bucket solves as one batched device
program: ONE ``device_put`` of the stacked channel tables, per-member
pipelined dispatches of the unchanged ``ops/batch._solve_member``
kernel (NOT a vmapped lockstep — see ops/batch.py's measured
economics), and ONE batched ``device_get`` running on a background
thread from the moment of dispatch.

Pricing runs on the host CPU backend (the same rule as the resident
lane's small-instance degrade path): the registry cost models are
O(arcs) elementwise jnp, so a per-tenant pricing fetch on the CPU
backend never crosses the device link — the solve's batched fetch is
the dispatch's one sanctioned download.

Per-tenant exactness: a member's bucketed solve is bit-identical to
its solo ``solve_transport_dense`` (tests/test_service.py pins this
across cost models, preemption modes, and mixed shape buckets); an
uncertified warm solve retries cold, and anything past that degrades
LOUDLY to the C++ oracle for that tenant alone — never a silent wrong
placement, and never a stall for the rest of the batch.
"""

from __future__ import annotations

import dataclasses
import logging
import time

import jax
import jax.numpy as jnp
import numpy as np

from poseidon_tpu.compat import enable_x64
from poseidon_tpu.graph.builder import GraphMeta
from poseidon_tpu.graph.network import FlowNetwork, pad_bucket
from poseidon_tpu.guards import (
    CompileCounter,
    FetchTimeout,
    no_implicit_transfers,
    sanctioned_transfer,
)
from poseidon_tpu.models.costs import build_cost_inputs_host
from poseidon_tpu.ops.batch import (
    MEMBER_KEYS,
    _solve_member,
    build_member_tables,
    member_bucket_dims,
    stack_members,
)
from poseidon_tpu.ops.dense_auction import (
    I32,
    CostDomainTooLarge,
    DenseMemoryTooLarge,
    DenseState,
    _channels_for,
    check_table_budget,
    default_fuse,
    max_variants_for,
    member_side_ints,
)
from poseidon_tpu.ops.resident import (
    ResidentOutcome,
    TenantWarmPool,
    _AsyncFetch,
    _jitted_model,
)
from poseidon_tpu.ops.transport import (
    NotSchedulingShaped,
    TransportTopology,
    extract_topology,
    instance_from_topology,
)

log = logging.getLogger(__name__)

# Budget accounting: each bucket member is charged its dense table
# plus TWICE its channel side tables (ops/dense_auction
# .member_side_ints). The 2x covers the batch-axis padding — stacked
# uploads pad the member count to a grow-only pad_bucket width, which
# is at most 2x the live member count, and padding slots carry channel
# tables only (their dense tables are never materialized: padding
# members are never dispatched).
def _budget_side_ints(Tp: int, Mp: int, P: int) -> int:
    return 2 * member_side_ints(Tp, Mp, P)


@dataclasses.dataclass
class PendingSolve:
    """One tenant's registered-but-not-yet-dispatched round solve.

    The service-lane analog of ``ops/resident.InflightSolve``: returned
    by ``TenantSolver.begin_round``, consumed by ``finish_round``.
    Degrade paths (non-taxonomy graph, cost domain, empty bucket)
    resolve synchronously and carry ``outcome`` directly.
    """

    tenant: str = ""
    outcome: ResidentOutcome | None = None
    inst: object = None              # TransportInstance
    meta: GraphMeta | None = None
    topo: TransportTopology | None = None
    arrays: dict | None = None
    cost_host: np.ndarray | None = None
    tables: dict | None = None       # padded host channel tables
    T: int = 0
    n_machines: int = 0
    Tp: int = 0
    Mp: int = 0
    P: int = 0
    smax: int = 1
    warm: DenseState | None = None
    warm_used: bool = False
    chunk: object = None             # _Chunk, set at launch
    slot: int = -1
    timings: dict = dataclasses.field(default_factory=dict)
    consumed: bool = False


@dataclasses.dataclass
class _Chunk:
    """One launched bucket chunk: stacked device tables + the in-flight
    batched fetch + per-member device state refs."""

    key: tuple
    members: list
    stacked: object = None           # device tree of stacked tables
    future: _AsyncFetch | None = None
    states: list = dataclasses.field(default_factory=list)
    smax: int = 1
    t_dispatch: float = 0.0
    # set when the chunk's batched fetch missed its deadline: later
    # members fail FAST instead of each re-waiting the full timeout on
    # the same dead future (an 8-member chunk would otherwise stall
    # the wave for 8 x the deadline)
    failed: bool = False


class TenantSolver:
    """The ResidentSolver-shaped seam one tenant's bridge drives.

    ``begin_round`` prices the tenant's graph (host CPU backend),
    compacts it to transportation form, and registers it with the
    shared dispatcher; ``finish_round`` joins the bucket's batched
    fetch and completes this tenant's round (certificate check, cold
    retry, warm-context commit, oracle degrade). The debug handles
    (``last_instance`` / ``last_assignment`` / ``last_cost_host``)
    feed the bench/test bit-identity proofs.
    """

    def __init__(self, tenant_id: str, dispatcher: "BatchDispatcher"):
        self.tenant_id = tenant_id
        self.dispatcher = dispatcher
        # bridge-compat surface (the bridge reads/sets these)
        self.fetch_timeouts = 0
        self.oracle_timeout_s = dispatcher.oracle_timeout_s
        self.express_fetches = 0
        # bit-identity hooks: the last round's exact solver inputs and
        # output, host-side (tests/bench re-solve them solo)
        self.last_instance = None
        self.last_assignment = None
        self.last_cost_host = None
        self.last_arrays = None
        self.last_meta = None
        self.last_backend = ""

    # ---- bridge-compat stubs (no express lane in the service yet) ----

    @property
    def express_ready(self) -> bool:
        return False

    def invalidate_express(self) -> None:
        pass

    @property
    def warm(self):
        ctx = self.dispatcher.pool.context(self.tenant_id)
        return ctx.state

    def reset(self) -> None:
        self.dispatcher.pool.invalidate(self.tenant_id)

    # ---- the round ----------------------------------------------------

    def begin_round(
        self,
        arrays: dict[str, np.ndarray],
        meta: GraphMeta,
        *,
        cost_model: str,
        cost_input_kwargs: dict | None = None,
        topology: TransportTopology | None = None,
    ) -> PendingSolve:
        """Price + compact + register one tenant round with the shared
        dispatcher. Returns a ``PendingSolve``; the batched dispatch
        happens at the service's next ``launch()`` (or lazily on this
        tenant's ``finish_round`` — the serial one-tenant case)."""
        timings: dict[str, float] = {}
        t0 = time.perf_counter()
        ctx = self.dispatcher.pool.context(self.tenant_id)
        topo = topology
        if topo is None:
            try:
                topo = extract_topology(
                    meta, arrays["src"], arrays["dst"], arrays["cap"]
                )
            except NotSchedulingShaped:
                topo = None
        # ---- price on the host CPU backend (O(arcs) elementwise) ----
        ctx.e_floor = pad_bucket(max(meta.n_arcs, 1), minimum=ctx.e_floor)
        ctx.ti_floor = pad_bucket(
            max(len(meta.task_uids), 1), minimum=ctx.ti_floor
        )
        ctx.mi_floor = pad_bucket(
            max(len(meta.machine_names), 1), minimum=ctx.mi_floor
        )
        inputs_host = build_cost_inputs_host(
            ctx.e_floor, meta, t_min=ctx.ti_floor, m_min=ctx.mi_floor,
            **(cost_input_kwargs or {}),
        )
        try:
            cpu = jax.local_devices(backend="cpu")[0]
        except RuntimeError:  # no CPU backend registered: default dev
            cpu = None
        inputs_dev = (
            jax.device_put(inputs_host, cpu)
            if cpu is not None else jax.device_put(inputs_host)
        )
        cost = _jitted_model(cost_model)(inputs_dev)
        fetched = jax.device_get(cost)  # noqa: PTA001 -- host-CPU pricing fetch: the model ran on the CPU backend (no device-link crossing); the solve's batched fetch is the dispatch's one sanctioned download
        cost_host = np.asarray(fetched, np.int32)[: meta.n_arcs]  # noqa: PTA001 -- already-fetched host data
        timings["prep_ms"] = (time.perf_counter() - t0) * 1000
        self.last_cost_host = cost_host
        self.last_arrays = arrays
        self.last_meta = meta
        if topo is None:
            # non-taxonomy graph: the batched transportation form does
            # not apply — solve this tenant on the oracle now (the same
            # deliberate routing the resident lane makes)
            return PendingSolve(
                tenant=self.tenant_id,
                outcome=self._oracle_outcome(
                    arrays, meta, None, cost_host, timings,
                    why="not-scheduling-shaped",
                ),
            )
        inst = instance_from_topology(topo, cost_host)
        self.last_instance = inst
        return self.dispatcher.register(
            self, inst, arrays, meta, topo, cost_host, timings
        )

    def finish_round(self, pending: PendingSolve) -> ResidentOutcome:
        """Join this tenant's slice of the batched fetch and complete
        the round. Launches the dispatcher first if the wave was never
        launched (the serial path)."""
        if pending.outcome is not None:
            pending.consumed = True
            self.last_backend = pending.outcome.backend
            return pending.outcome
        self.dispatcher.ensure_launched(pending)
        out = self.dispatcher.finish(self, pending)
        self.last_backend = out.backend
        return out

    def discard_round(self, pending: PendingSolve) -> None:
        """Join and drop (driver error path) — drains the chunk fetch
        so the worker thread idles; warm state is left as it was."""
        if pending.outcome is not None or pending.consumed:
            return
        pending.consumed = True
        chunk = pending.chunk
        if chunk is None or chunk.future is None:
            return
        if chunk.failed:
            return  # deadline already paid by a bucket-mate
        try:
            chunk.future.result(
                timeout_s=self.dispatcher.oracle_timeout_s
            )
        except FetchTimeout:
            chunk.failed = True
            self.fetch_timeouts += 1
            log.error(
                "discard_round(%s): abandoning a batched fetch still "
                "pending", self.tenant_id,
            )
        except Exception:
            log.exception(
                "discard_round(%s): in-flight fetch failed",
                self.tenant_id,
            )

    def _oracle_outcome(
        self, arrays, meta, topo, cost_host, timings, *, why: str
    ) -> ResidentOutcome:
        """Degrade ONE tenant's round to the C++ oracle (host costs are
        already in hand — no device download needed here, unlike the
        resident degrade path)."""
        from poseidon_tpu.graph.decompose import extract_placements
        from poseidon_tpu.oracle import solve_oracle

        t0 = time.perf_counter()
        net = FlowNetwork.from_arrays(
            arrays["src"], arrays["dst"], arrays["cap"], cost_host,
            arrays["supply"],
        )
        o = solve_oracle(
            net, algorithm="cost_scaling",
            timeout_s=self.oracle_timeout_s,
        )
        placements = extract_placements(
            np.asarray(o.flows, np.int64), meta,
            arrays["src"], arrays["dst"],
        )
        T = len(meta.task_uids)
        midx = {name: i for i, name in enumerate(meta.machine_names)}
        asg = np.full(T, -1, np.int32)
        for i, uid in enumerate(meta.task_uids):
            m = placements.get(uid)
            if m is not None:
                asg[i] = midx[m]
        if topo is not None:
            channel = _channels_for(
                instance_from_topology(topo, cost_host), asg
            )
        else:
            channel = np.full(T, -1, np.int32)
        timings["oracle_ms"] = (time.perf_counter() - t0) * 1000
        self.last_assignment = asg
        return ResidentOutcome(
            assignment=asg,
            channel=channel,
            cost=int(o.cost),
            backend=f"oracle:{why}",
            converged=True,
            rounds=0,
            phases=0,
            topology=topo,
            timings=timings,
        )


class BatchDispatcher:
    """Groups registered tenant solves into shape buckets and solves
    each bucket as one batched device program with one batched fetch.

    Single-threaded by contract (the service pump thread owns it, like
    ``SchedulerBridge``); the only cross-thread structure is the
    ``_AsyncFetch`` handle each launched chunk carries. ``max_batch``
    bounds instances per chunk on top of the HBM budget's own fit
    (``max_variants_for``) — an oversize wave splits into several
    fitting dispatches, each with its own sanctioned fetch.
    """

    def __init__(
        self,
        *,
        alpha: int = 1024,
        max_rounds: int | None = None,
        oracle_fallback: bool = True,
        oracle_timeout_s: float = 1000.0,
        max_batch: int = 64,
        metrics=None,
    ):
        self.alpha = alpha
        self.max_rounds = (
            max_rounds if max_rounds is not None else default_fuse()
        )
        self.oracle_fallback = oracle_fallback
        self.oracle_timeout_s = oracle_timeout_s
        self.max_batch = max(max_batch, 1)
        self.metrics = metrics
        self.pool = TenantWarmPool()
        self._queue: list[PendingSolve] = []
        # grow-only per-bucket floors: batch-axis width and smax are
        # STATIC kernel knobs, so a churning tenant count / free-slot
        # high-water must not recompile the member kernel (satellite:
        # bucket dims ride grow-only floors too)
        self._b_floor: dict[tuple, int] = {}
        self._smax_floor: dict[tuple, int] = {}
        # observability: lifetime dispatches and the last launch's
        # compile count (0 in steady state — the bench asserts it)
        self.dispatches = 0
        self.last_launch_compiles = 0

    # ---- registration --------------------------------------------------

    def register(
        self, solver: TenantSolver, inst, arrays, meta, topo,
        cost_host, timings,
    ) -> PendingSolve:
        ctx = self.pool.context(solver.tenant_id)
        Tp, Mp, P = member_bucket_dims(
            inst, t_min=ctx.t_floor, m_min=ctx.m_floor,
            p_min=ctx.p_floor,
        )
        try:
            check_table_budget(
                Tp, Mp, 1,
                side_ints_per_variant=_budget_side_ints(Tp, Mp, P),
            )
            tables = build_member_tables(inst, Tp, Mp, P)
        except DenseMemoryTooLarge as e:
            # this tenant alone blows the budget: degrade it (and reset
            # its floors — a floor raised by a past larger cluster must
            # not re-pad a fitting instance over budget forever)
            self.pool.reset_floors(solver.tenant_id)
            if not self.oracle_fallback:
                raise
            log.warning(
                "tenant %s exceeds the dense HBM budget (%s); "
                "degrading to oracle", solver.tenant_id, e,
            )
            return PendingSolve(
                tenant=solver.tenant_id,
                outcome=solver._oracle_outcome(
                    arrays, meta, topo, cost_host, timings,
                    why="memory-envelope",
                ),
            )
        except (CostDomainTooLarge, ValueError) as e:
            if not self.oracle_fallback:
                raise
            log.warning(
                "tenant %s rejected by the dense kernel (%s); "
                "degrading to oracle", solver.tenant_id, e,
            )
            return PendingSolve(
                tenant=solver.tenant_id,
                outcome=solver._oracle_outcome(
                    arrays, meta, topo, cost_host, timings,
                    why="cost-domain",
                ),
            )
        ctx.t_floor, ctx.m_floor, ctx.p_floor = Tp, Mp, P
        ctx.s_floor = pad_bucket(
            max(int(inst.slots.max(initial=1)), 1), minimum=ctx.s_floor
        )
        pending = PendingSolve(
            tenant=solver.tenant_id,
            inst=inst,
            meta=meta,
            topo=topo,
            arrays=arrays,
            cost_host=cost_host,
            tables=tables,
            T=inst.n_tasks,
            n_machines=inst.n_machines,
            Tp=Tp,
            Mp=Mp,
            P=P,
            smax=min(ctx.s_floor, Tp),
            warm=self.pool.warm(solver.tenant_id, Tp, Mp),
            timings=timings,
        )
        pending.warm_used = pending.warm is not None
        self._queue.append(pending)
        return pending

    def ensure_launched(self, pending: PendingSolve) -> None:
        if pending.chunk is None and pending.outcome is None:
            self.launch()

    # ---- launch: bucket, stack, upload, dispatch, async fetch ----------

    def launch(self) -> int:
        """Dispatch every registered solve: group by (Tp, Mp, P) shape
        bucket, chunk against the HBM budget + ``max_batch``, and for
        each chunk do ONE upload, per-member kernel dispatches, and ONE
        batched background fetch. Returns the number of chunks."""
        queue, self._queue = self._queue, []
        if not queue:
            return 0
        buckets: dict[tuple, list[PendingSolve]] = {}
        for p in queue:
            buckets.setdefault((p.Tp, p.Mp, p.P), []).append(p)
        n_chunks = 0
        counter = CompileCounter()
        with counter:
            waves: list[tuple[tuple, list]] = []
            for key, members in sorted(buckets.items()):
                Tp, Mp, P = key
                fit = max_variants_for(
                    Tp, Mp,
                    side_ints_per_variant=_budget_side_ints(Tp, Mp, P),
                )
                width = max(min(self.max_batch, fit), 1)
                for i in range(0, len(members), width):
                    waves.append((key, members[i: i + width]))
            # wave streaming: stage wave 0, then dispatch wave k and
            # stage wave k+1 back-to-back — the next wave's member-
            # table upload overlaps the in-flight wave's (async) member
            # dispatches, so N waves pay ONE batched fetch each with
            # zero idle gap between them (the service-lane twin of the
            # resident stream lane's double buffer)
            staged = self._stage_chunk(*waves[0]) if waves else None
            for j in range(len(waves)):
                self._dispatch_chunk(staged)
                n_chunks += 1
                staged = (
                    self._stage_chunk(*waves[j + 1])
                    if j + 1 < len(waves) else None
                )
        self.last_launch_compiles = counter.count if counter.supported \
            else -1
        if self.metrics is not None and counter.supported:
            self.metrics.record_service_compiles(counter.count)
        return n_chunks

    def _stage_chunk(self, key: tuple, members: list):
        """Stage one wave's member tables on device WITHOUT
        dispatching: host-side stack + ONE batched upload. ``launch``
        calls this one wave ahead of ``_dispatch_chunk`` so the upload
        overlaps the previous wave's in-flight member dispatches (the
        double buffer)."""
        Tp, Mp, P = key
        # grow-only batch-axis bucket: one compiled member-kernel shape
        # per (Tp, Mp, P) even as the tenant count churns
        Bp = pad_bucket(len(members), minimum=self._b_floor.get(key, 1))
        self._b_floor[key] = Bp
        smax = max(
            self._smax_floor.get(key, 1),
            max(m.smax for m in members),
        )
        self._smax_floor[key] = smax
        t0 = time.perf_counter()
        stacked_host = stack_members([m.tables for m in members], Bp)
        # zeros + member-index scalars OUTSIDE the transfer guard:
        # their fill/scalar uploads are implicit h2d the guard would
        # reject (same rule as resident's arg prep)
        zeros_t = jnp.zeros(Tp, I32)
        zeros_m = jnp.zeros(Mp, I32)
        idxs = [jnp.int32(i) for i in range(len(members))]
        chunk = _Chunk(key=key, members=members, smax=smax)
        with no_implicit_transfers():
            stacked = jax.device_put(stacked_host)
        up_ms = (time.perf_counter() - t0) * 1000
        return (chunk, stacked, zeros_t, zeros_m, idxs, up_ms)

    def _dispatch_chunk(self, staged) -> None:
        """Dispatch a staged wave's member kernels and start its ONE
        batched background fetch."""
        chunk, stacked, zeros_t, zeros_m, idxs, up_ms = staged
        Tp, Mp, P = chunk.key
        members = chunk.members
        smax = chunk.smax
        with no_implicit_transfers():
            chunk.t_dispatch = time.perf_counter()
            with enable_x64(True):
                for i, m in enumerate(members):
                    warm = m.warm
                    out = _solve_member(
                        *(stacked[k] for k in MEMBER_KEYS),
                        idxs[i],
                        warm.asg if warm is not None else zeros_t,
                        warm.lvl if warm is not None else zeros_t,
                        warm.floor if warm is not None else zeros_m,
                        n_prefs=P, smax=smax, alpha=self.alpha,
                        max_rounds=self.max_rounds,
                        warm_start=warm is not None,
                    )
                    chunk.states.append(out)
                    m.chunk = chunk
                    m.slot = i
                    m.timings["upload_ms"] = up_ms / len(members)

        fetch_refs = [
            (cost, conv, asg, rounds)
            for cost, conv, asg, rounds, *_ in chunk.states
        ]

        def _fetch():
            with sanctioned_transfer():
                vals = jax.device_get(fetch_refs)  # noqa: PTA001 -- THE chunk's one sanctioned batched fetch: every member's placements in one download
            return vals, time.perf_counter()

        chunk.stacked = stacked
        chunk.future = _AsyncFetch(_fetch)
        self.dispatches += 1
        if self.metrics is not None:
            self.metrics.record_service_dispatch(
                f"{Tp}x{Mp}x{P}", len(members)
            )

    # ---- finish: join, certify, commit, degrade ------------------------

    def finish(
        self, solver: TenantSolver, pending: PendingSolve
    ) -> ResidentOutcome:
        pending.consumed = True
        chunk: _Chunk = pending.chunk
        timings = pending.timings
        t0 = time.perf_counter()
        if chunk.failed:
            # a bucket-mate already paid the deadline on this chunk's
            # fetch: fail fast rather than re-waiting on a dead future
            solver.fetch_timeouts += 1
            self.pool.invalidate(pending.tenant)
            raise FetchTimeout(
                f"batched fetch for tenant {pending.tenant}'s chunk "
                f"already missed its deadline"
            )
        try:
            vals, t_done = chunk.future.result(
                timeout_s=self.oracle_timeout_s
            )
        except FetchTimeout:
            chunk.failed = True
            solver.fetch_timeouts += 1
            self.pool.invalidate(pending.tenant)
            log.error(
                "batched placement fetch missed its deadline; "
                "abandoning tenant %s's round", pending.tenant,
            )
            raise
        timings["fetch_wait_ms"] = (time.perf_counter() - t0) * 1000
        timings["solve_ms"] = (t_done - chunk.t_dispatch) * 1000
        timings["fetch_ms"] = 0.0
        cost_np, conv, asg_np, rounds = vals[pending.slot]
        state_refs = chunk.states[pending.slot]
        if not bool(conv) and pending.warm_used:
            # a stale warm start stranded the eps=1 settle: retry cold
            # against the chunk's still-resident stacked tables (one
            # extra dispatch + one extra sanctioned fetch, this member
            # only — the rest of the batch is untouched)
            self.pool.invalidate(pending.tenant)
            t0 = time.perf_counter()
            zeros_t = jnp.zeros(pending.Tp, I32)
            zeros_m = jnp.zeros(pending.Mp, I32)
            slot_idx = jnp.int32(pending.slot)
            with no_implicit_transfers():
                with enable_x64(True):
                    state_refs = _solve_member(
                        *(chunk.stacked[k] for k in MEMBER_KEYS),
                        slot_idx,
                        zeros_t, zeros_t, zeros_m,
                        n_prefs=pending.P, smax=chunk.smax,
                        alpha=self.alpha, max_rounds=self.max_rounds,
                        warm_start=False,
                    )
            with sanctioned_transfer():
                cost_np, conv, asg_np, rounds = jax.device_get((  # noqa: PTA001 -- sanctioned second fetch of the cold retry (this member really does pay twice)
                    state_refs[0], state_refs[1], state_refs[2],
                    state_refs[3],
                ))
            timings["solve_ms"] += (time.perf_counter() - t0) * 1000
        if not bool(conv):
            self.pool.invalidate(pending.tenant)
            if not self.oracle_fallback:
                raise RuntimeError(
                    f"service solve for tenant {pending.tenant} did "
                    f"not certify and oracle fallback is disabled"
                )
            return solver._oracle_outcome(
                pending.arrays, pending.meta, pending.topo,
                pending.cost_host, timings, why="uncertified",
            )
        # commit the member's device state as the tenant's warm context
        _c, conv_d, asg_d, rounds_d, lvl_d, floor_d, gap_d, phases_d = \
            state_refs
        self.pool.commit(
            pending.tenant,
            DenseState(
                asg=asg_d, lvl=lvl_d, floor=floor_d, gap=gap_d,
                converged=conv_d, rounds=rounds_d, phases=phases_d,
            ),
            pending.Tp, pending.Mp,
        )
        T = pending.T
        asg = np.asarray(asg_np, np.int32)[:T]  # noqa: PTA001 -- already-fetched host data (the chunk's sanctioned batched fetch)
        asg = np.where(
            (asg >= 0) & (asg < pending.n_machines), asg, -1
        ).astype(np.int32)
        channel = _channels_for(pending.inst, asg)
        solver.last_assignment = asg
        return ResidentOutcome(
            assignment=asg,
            channel=channel,
            cost=int(cost_np) // (T + 1),
            backend="dense_service",
            converged=True,
            rounds=int(rounds),
            phases=0,
            topology=pending.topo,
            timings=timings,
        )
