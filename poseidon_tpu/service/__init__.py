"""Scheduling-as-a-service: batch N heterogeneous tenant clusters into
one device program behind an async front door.

- ``service.SchedulingService`` — the front door: per-tenant isolated
  ``SchedulerBridge`` sessions, ``submit(tenant) -> Future``, and the
  double-buffered ``pump()`` pipeline;
- ``dispatch.BatchDispatcher`` / ``dispatch.TenantSolver`` — the shared
  solver seam: shape-bucket routing, grow-only bucket floors, one
  batched upload + per-member kernel dispatches + one batched fetch
  per dispatch chunk (the ``ops/batch._solve_member`` kernel);
- ``serve.run_serve`` — the cli ``--serve`` driver (N real or fake
  tenant apiservers).
"""

from poseidon_tpu.service.dispatch import BatchDispatcher, TenantSolver
from poseidon_tpu.service.service import (
    MAX_TENANT_LABELS,
    SchedulingService,
    TenantSession,
)

__all__ = [
    "BatchDispatcher",
    "MAX_TENANT_LABELS",
    "SchedulingService",
    "TenantSession",
    "TenantSolver",
]
