"""Phase-span profiling: structured span trees in the trace stream.

With ``--trace_profile`` on, every completed round and every express
batch emits ONE ``SPAN`` trace event whose ``detail`` is a
self-describing span tree — the per-phase decomposition the flat
``ROUND`` stats already carry, laid out as intervals so host/device
overlap is visually inspectable:

    {"name": "round", "lane": "watch+pipelined", "dur_ms": 7.1,
     "children": [
       {"name": "observe",   "off_ms": 0.0, "dur_ms": 0.6},
       {"name": "build",     "off_ms": 0.6, "dur_ms": 0.9},
       ...
       {"name": "solve-wait", "off_ms": 5.0, "dur_ms": 1.8,
        "children": [{"name": "fetch-wait", ...}]},
       {"name": "device-solve", "track": "device", ...}]}

Clock contract (trace.py module docstring has the full statement): the
SPAN event's ``timestamp_us`` is WALL clock like every trace event —
correlate across hosts with it, never difference it. All ``dur_ms`` /
``off_ms`` values are measured on the monotonic clock family
(``time.monotonic`` / ``perf_counter``) by the producers, so they are
NTP-step-safe. Offsets are a sequential reconstruction from the phase
durations (phases on one track run back-to-back; the device track
overlaps), not independent stamps.

``chrome_trace`` converts a trace's SPAN events into Chrome-trace /
Perfetto JSON ("trace event format", ``ph: "X"`` complete events) —
load the output in ``chrome://tracing`` or ui.perfetto.dev. Rounds are
anchored on their wall timestamps, so inter-round gaps are real; the
intra-round layout is the reconstruction above.

The builders run inside the bridge's finish/actuate window, so they
are registered PTA001/PTA002 hot scopes: pure dict assembly from host
floats the caller already holds, never a device sync, never a
cluster-sized walk.
"""

from __future__ import annotations

import json


def round_span_tree(
    stats, *, join_ms: float, actuate_ms: float
) -> dict:
    """One round's span tree from its ``SchedulerStats`` plus the two
    finish-side durations only the caller's monotonic stamps know
    (``join_ms``: the solver fetch-join; ``actuate_ms``: delta
    application + trace emission)."""
    children = []
    off = 0.0
    for name, dur in (
        ("observe", stats.observe_ms),
        ("build", stats.build_ms),
        ("dispatch", stats.dispatch_ms),
        ("overlap", stats.overlap_ms),
        ("solve-wait", join_ms),
        ("actuate", actuate_ms),
    ):
        node = {
            "name": name,
            "off_ms": round(off, 3),
            "dur_ms": round(dur, 3),
        }
        if name == "solve-wait" and stats.fetch_wait_ms:
            node["children"] = [{
                "name": "fetch-wait",
                "off_ms": round(off, 3),
                "dur_ms": round(stats.fetch_wait_ms, 3),
            }]
        children.append(node)
        off += dur
    # the device program runs concurrently with the overlap window:
    # anchor it at dispatch end, on its own track
    dev_off = stats.observe_ms + stats.build_ms + stats.dispatch_ms
    children.append({
        "name": "device-solve",
        "track": "device",
        "off_ms": round(dev_off, 3),
        "dur_ms": round(stats.solve_ms, 3),
    })
    return {
        "name": "round",
        "lane": stats.lane or "round",
        "build_mode": stats.build_mode,
        "backend": stats.backend,
        "dur_ms": round(off, 3),
        "children": children,
    }


def express_span_tree(latency_ms: float, timings: dict) -> dict:
    """One express batch's span tree from its already-measured phase
    timings (prep / upload / solve, ops/resident.py vocabulary).

    The root spans the whole event-to-bind window; the work phases
    tile its END (the batch binds when solve finishes), so any
    event-receipt queue wait renders BEFORE the work — where it
    actually happened — not as a trailing gap."""
    work = sum(
        float(timings.get(n, 0.0))
        for n in ("prep_ms", "upload_ms", "solve_ms")
    )
    children = []
    off = max(latency_ms - work, 0.0)
    if off:
        children.append({
            "name": "e2b-wait",
            "off_ms": 0.0,
            "dur_ms": round(off, 3),
        })
    for name in ("prep_ms", "upload_ms", "solve_ms"):
        dur = float(timings.get(name, 0.0))
        children.append({
            "name": name[:-3],
            "off_ms": round(off, 3),
            "dur_ms": round(dur, 3),
        })
        off += dur
    return {
        "name": "express-batch",
        "lane": "express",
        "dur_ms": round(latency_ms, 3),
        "children": children,
    }


def stream_span_tree(
    latency_ms: float, timings: dict, *, windows: int = 0
) -> dict:
    """One stream flush's span tree (K windows, one fetch): the
    per-window prep/upload phases on the host track (they overlapped
    the PREVIOUS batch's scan — that's the double buffer), the stack +
    scanned solve on the device track, and the single fetch-join last.
    Perfetto shows the amortization directly: one ``fetch`` interval
    spanning ``windows`` windows' worth of decisions."""
    prep = float(timings.get("prep_ms", 0.0))
    upload = float(timings.get("upload_ms", 0.0))
    stack = float(timings.get("stack_ms", 0.0))
    solve = float(timings.get("solve_ms", 0.0))
    work = prep + upload + stack + solve
    children = []
    off = max(latency_ms - work, 0.0)
    if off:
        children.append({
            "name": "accumulate-wait",
            "off_ms": 0.0,
            "dur_ms": round(off, 3),
        })
    for name, dur in (("prep", prep), ("upload", upload)):
        children.append({
            "name": name,
            "off_ms": round(off, 3),
            "dur_ms": round(dur, 3),
        })
        off += dur
    children.append({
        "name": "stack",
        "track": "device",
        "off_ms": round(off, 3),
        "dur_ms": round(stack, 3),
    })
    off += stack
    children.append({
        "name": "scan+fetch",
        "track": "device",
        "off_ms": round(off, 3),
        "dur_ms": round(solve, 3),
    })
    off += solve
    return {
        "name": "stream-flush",
        "lane": "stream",
        "windows": windows,
        "dur_ms": round(max(latency_ms, off), 3),
        "children": children,
    }


def emit_span(trace, tree: dict, round_num: int) -> None:
    """One SPAN trace event per tree (the PTA005-declared type)."""
    trace.emit("SPAN", round_num=round_num, detail=tree)


# ---------------------------------------------------------------------------
# Chrome-trace / Perfetto export
# ---------------------------------------------------------------------------


def _emit_node(
    out: list[dict], node: dict, t0_us: float, tid: str, pid: int
) -> None:
    ts = t0_us + float(node.get("off_ms", 0.0)) * 1000.0
    dur = float(node.get("dur_ms", 0.0)) * 1000.0
    out.append({
        "name": node.get("name", "span"),
        "ph": "X",
        "ts": ts,
        "dur": dur,
        "pid": pid,
        "tid": node.get("track", tid),
        "cat": "poseidon",
    })
    for child in node.get("children", ()):
        _emit_node(out, child, t0_us, tid, pid)


def chrome_trace(events) -> dict:
    """Convert trace events (``trace.read_trace`` output or any
    iterable of ``TraceEvent``) into a Chrome-trace JSON document.

    Only SPAN events contribute intervals; each tree's root anchors at
    its event's wall ``timestamp_us`` MINUS its duration (spans are
    emitted at finish time), children at root + their reconstructed
    offsets. Lanes become thread names so round / express / device
    tracks stack separately.
    """
    out: list[dict] = []
    tids: set[str] = set()
    for ev in events:
        if ev.event != "SPAN" or not isinstance(ev.detail, dict):
            continue
        tree = ev.detail
        tid = tree.get("lane", "round")
        t0 = float(ev.timestamp_us) - float(
            tree.get("dur_ms", 0.0)
        ) * 1000.0
        root = dict(tree)
        root.setdefault("off_ms", 0.0)
        _emit_node(out, root, t0, tid, pid=1)
        tids.add(tid)
        for node in tree.get("children", ()):
            if "track" in node:
                tids.add(node["track"])
    meta = [
        {"name": "thread_name", "ph": "M", "pid": 1, "tid": t,
         "args": {"name": f"poseidon:{t}"}}
        for t in sorted(tids)
    ]
    return {"traceEvents": meta + out, "displayTimeUnit": "ms"}


def write_chrome_trace(events, path: str) -> str:
    doc = chrome_trace(events)
    with open(path, "w") as fh:
        json.dump(doc, fh)
    return path
