"""The metrics/health HTTP endpoint: /metrics, /healthz, /readyz, /slo.

A ``ThreadingHTTPServer`` on a daemon thread (one short-lived handler
thread per scrape; the registry's shared lock makes renders safe
against in-flight recording — analysis/contracts.py PTA004 declares
the discipline). Endpoints:

- ``GET /metrics``: Prometheus text exposition of the registry
  (version 0.0.4);
- ``GET /healthz``: process liveness — 200 as long as the daemon can
  answer at all (the loop owns no state a liveness probe should gate
  on; a wedged round shows up in ``/readyz`` and the metrics, not
  here). The body is JSON: ``{"status": "ok", "build": {...}}`` with
  the same build-identity labelset the ``poseidon_build_info`` gauge
  publishes (package/jax versions, backend, mesh width) — so "what
  exactly is this pod running" is one curl, not a registry query;
- ``GET /readyz``: readiness — 200 only after BOTH (a) the seed
  LIST/snapshot has been applied to the bridge and (b) the first
  scheduling round over that real cluster state has completed (every
  completed solve here is exact — certified dense or oracle — and a
  proven-EMPTY round counts too: an idle cluster with nothing pending
  is the steady state of a fully operational scheduler, and gating
  readiness on a solve would wedge a readiness-gated rollout there
  forever). Until then 503 with the missing conditions in the body, so
  an operator can tell "waiting for the apiserver" from "waiting for
  the first solve". A 200 body carries the ``restored_warm`` condition
  detail when the daemon rehydrated from a checkpoint at startup
  (ha/checkpoint.py), and ``degraded=outage,overload`` while the
  failure-domain ladder has declared a degraded window (apiserver
  unreachable / round-deadline watchdog tripping) — informational,
  never a gate: a degraded scheduler is still scheduling from
  last-known state. Degraded-to-oracle
  and resync-storm states are NOT
  readiness failures — they surface as labeled gauges
  (``poseidon_degraded{why=...}``, ``poseidon_watch_resync_storm``)
  since a degraded scheduler is still scheduling.

- ``GET /slo``: the SLO engine's evaluation state (obs/slo.py) as
  JSON — per objective: spec, healthy, short/long burn rates, breach
  count, current value. 404 when no ``--slo`` objectives were
  declared.

``HealthState`` is the driver-fed latch behind ``/readyz``; the cli
marks it from the observe/round loop (cli.py), tests drive it
directly.
"""

from __future__ import annotations

import http.server
import json
import logging
import threading

from poseidon_tpu.obs.metrics import MetricsRegistry

log = logging.getLogger(__name__)


class HealthState:
    """Readiness latch: seeded observe + first round over real state.

    Written by the driver loop, read by the handler threads; a lock
    guards the two booleans (they flip once, but torn multi-field
    reads would make ``reasons()`` lie during the flip).

    ``ready_gauge`` (the registry's ``poseidon_ready`` gauge, or None)
    is updated INSIDE the latch's lock: a scraper that has seen
    ``/readyz`` return 200 can never read the gauge at 0, because the
    readyz handler's own ``ready`` read serializes behind the flip
    that already set the gauge.
    """

    def __init__(self, ready_gauge=None):
        self._lock = threading.Lock()
        self._seeded = False
        self._round_done = False
        # informational condition detail, not a readiness gate: True
        # when the daemon rehydrated warm state from a checkpoint at
        # startup (ha/checkpoint.py) — "did this pod cold-start or
        # warm-restore" is the first rollout question after a bounce
        self._restored_warm = False
        # declared degraded modes (the failure-domain ladder:
        # "outage" while the apiserver is unreachable, "overload"
        # while the round-deadline watchdog is tripping). NEVER a
        # readiness gate — a degraded scheduler is still scheduling —
        # but surfaced in the 200 body so a rollout can tell a
        # healthy pod from one riding out an incident.
        self._degraded: set[str] = set()
        self._gauge = ready_gauge
        if ready_gauge is not None:
            ready_gauge.set(0)

    def mark_seeded(self) -> None:
        """The seed LIST (or first successful poll snapshot) has been
        applied to the bridge."""
        with self._lock:
            self._seeded = True
            if self._gauge is not None:
                self._gauge.set(
                    1 if self._seeded and self._round_done else 0
                )

    def mark_round(self, backend: str) -> None:
        """A scheduling round completed; ``backend`` is its
        ``SchedulerStats.backend``. Empty-backend rounds count too:
        the loop only rounds after a successful observe, so an empty
        round is PROVEN-empty real state (an idle cluster's steady
        state), not a startup transient — the separate seeded latch
        already guards against reporting ready before real state
        arrived."""
        del backend  # kept for the call sites' self-documentation
        with self._lock:
            self._round_done = True
            if self._gauge is not None:
                self._gauge.set(
                    1 if self._seeded and self._round_done else 0
                )

    def mark_restored_warm(self) -> None:
        """The startup path rehydrated warm state from a checkpoint
        (surfaced as a /readyz condition detail, never a gate)."""
        with self._lock:
            self._restored_warm = True

    @property
    def restored_warm(self) -> bool:
        with self._lock:
            return self._restored_warm

    def set_degraded(self, mode: str, active: bool) -> None:
        """Declare or clear a degraded mode ("outage", "overload").
        Informational: /readyz stays 200, the body carries
        ``degraded=<modes>``."""
        with self._lock:
            if active:
                self._degraded.add(mode)
            else:
                self._degraded.discard(mode)

    def degraded_modes(self) -> list[str]:  # pta: background-thread
        with self._lock:
            return sorted(self._degraded)

    @property
    def ready(self) -> bool:
        with self._lock:
            return self._seeded and self._round_done

    def reasons(self) -> list[str]:  # pta: background-thread
        """What readiness is still waiting on (handler threads)."""
        with self._lock:
            out = []
            if not self._seeded:
                out.append("waiting for the seed LIST/snapshot")
            if not self._round_done:
                out.append("waiting for the first scheduling round")
            return out


class ObsServer:
    """The background endpoint server; start() binds and returns the
    port (pass ``port=0`` to let the OS pick — tests do)."""

    def __init__(
        self,
        registry: MetricsRegistry,
        health: HealthState,
        *,
        port: int = 0,
        host: str = "0.0.0.0",
        build: dict | None = None,
        slo=None,
    ):
        self.registry = registry
        self.health = health
        self.host = host
        self.port = port
        # the /healthz build-identity echo (obs.metrics.build_info());
        # immutable after start, so handler threads read it lock-free
        self.build = dict(build or {})
        # the SLO engine behind /slo (obs/slo.py; None = 404):
        # status() serves handler threads under the engine's own lock
        self.slo = slo
        self._httpd: http.server.ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None

    def start(self) -> int:
        registry = self.registry
        health = self.health
        srv = self  # handlers read srv.slo PER REQUEST (below)
        healthz_body = json.dumps(
            {"status": "ok", "build": self.build}
        ).encode() + b"\n"

        class _Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # pta: background-thread
                # probes and agents append query params freely
                # (?verbose=1, cache busters): route on the path alone
                route = self.path.split("?", 1)[0]
                if route == "/metrics":
                    body = registry.render().encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type",
                        "text/plain; version=0.0.4; charset=utf-8",
                    )
                elif route == "/healthz":
                    body = healthz_body
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "application/json")
                elif route == "/slo":
                    # read per request, not captured at start():
                    # drivers assign server.slo by attribute and must
                    # not need to order that before start() (reference
                    # assignment is atomic; a stale read costs one
                    # 404 scrape, never a crash)
                    slo_engine = srv.slo
                    if slo_engine is None:
                        body = (
                            b"no SLO engine configured (--slo)\n"
                        )
                        self.send_response(404)
                        self.send_header("Content-Type",
                                         "text/plain")
                    else:
                        body = json.dumps(
                            slo_engine.status(), indent=1
                        ).encode() + b"\n"
                        self.send_response(200)
                        self.send_header("Content-Type",
                                         "application/json")
                elif route == "/readyz":
                    if health.ready:
                        # condition details: did this process warm-
                        # restore, and is it riding out a declared
                        # degraded window (outage/overload)? Both
                        # informational, never gates.
                        parts = ["ready"]
                        if health.restored_warm:
                            parts.append("restored_warm=true")
                        modes = health.degraded_modes()
                        if modes:
                            parts.append(
                                "degraded=" + ",".join(modes)
                            )
                        body = (" ".join(parts) + "\n").encode()
                        self.send_response(200)
                    else:
                        body = (
                            "; ".join(health.reasons()) + "\n"
                        ).encode()
                        self.send_response(503)
                    self.send_header("Content-Type", "text/plain")
                else:
                    body = b"not found\n"
                    self.send_response(404)
                    self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # pta: background-thread
                pass  # scrapes are not log lines

        self._httpd = http.server.ThreadingHTTPServer(
            (self.host, self.port), _Handler
        )
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="obs-server",
            daemon=True,
        )
        self._thread.start()
        log.info("obs server listening on %s:%d", self.host, self.port)
        return self.port

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def __enter__(self) -> "ObsServer":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()
