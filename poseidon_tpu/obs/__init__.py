"""Observability: metrics, health endpoints, span profiling, reports.

The reference collects ``SchedulerStats`` per round and drops them
(scheduler_bridge.cc:170-172); seven PRs in, this reproduction has five
latency-critical lanes and a dozen loud degradation paths, and tailing
a JSONL file is not an operational surface. This package is that
surface, in three pieces:

- ``metrics``: a dependency-free metrics registry (counters, gauges,
  fixed-bucket histograms) rendered in Prometheus text exposition
  format, plus ``SchedulerMetrics`` — the one place every instrument
  the scheduler feeds is declared. Recording happens exclusively from
  values the hot path already holds on the host (stats fields,
  perf-counter durations, outcome counters) at finish/actuate time:
  no new device syncs, PTA001-clean by construction and registered
  with the contract linter from day one.
- ``server``: a daemon-thread HTTP server exposing ``/metrics``,
  ``/healthz`` (process liveness) and ``/readyz`` (ready = the seed
  LIST applied AND the first certified round completed), with
  ``HealthState`` as the driver-fed readiness latch.
- ``spans`` + ``report``: phase-span profiling (every round and
  express batch emits a structured span tree into the trace stream as
  a ``SPAN`` event; an exporter turns them into Chrome-trace/Perfetto
  JSON), and the trace analysis backend behind
  ``python -m poseidon_tpu.trace report`` — round-latency percentiles
  by lane/build-mode, express event-to-bind percentiles,
  degrade/resync/timeout tallies with reasons, placement-churn
  summaries.
- ``explain`` + ``flightrec`` + ``replay``: the decision-evidence
  layer (README "Explain & replay"). ``explain`` decomposes any
  decision's cost into named model terms that sum bit-exactly to the
  solver's arc cost and diagnoses unscheduled pods with a validated
  minimal relaxation; ``flightrec`` keeps a bounded ring of the last K
  rounds' full solve inputs and dumps it on anomalies (DEGRADE,
  EXPRESS_DEGRADE, FETCH_TIMEOUT, resync storms) or on demand;
  ``python -m poseidon_tpu.obs.replay`` re-runs a dump through the
  real solve path offline and asserts bit-identity with the recorded
  assignment/cost, reporting divergence instead of crashing.
- ``lifecycle`` + ``audit`` + ``slo``: the quality observatory
  (README "Quality & SLOs"). ``lifecycle`` stamps bounded per-pod
  timelines across the tick/express/service/restart lanes and closes
  them into true event-to-confirmed latency histograms plus a
  standing-unscheduled wait-age distribution; ``audit`` re-solves a
  sampled cluster snapshot on a background thread (CPU-pinned
  pricing + the subprocess oracle — never the accelerator) and
  publishes placement regret vs the certified optimum, a
  fragmentation index per SKU class, and drift counts; ``slo``
  evaluates declarative objectives (``e2b_p99_ms < 10 by
  lane=express``, ``regret == 0``, ``ready``) with multi-window
  burn rates, latched ``SLO_BREACH`` trace events, and the ``/slo``
  endpoint.
"""

from poseidon_tpu.obs.audit import ShadowAuditor
from poseidon_tpu.obs.flightrec import FlightRecorder
from poseidon_tpu.obs.lifecycle import LifecycleTracker
from poseidon_tpu.obs.metrics import (
    MetricsRegistry,
    SchedulerMetrics,
    build_info,
)
from poseidon_tpu.obs.server import HealthState, ObsServer
from poseidon_tpu.obs.slo import SloEngine

__all__ = [
    "FlightRecorder",
    "HealthState",
    "LifecycleTracker",
    "MetricsRegistry",
    "ObsServer",
    "SchedulerMetrics",
    "ShadowAuditor",
    "SloEngine",
    "build_info",
]
