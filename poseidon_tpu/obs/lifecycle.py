"""Per-pod lifecycle tracing: event-to-confirmed latency, by lane.

The latency story before this module was partial on purpose: PR 8's
``e2b_ms`` covers the express lane's event-to-bind-DECISION only, and
the round path's ``total_ms`` times the solver, not the pod. Nothing
answered the operator's actual question — "from the moment the
apiserver told us about this pod, how long until its binding was
confirmed, end to end?" — across the tick lane (wait for the round),
the express lane (between-tick fast path), the service lane
(multi-tenant sessions), and the restart-replay lane (a bind whose
POST the previous process journaled and died before confirming).

``LifecycleTracker`` keeps a BOUNDED per-uid timeline stamped at every
stage the pod passes through:

- ``event``    — the pod became schedulable work: watch-event dequeue
  (the express path's per-event receipt stamp when the driver has
  one) or the observe that first saw it Pending;
- ``decided``  — a round/express solve chose its machine;
- ``journal``  — the actuation intent hit the write-ahead journal
  (``--checkpoint_dir``);
- ``posted``   — the bind POST returned success;
- ``confirmed``— the driver applied the confirm to bridge state. This
  CLOSES the timeline and records one event-to-confirmed sample into
  ``poseidon_pod_e2c_ms{lane=...}``.

A pod whose POST fails keeps its timeline open (aging is part of its
latency, not a reset); a pod that retires or is deleted before
confirming drops its timeline. The per-round wait-age distribution of
STANDING unscheduled pods (how long has the queue's tail been waiting,
in rounds) lands in ``poseidon_unsched_wait_rounds{q=p50|p95|max}`` —
the starvation surface ``wait_rounds`` feeds the cost models with but
nothing ever reported.

**Clock contract** (trace.py has the full statement): every in-process
duration is a ``time.monotonic`` difference — never wall clock. The
ONE exception is the restart-replay lane: a monotonic clock does not
survive the process, so the journal carries the event's WALL stamp
(``t_event_us``) and ``close_replayed`` computes the cross-process
e2c as a wall difference. Those samples are recorded under
``lane="restart"`` exactly so a consumer can tell the NTP-step-safe
samples from the cross-boot ones.

**Bounds.** At most ``max_open`` open timelines (default 65536); when
full, new timelines are dropped and counted
(``poseidon_lifecycle_dropped_total``) — a scheduler 65k pods behind
on confirms has bigger problems than a missing histogram sample, and
an unbounded dict keyed by uid is how a daemon leaks. Lanes fold to
the bounded ``LANES`` vocabulary before they reach a metric label.

Hot-path discipline: ``stamp_*`` / ``close_*`` run inside the bridge's
round window and the express fast path — dict ops and perf-counter
reads only, registered PTA001/PTA002 scopes (analysis/contracts.py).
``note_unscheduled`` takes the wait-age list the caller's existing
unscheduled walk already produced (no second walk).
"""

from __future__ import annotations

import collections
import dataclasses
import logging
import time

import numpy as np

log = logging.getLogger(__name__)

# the bounded lane vocabulary (metric label values); anything else
# folds to "other"
LANES = ("tick", "express", "stream", "service", "restart", "other")

# timeline stage names, in lifecycle order
STAGES = ("event", "decided", "journal", "posted", "confirmed")


def bounded_lane(lane: str) -> str:
    """Fold a free-text lane onto the bounded vocabulary."""
    return lane if lane in LANES else "other"


@dataclasses.dataclass
class PodTimeline:
    """One pod's in-flight lifecycle: monotonic stamps per stage plus
    the wall twin of the event stamp (the journal's cross-restart
    seed). ``lane`` is stamped at decision time — the same pod riding
    the tick lane one day and the express lane the next reports into
    the right bucket each time."""

    t_event: float            # perf_counter at first sight
    t_event_wall_us: int      # wall µs twin (journaled for restarts)
    lane: str = ""
    stages: dict = dataclasses.field(default_factory=dict)


class LifecycleTracker:
    """Bounded per-uid timelines + the histograms they close into.

    One instance per bridge, driver-thread only (the bridge's own
    single-thread contract covers it; nothing here takes a lock).
    ``metrics`` is an ``obs.SchedulerMetrics`` (or None: stamps still
    tracked — tests read ``last_closed`` — but nothing is published).
    """

    def __init__(self, metrics=None, *, max_open: int = 65536):
        self.metrics = metrics
        self.max_open = max_open
        self.open: dict[str, PodTimeline] = {}
        self.dropped = 0
        self.closed_total = 0
        # (uid, lane, e2c_ms) of the most recently closed timeline —
        # the lifecycle-differential tests' read surface — plus its
        # stage stamps (decided/journal/posted offsets, debugging)
        self.last_closed: tuple[str, str, float] | None = None
        self.last_closed_stages: dict = {}
        # recently-closed stamps, bounded: the pipelined driver
        # confirms OPTIMISTICALLY (before the POST), so a failed POST
        # must be able to REOPEN the timeline from its original event
        # stamp — otherwise the pod's real (longer) wait is never
        # measured and the histogram reads optimistic exactly when
        # the apiserver is flaky
        self._closed_stash: collections.OrderedDict[
            str, tuple[float, int]
        ] = collections.OrderedDict()
        self._stash_max = 4096

    # ---- stamps (hot scopes: dict ops + clock reads only) --------------

    def stamp_event(
        self, uid: str, t_event: float | None = None
    ) -> None:
        """First sight of schedulable work for ``uid``. Idempotent —
        re-observations keep the ORIGINAL stamp (latency is measured
        from first sight, not last poll). ``t_event`` is the driver's
        own receipt stamp (watch dequeue) when it has one."""
        if uid in self.open:
            return
        if len(self.open) >= self.max_open:
            if not self.dropped:
                log.warning(
                    "lifecycle tracker full (%d open timelines); "
                    "dropping new ones (counted)", self.max_open,
                )
            self.dropped += 1
            if self.metrics is not None:
                self.metrics.record_lifecycle_dropped()
            return
        now = time.perf_counter()
        self.open[uid] = PodTimeline(
            t_event=t_event if t_event is not None else now,
            t_event_wall_us=int(time.time() * 1e6),
        )

    def backdate_event(self, uid: str, t_event: float) -> None:
        """Move an open timeline's event stamp EARLIER (never later):
        the express driver dequeues events with their own receipt
        stamps, which precede the observe that minted the timeline.
        The wall twin (the journal's cross-restart seed) backdates by
        the same delta, so a restart-replayed bind's e2c also starts
        at the receipt, not the observe."""
        tl = self.open.get(uid)
        if tl is not None and t_event < tl.t_event:
            tl.t_event_wall_us -= int(
                (tl.t_event - t_event) * 1e6
            )
            tl.t_event = t_event

    def stamp(self, uid: str, stage: str) -> None:
        """Mark one mid-life stage (``decided``/``journal``/``posted``)
        at now; unknown uids are ignored (a journaled op for a pod the
        tracker never saw — e.g. a restore-path migration — is not an
        error)."""
        tl = self.open.get(uid)
        if tl is not None:
            tl.stages[stage] = time.perf_counter()

    def stamp_decided(self, uid: str, lane: str) -> None:
        """The solve chose this pod's machine; ``lane`` is the bounded
        lifecycle lane the eventual e2c sample reports under."""
        tl = self.open.get(uid)
        if tl is not None:
            tl.lane = bounded_lane(lane)
            tl.stages["decided"] = time.perf_counter()

    def event_wall_us(self, uid: str) -> int:
        """The journaled cross-restart seed: wall µs of the event
        stamp (0 = unknown uid)."""
        tl = self.open.get(uid)
        return tl.t_event_wall_us if tl is not None else 0

    def close_confirmed(self, uid: str) -> float | None:
        """The binding confirm landed: close the timeline and record
        its event-to-confirmed sample (ms, monotonic). Returns the
        sample, or None for an untracked uid.

        The pipelined driver confirms OPTIMISTICALLY (POST follows in
        the overlap window), so this sample measures event-to-commit;
        if the POST then fails, ``reopen`` restores the timeline from
        its original event stamp and the eventual successful bind
        records the pod's full wait as a second sample."""
        tl = self.open.pop(uid, None)
        if tl is None:
            return None
        e2c = (time.perf_counter() - tl.t_event) * 1000
        lane = tl.lane or "other"
        self.closed_total += 1
        self.last_closed = (uid, lane, e2c)
        self.last_closed_stages = dict(tl.stages)
        self._closed_stash[uid] = (tl.t_event, tl.t_event_wall_us)
        while len(self._closed_stash) > self._stash_max:
            self._closed_stash.popitem(last=False)
        if self.metrics is not None:
            self.metrics.record_pod_e2c(e2c, lane)
        return e2c

    def reopen(self, uid: str) -> None:
        """A bind that was optimistically confirmed failed its POST
        (the pod re-queues): restore the timeline from its ORIGINAL
        event stamp so the pod's real end-to-end wait is still
        measured when it finally binds. No-op for unknown uids or
        already-open timelines."""
        if uid in self.open:
            return
        stash = self._closed_stash.pop(uid, None)
        if stash is None:
            return
        if len(self.open) >= self.max_open:
            self.dropped += 1
            if self.metrics is not None:
                self.metrics.record_lifecycle_dropped()
            return
        self.open[uid] = PodTimeline(
            t_event=stash[0], t_event_wall_us=stash[1]
        )

    def drop(self, uid: str) -> None:
        """The pod left the cluster unconfirmed (retired, deleted,
        evicted-for-good): the timeline is moot."""
        self.open.pop(uid, None)
        self._closed_stash.pop(uid, None)

    # ---- the restart-replay lane ---------------------------------------

    def close_replayed(self, uid: str, t_event_us: int) -> float | None:
        """A journal replay settled this pod's bind after a restart:
        record the CROSS-PROCESS e2c from the journaled wall stamp
        (the pre-crash timeline's event receipt) instead of minting a
        fresh timeline that would erase the pre-crash wait. Wall-
        differenced by necessity (the clock-contract exception this
        lane documents); samples land under ``lane="restart"``.
        Returns the sample, or None when no stamp was journaled."""
        if not t_event_us:
            return None
        e2c = max((time.time() * 1e6 - t_event_us) / 1000, 0.0)
        # a fresh-process tracker has no open timeline for the uid —
        # and must not mint one: the bind is settled
        self.open.pop(uid, None)
        self.closed_total += 1
        self.last_closed = (uid, "restart", e2c)
        if self.metrics is not None:
            self.metrics.record_pod_e2c(e2c, "restart")
        return e2c

    # ---- the standing-unscheduled surface ------------------------------

    def note_unscheduled(self, wait_rounds: list[int]) -> None:
        """Per-round wait-age distribution of pods the round left
        unscheduled. The caller's existing unscheduled walk collected
        the ages — this is one numpy percentile over that list, not a
        second walk."""
        if self.metrics is None:
            return
        if not wait_rounds:
            self.metrics.record_unsched_wait(0.0, 0.0, 0.0)
            return
        ages = np.asarray(wait_rounds, np.int64)  # noqa: PTA001 -- host ints from the caller's walk, never a device array
        self.metrics.record_unsched_wait(
            float(np.percentile(ages, 50)),
            float(np.percentile(ages, 95)),
            float(ages.max()),
        )
