"""Trace analysis: the operator's one-pager from a trace JSONL file.

Backend of ``python -m poseidon_tpu.trace report <file>``. One pass
over ``trace.read_trace`` builds:

- **round latency** p50/p95/p99 of ``total_ms`` (the host critical
  path) grouped by (lane, build_mode) plus a per-backend-family
  breakdown — the "is the watch/pipelined/express/sharded lane doing
  what PERF.md says" table;
- **express**: event-to-bind percentiles from the per-placement
  ``e2b_ms`` carried on EXPRESS_PLACE events (real per-event samples,
  not window aggregates), plus batch/place/corrected/degrade tallies;
- **degradations**: DEGRADE / EXPRESS_DEGRADE / WATCH_RESYNC /
  WATCH_RECONNECT / FETCH_TIMEOUT tallies with their reasons, so an
  operator sees WHY the dense lane fell back, not just that it did;
- **placement churn**: SCHEDULE / MIGRATE / PREEMPT / EVICT /
  EXPRESS_PLACE totals and per-round rates, deferred-delta pressure,
  and the bind-failure count;
- **spans** (when ``--trace_profile`` was on): per-phase duration p50s
  across rounds.

Everything is computed from the JSONL alone — the report runs against
a live daemon's trace file or a post-mortem copy equally.
"""

from __future__ import annotations

import collections

import numpy as np

from poseidon_tpu.trace import read_trace

# events that count as placement churn, in report order
_CHURN_EVENTS = (
    "SCHEDULE", "MIGRATE", "PREEMPT", "EVICT", "EXPRESS_PLACE",
    "EXPRESS_CORRECTED", "FINISH", "SUBMIT",
)


def _pct(values, q) -> float:
    return round(float(np.percentile(np.asarray(values, float), q)), 3)


def _pcts(values) -> dict:
    if not values:
        return {"n": 0}
    return {
        "n": len(values),
        "p50": _pct(values, 50),
        "p95": _pct(values, 95),
        "p99": _pct(values, 99),
    }


def _why_of(detail) -> str:
    if not isinstance(detail, dict):
        return "unknown"
    return str(
        detail.get("why") or detail.get("reason")
        or detail.get("error") or "unknown"
    )


def analyze_trace(path: str, *, tenant: str = "") -> dict:
    """One pass over the trace -> the report's data model (a plain
    JSON-able dict; ``render_report`` formats it for humans).

    ``tenant`` filters to one service-lane session's events (every
    tenant's generator stamps its id; "" = no filter, the whole
    stream)."""
    lane_lat: dict[tuple[str, str], list[float]] = (
        collections.defaultdict(list)
    )
    backend_lat: dict[str, list[float]] = collections.defaultdict(list)
    e2b: list[float] = []
    tallies: dict[str, collections.Counter] = {
        k: collections.Counter()
        for k in ("DEGRADE", "EXPRESS_DEGRADE", "WATCH_RESYNC",
                  "WATCH_RECONNECT", "FETCH_TIMEOUT",
                  "FLIGHTREC_DUMP")
    }
    churn = collections.Counter()
    slo_breaches = collections.Counter()
    span_phases: dict[str, list[float]] = collections.defaultdict(list)
    rounds = 0
    nonempty_rounds = 0
    express = collections.Counter()
    deferred = 0
    bind_failures = 0
    first_round = last_round = None
    for ev in read_trace(path):
        if tenant and ev.tenant != tenant:
            continue
        if ev.event == "ROUND":
            rounds += 1
            if first_round is None:
                first_round = ev.round_num
            last_round = ev.round_num
            d = ev.detail or {}
            # window counters accumulate on EVERY round record: the
            # bridge deliberately flushes them into empty rounds too
            # (an express window that bound everything ends in one)
            express["batches"] += d.get("express_batches", 0)
            express["places"] += d.get("express_places", 0)
            express["corrected"] += d.get("express_corrected", 0)
            express["degrades"] += d.get("express_degrades", 0)
            deferred += d.get("deltas_deferred", 0)
            bind_failures += d.get("bind_failures", 0)
            backend = d.get("backend", "")
            if not backend:
                continue  # empty round: no solve to time
            nonempty_rounds += 1
            lane = d.get("lane") or "round"
            mode = d.get("build_mode") or "none"
            total = float(d.get("total_ms", 0.0))
            lane_lat[(lane, mode)].append(total)
            family = (
                "oracle" if backend.startswith("oracle:") else "dense"
            )
            backend_lat[family].append(total)
        elif ev.event in tallies:
            tallies[ev.event][_why_of(ev.detail)] += 1
        elif ev.event == "SLO_BREACH":
            d = ev.detail if isinstance(ev.detail, dict) else {}
            slo_breaches[str(d.get("slo", "unknown"))] += 1
        elif ev.event == "EXPRESS_PLACE":
            churn[ev.event] += 1
            if isinstance(ev.detail, dict) and "e2b_ms" in ev.detail:
                e2b.append(float(ev.detail["e2b_ms"]))
        elif ev.event == "SPAN":
            d = ev.detail or {}
            lane = d.get("lane", "round")
            # recurse: subspans nest (fetch-wait under solve-wait)
            stack = list(d.get("children", ()))
            while stack:
                child = stack.pop()
                span_phases[
                    f"{lane}:{child.get('name')}"
                ].append(float(child.get("dur_ms", 0.0)))
                stack.extend(child.get("children", ()))
        if ev.event in _CHURN_EVENTS and ev.event != "EXPRESS_PLACE":
            churn[ev.event] += 1
    per_round = max(nonempty_rounds, 1)
    return {
        "tenant": tenant,
        "rounds": rounds,
        "nonempty_rounds": nonempty_rounds,
        "round_range": [first_round, last_round],
        "round_latency_ms": {
            f"{lane}/{mode}": _pcts(v)
            for (lane, mode), v in sorted(lane_lat.items())
        },
        "backend_latency_ms": {
            k: _pcts(v) for k, v in sorted(backend_lat.items())
        },
        "express": {
            "e2b_ms": _pcts(e2b),
            **{k: int(v) for k, v in sorted(express.items())},
        },
        "degradations": {
            k: dict(c.most_common()) for k, c in tallies.items()
        },
        # SLO breach-latch trips by objective spec (obs/slo.py emits
        # exactly one SLO_BREACH per breach window)
        "slo_breaches": dict(slo_breaches.most_common()),
        "churn": {
            "totals": {k: int(churn.get(k, 0)) for k in _CHURN_EVENTS},
            "per_round": {
                k: round(churn.get(k, 0) / per_round, 2)
                for k in _CHURN_EVENTS
            },
            "deltas_deferred": deferred,
            "bind_failures": bind_failures,
        },
        "span_phase_p50_ms": {
            k: _pct(v, 50) for k, v in sorted(span_phases.items())
        },
    }


def render_report(data: dict) -> str:
    """The human one-pager."""
    out: list[str] = []
    add = out.append
    lo, hi = data["round_range"]
    add("== poseidon-tpu trace report ==")
    if data.get("tenant"):
        add(f"tenant: {data['tenant']}")
    add(
        f"rounds: {data['rounds']} "
        f"({data['nonempty_rounds']} with a solve), "
        f"round_num {lo}..{hi}"
    )
    add("")
    add("-- round latency (total_ms host critical path) --")
    add(f"{'lane/build_mode':<28}{'n':>6}{'p50':>10}{'p95':>10}"
        f"{'p99':>10}")
    for key, p in data["round_latency_ms"].items():
        add(f"{key:<28}{p['n']:>6}{p.get('p50', '-'):>10}"
            f"{p.get('p95', '-'):>10}{p.get('p99', '-'):>10}")
    for fam, p in data["backend_latency_ms"].items():
        add(f"{'backend=' + fam:<28}{p['n']:>6}{p.get('p50', '-'):>10}"
            f"{p.get('p95', '-'):>10}{p.get('p99', '-'):>10}")
    add("")
    ex = data["express"]
    e2b = ex["e2b_ms"]
    add("-- express lane --")
    if e2b["n"]:
        add(f"event-to-bind ms: n={e2b['n']} p50={e2b['p50']} "
            f"p95={e2b['p95']} p99={e2b['p99']}")
    else:
        add("event-to-bind ms: no samples (lane off or no arrivals)")
    add(f"batches={ex.get('batches', 0)} places={ex.get('places', 0)} "
        f"corrected={ex.get('corrected', 0)} "
        f"degrades={ex.get('degrades', 0)}")
    add("")
    add("-- degradations (count by reason) --")
    any_deg = False
    for kind, reasons in data["degradations"].items():
        for reason, n in reasons.items():
            any_deg = True
            add(f"{kind:<18}{n:>6}  {reason}")
    if not any_deg:
        add("none")
    add("")
    add("-- SLO breaches (latch trips by objective) --")
    if data.get("slo_breaches"):
        for spec, n in data["slo_breaches"].items():
            add(f"{n:>4}  {spec}")
    else:
        add("none")
    add("")
    ch = data["churn"]
    add("-- placement churn --")
    add(f"{'event':<20}{'total':>8}{'per round':>12}")
    for k in _CHURN_EVENTS:
        if ch["totals"][k]:
            add(f"{k:<20}{ch['totals'][k]:>8}"
                f"{ch['per_round'][k]:>12}")
    add(f"deltas deferred: {ch['deltas_deferred']}  "
        f"bind failures: {ch['bind_failures']}")
    if data["span_phase_p50_ms"]:
        add("")
        add("-- span phases (p50 ms; --trace_profile) --")
        for k, v in data["span_phase_p50_ms"].items():
            add(f"{k:<28}{v:>10}")
    return "\n".join(out)
