"""Anomaly flight recorder: the last K rounds' full inputs, dumpable.

The operational gap this closes (ISSUE 12): when a round degrades to
the oracle, an express batch falls back, a placement fetch times out,
or the watch stream storms into repeated resyncs, the DEGRADE /
EXPRESS_DEGRADE / FETCH_TIMEOUT counters tick — and the inputs that
triggered them evaporate. The flight recorder keeps a bounded ring of
the last K rounds' complete host-side solve inputs (graph arrays,
GraphMeta, cost-model inputs, flags, padding floors, padded dims,
warm-start seed, stats) plus the inter-round express batches, captured
at ``begin_round`` time from arrays the builder/bridge already
materialized. On an anomaly (or on demand) the ring dumps to an
``.npz`` (every array) + a JSON manifest (every scalar/name), and
``python -m poseidon_tpu.obs.replay <dump>`` reconstructs the
instances, re-runs the real solve path offline, and asserts
bit-identity with the recorded assignment/cost.

Capture cost discipline: the capture helpers run inside the round's
begin/finish window, so they are registered PTA001/PTA002 hot scopes
(analysis/contracts.py) from day one — vectorized ``np.copy`` /
``list()`` only, no device syncs, no O(cluster) Python loops. The
warm-start seed is a host mirror the solver's ONE batched fetch
already downloaded (ops/resident.py ``_warm_seed``) — capturing it
moves bytes that are already on the host, never a new sync. Bench
config 12 (``flight_recorder_overhead``) pins the whole surface under
2% of a churned-warm round p50 with zero steady-state recompiles, the
same methodology as config 10.

Replay-fidelity contract: a round record carries everything the
resident solver's compiled chain reads — the replayed round runs the
SAME program over the SAME inputs from the SAME warm state, so its
assignment and cost are bit-identical, not merely cost-equal. Rounds
whose warm state had been patched on device by express batches carry
``warm_seed=None``; the recorded express batches in between reproduce
that state deterministically when the ring contains the full chain
(obs/replay.py replays records in order through one solver).
"""

from __future__ import annotations

import collections
import dataclasses
import json
import logging
import os
import time

import numpy as np

from poseidon_tpu.graph.builder import GraphMeta

log = logging.getLogger(__name__)

# default ring depth: how many rounds of inputs survive to a dump
FLIGHT_ROUNDS_DEFAULT = 8

# the dump format version (manifest "format"): bump on layout changes
# so replay can refuse dumps it does not understand instead of
# misreading them
DUMP_FORMAT = 1

# bounded dump-reason vocabulary — the trigger sites map their free-
# text causes onto these before they reach the metrics label
DUMP_REASONS = (
    "degrade",          # dense lane fell back to the oracle
    "express-degrade",  # an express batch fell back to the round path
    "fetch-timeout",    # the pipelined placement fetch missed deadline
    "resync-storm",     # repeated full-LIST resyncs within the window
    "manual",           # operator / driver requested
)

_META_ARRAYS = (
    "node_role", "arc_kind", "arc_task", "arc_machine", "arc_rack",
    "arc_weight", "arc_discount", "task_wait", "task_current",
    "task_node", "machine_node", "node_machine",
)
_META_LISTS = ("task_uids", "machine_names", "rack_names", "job_ids")


@dataclasses.dataclass
class RoundRecord:
    """One round's full host-side solve input (+ its result, attached
    at finish time)."""

    round_num: int
    cost_model: str
    flags: dict
    arrays: dict                 # src/dst/cap/supply (copies)
    meta: GraphMeta              # deep-copied host metadata
    cost_kwargs: dict            # KnowledgeBase aggregates (copies)
    pad_floors: dict             # solver grow-only padding floors
    dims: dict                   # Tp/Mp/n_prefs/smax the solve padded to
    warm_used: bool
    warm_seed: tuple | None      # host (asg, lvl, floor) or None
    rv: str = ""                 # watch resourceVersion, when known
    stats: dict | None = None
    result: dict | None = None   # assignment/channel/cost/backend/...

    kind = "round"


@dataclasses.dataclass
class ExpressRecord:
    """One inter-round express batch (inputs + outcome)."""

    round_num: int               # the round window it patched
    arrivals: list               # [{uid, wait_rounds, cpu_milli, mem_kb, prefs}]
    retires: list                # [(uid, machine)]
    removals: list               # [uid]
    slot_deltas: list            # [(machine, delta)]
    result: dict | None = None   # ok/reason/placements/cost/rounds

    kind = "express"


def _copy_meta(meta: GraphMeta) -> GraphMeta:
    """Deep host copy of a GraphMeta: the incremental builder patches
    its cached columns in place across rounds, so retained references
    would silently mutate under the ring."""
    return dataclasses.replace(
        meta,
        **{k: np.array(getattr(meta, k), copy=True)
           for k in _META_ARRAYS},
        **{k: list(getattr(meta, k)) for k in _META_LISTS},
    )


class FlightRecorder:
    """Bounded ring of round/express records + the dump writer.

    One instance per bridge (the driver builds it from
    ``--flight_recorder``/``--flight_dir``). Single-threaded by the
    bridge's own contract — every capture happens on the driver thread
    inside the round window.
    """

    # per-reason anomaly-dump cooldown: a persistently-anomalous
    # daemon (e.g. every round degrading to the oracle) must not
    # serialize the full ring to disk every round forever — one dump
    # per reason per window preserves the evidence without turning the
    # recorder into the incident. "manual" dumps are never throttled.
    COOLDOWN_S = 300.0

    # default on-disk retention: newest dumps kept per --flight_dir (a
    # flapping daemon writing one ring .npz per cooldown window must
    # not fill the disk before anyone reads the evidence); 0 disables
    # the GC entirely
    MAX_DUMPS_DEFAULT = 16

    def __init__(
        self,
        out_dir: str = "flightrec",
        *,
        rounds: int = FLIGHT_ROUNDS_DEFAULT,
        metrics=None,
        cooldown_s: float = COOLDOWN_S,
        max_dumps: int = MAX_DUMPS_DEFAULT,
    ):
        self.out_dir = out_dir
        self.rounds = max(int(rounds), 1)
        self.metrics = metrics
        self.cooldown_s = cooldown_s
        self.max_dumps = max(int(max_dumps), 0)
        self.records: collections.deque = collections.deque()
        self.dumps_total = 0
        self.dumps_suppressed = 0
        self.dumps_pruned = 0
        self._seq = 0
        self._last_dump: dict[str, float] = {}
        # boot-unique filename token: a restarted daemon's round
        # numbers and sequence counter reset, and overwriting the
        # PREVIOUS boot's dump would destroy exactly the post-mortem
        # evidence the recorder exists to preserve
        self._boot = time.strftime("%Y%m%dT%H%M%S", time.gmtime())

    # ---- capture (hot scopes: vectorized copies only) ------------------

    def capture_begin(
        self,
        *,
        round_num: int,
        cost_model: str,
        flags: dict,
        arrays: dict,
        meta: GraphMeta,
        cost_kwargs: dict,
        pad_floors: dict,
        dims: dict,
        warm_used: bool,
        warm_seed: tuple | None,
        rv: str = "",
    ) -> RoundRecord:
        rec = RoundRecord(
            round_num=round_num,
            cost_model=str(cost_model),
            flags=dict(flags),
            arrays={
                k: np.array(v, copy=True) for k, v in arrays.items()
            },
            meta=_copy_meta(meta),
            cost_kwargs={
                k: (np.array(v, copy=True) if v is not None else None)
                for k, v in cost_kwargs.items()
            },
            pad_floors=dict(pad_floors),
            dims=dict(dims),
            warm_used=bool(warm_used),
            warm_seed=(
                tuple(np.array(a, copy=True) for a in warm_seed)
                if warm_seed is not None else None
            ),
            rv=rv,
        )
        self.records.append(rec)
        self._trim()
        return rec

    def capture_finish(self, rec: RoundRecord | None, outcome,
                       stats_dict: dict | None,
                       extra: dict | None = None) -> None:
        """Attach a finished round's outcome (the replay assertion
        target) to its begin-time record. ``extra`` carries decision-
        layer context (unscheduled/deferred uids) for the explainer."""
        if rec is None:
            return
        if outcome is not None:
            rec.result = {
                "assignment": np.array(outcome.assignment, copy=True),
                "channel": np.array(outcome.channel, copy=True),
                "cost": int(outcome.cost),
                "backend": outcome.backend,
                "converged": bool(outcome.converged),
                **(extra or {}),
            }
        if stats_dict is not None:
            rec.stats = dict(stats_dict)

    def capture_express(
        self, round_num: int, batch, outcome,
        placements: dict | None = None,
    ) -> ExpressRecord:
        """One express batch: the coalesced inputs (already plain host
        scalars/tuples) + its outcome. ``placements`` is the bridge's
        post-validation uid->machine map when the batch bound pods."""
        rec = ExpressRecord(
            round_num=round_num,
            arrivals=[
                {
                    "uid": a.uid,
                    "wait_rounds": int(a.wait_rounds),
                    "cpu_milli": int(a.cpu_milli),
                    "mem_kb": int(a.mem_kb),
                    "prefs": [list(map(int, p)) for p in a.prefs],
                }
                for a in batch.arrivals
            ],
            retires=[list(r) for r in batch.retires],
            removals=list(batch.removals),
            slot_deltas=[[m, int(d)] for m, d in batch.slot_deltas],
        )
        if outcome is not None:
            rec.result = {
                "ok": bool(outcome.ok),
                "reason": outcome.reason,
                "placements": (
                    sorted(placements.items())
                    if placements is not None
                    else [list(p) for p in outcome.placements]
                ),
                "cost": int(outcome.cost),
                "rounds": int(outcome.rounds),
            }
        self.records.append(rec)
        self._trim()
        return rec

    def last_round_record(self) -> RoundRecord | None:
        """The most recent round record (the live ``--explain``
        target), or None before the first captured round."""
        for r in reversed(self.records):
            if r.kind == "round":
                return r
        return None

    # express records kept per retained round window: a daemon parked
    # in one endless express window (no round ticking) must not grow
    # the ring without bound — the oldest batches drop first, and a
    # replay of the truncated chain reports divergence honestly
    EXPRESS_PER_ROUND = 64

    def _trim(self) -> None:
        """Keep at most ``rounds`` RoundRecords (express records ride
        with their round window; leading orphans drop with it) and a
        bounded number of express records."""
        n_rounds = sum(
            1 for r in self.records if r.kind == "round"
        )
        while n_rounds > self.rounds and self.records:
            dropped = self.records.popleft()
            if dropped.kind == "round":
                n_rounds -= 1
        # orphan express records older than the first retained round
        while self.records and self.records[0].kind != "round":
            self.records.popleft()
        n_express = len(self.records) - n_rounds
        if n_express > self.rounds * self.EXPRESS_PER_ROUND:
            kept: collections.deque = collections.deque()
            to_drop = n_express - self.rounds * self.EXPRESS_PER_ROUND
            for r in self.records:
                if to_drop and r.kind == "express":
                    to_drop -= 1
                    continue
                kept.append(r)
            self.records = kept

    # ---- the dump writer (anomaly / on-demand; NOT a hot scope) --------

    def dump(self, reason: str, *, label: str = "") -> str | None:
        """Write the ring as ``<stem>.npz`` + ``<stem>.json``; returns
        the manifest path (None when the ring is empty, or when the
        same anomaly reason already dumped within ``cooldown_s`` —
        "manual" is never throttled). ``reason`` must be one of
        ``DUMP_REASONS``; ``label`` carries the free-text cause into
        the manifest."""
        if reason not in DUMP_REASONS:
            raise ValueError(
                f"undeclared dump reason {reason!r}; the vocabulary "
                f"is flightrec.DUMP_REASONS"
            )
        if not self.records:
            return None
        now = time.monotonic()
        if reason != "manual":
            last = self._last_dump.get(reason)
            if last is not None and now - last < self.cooldown_s:
                self.dumps_suppressed += 1
                log.info(
                    "flight recorder: %s dump suppressed (%gs "
                    "cooldown; %d suppressed so far)",
                    reason, self.cooldown_s, self.dumps_suppressed,
                )
                return None
            self._last_dump[reason] = now
        os.makedirs(self.out_dir, exist_ok=True)
        last_round = max(
            (r.round_num for r in self.records), default=0
        )
        self._seq += 1
        stem = os.path.join(
            self.out_dir,
            f"flightrec-{self._boot}-r{last_round:06d}-{reason}-"
            f"{self._seq:03d}",
        )
        blobs: dict[str, np.ndarray] = {}
        manifest_records = []
        for i, rec in enumerate(self.records):
            pre = f"{i:03d}"
            if rec.kind == "express":
                manifest_records.append({
                    "kind": "express",
                    "round_num": rec.round_num,
                    "arrivals": rec.arrivals,
                    "retires": rec.retires,
                    "removals": rec.removals,
                    "slot_deltas": rec.slot_deltas,
                    "result": rec.result,
                })
                continue
            for k, v in rec.arrays.items():
                blobs[f"{pre}/arrays/{k}"] = v
            for k in _META_ARRAYS:
                blobs[f"{pre}/meta/{k}"] = getattr(rec.meta, k)
            for k, v in rec.cost_kwargs.items():
                if v is not None:
                    blobs[f"{pre}/ck/{k}"] = v
            if rec.warm_seed is not None:
                for name, v in zip(("asg", "lvl", "floor"),
                                   rec.warm_seed):
                    blobs[f"{pre}/warm/{name}"] = v
            if rec.result is not None:
                blobs[f"{pre}/result/assignment"] = \
                    rec.result["assignment"]
                blobs[f"{pre}/result/channel"] = rec.result["channel"]
            manifest_records.append({
                "kind": "round",
                "round_num": rec.round_num,
                "cost_model": rec.cost_model,
                "flags": rec.flags,
                "pad_floors": rec.pad_floors,
                "dims": rec.dims,
                "warm_used": rec.warm_used,
                "has_warm_seed": rec.warm_seed is not None,
                "rv": rec.rv,
                "meta": {
                    **{k: getattr(rec.meta, k) for k in _META_LISTS},
                    "n_nodes": int(rec.meta.n_nodes),
                    "n_arcs": int(rec.meta.n_arcs),
                },
                "cost_kwargs_present": sorted(
                    k for k, v in rec.cost_kwargs.items()
                    if v is not None
                ),
                "stats": rec.stats,
                "result": (
                    {
                        k: v for k, v in rec.result.items()
                        if k not in ("assignment", "channel")
                    }
                    if rec.result is not None else None
                ),
            })
        import jax

        import poseidon_tpu

        manifest = {
            "format": DUMP_FORMAT,
            "reason": reason,
            "label": label,
            "created_unix": time.time(),
            "poseidon_tpu": poseidon_tpu.__version__,
            "jax": jax.__version__,
            "records": manifest_records,
        }
        np.savez_compressed(stem + ".npz", **blobs)
        with open(stem + ".json", "w") as fh:
            json.dump(manifest, fh, indent=1, default=str)
        self.dumps_total += 1
        if self.metrics is not None:
            self.metrics.record_flightrec_dump(reason)
        log.warning(
            "flight recorder dumped %d record(s) to %s.{npz,json} "
            "(reason=%s%s)", len(self.records), stem, reason,
            f": {label}" if label else "",
        )
        self._prune_dumps()
        return stem + ".json"

    def _prune_dumps(self) -> None:
        """Bound the on-disk dump set to the ``max_dumps`` most recent
        (oldest-first GC over every ``flightrec-*`` stem in the
        directory — previous boots' dumps age out the same way, which
        is the point: the disk bound must hold across restarts)."""
        if not self.max_dumps:
            return
        try:
            names = sorted(
                n for n in os.listdir(self.out_dir)
                if n.startswith("flightrec-") and n.endswith(".json")
            )
        except OSError:
            return
        for stale in names[:-self.max_dumps]:
            stem = os.path.join(self.out_dir, stale[: -len(".json")])
            for suffix in (".json", ".npz"):
                try:
                    os.remove(stem + suffix)
                except OSError:
                    pass
            self.dumps_pruned += 1
            log.info("flight recorder pruned old dump %s", stem)


# ---------------------------------------------------------------------------
# dump loading (the replay harness's input side)
# ---------------------------------------------------------------------------


def load_dump(manifest_path: str) -> dict:
    """Load a dump back into record objects.

    Returns ``{"manifest": dict, "records": [RoundRecord |
    ExpressRecord]}``. Tolerant of doctored dumps to the extent of
    raising ``ValueError`` with a reason (unknown format, missing
    blobs) rather than crashing deeper in."""
    if manifest_path.endswith(".npz"):
        manifest_path = manifest_path[: -len(".npz")] + ".json"
    with open(manifest_path) as fh:
        manifest = json.load(fh)
    if manifest.get("format") != DUMP_FORMAT:
        raise ValueError(
            f"dump format {manifest.get('format')!r} != supported "
            f"{DUMP_FORMAT}"
        )
    npz_path = manifest_path[: -len(".json")] + ".npz"
    with np.load(npz_path) as z:
        blobs = {k: z[k] for k in z.files}

    def blob(pre, key):
        full = f"{pre}/{key}"
        if full not in blobs:
            raise ValueError(f"dump is missing array {full!r}")
        return blobs[full]

    records = []
    for i, m in enumerate(manifest.get("records", [])):
        pre = f"{i:03d}"
        if m.get("kind") == "express":
            records.append(ExpressRecord(
                round_num=int(m["round_num"]),
                arrivals=m["arrivals"],
                retires=m["retires"],
                removals=m["removals"],
                slot_deltas=m["slot_deltas"],
                result=m.get("result"),
            ))
            continue
        mm = m["meta"]
        meta = GraphMeta(
            **{k: blob(pre, f"meta/{k}") for k in _META_ARRAYS},
            **{k: list(mm[k]) for k in _META_LISTS},
            n_nodes=int(mm["n_nodes"]),
            n_arcs=int(mm["n_arcs"]),
        )
        arrays = {
            k.split("/", 2)[2]: v for k, v in blobs.items()
            if k.startswith(f"{pre}/arrays/")
        }
        cost_kwargs = {
            k: blob(pre, f"ck/{k}")
            for k in m.get("cost_kwargs_present", [])
        }
        warm_seed = None
        if m.get("has_warm_seed"):
            warm_seed = tuple(
                blob(pre, f"warm/{name}")
                for name in ("asg", "lvl", "floor")
            )
        result = None
        if m.get("result") is not None:
            result = dict(m["result"])
            result["assignment"] = blob(pre, "result/assignment")
            result["channel"] = blob(pre, "result/channel")
        records.append(RoundRecord(
            round_num=int(m["round_num"]),
            cost_model=m["cost_model"],
            flags=m.get("flags", {}),
            arrays=arrays,
            meta=meta,
            cost_kwargs=cost_kwargs,
            pad_floors=m.get("pad_floors", {}),
            dims=m.get("dims", {}),
            warm_used=bool(m.get("warm_used")),
            warm_seed=warm_seed,
            rv=m.get("rv", ""),
            stats=m.get("stats"),
            result=result,
        ))
    return {"manifest": manifest, "records": records}
