"""Dependency-free metrics registry + the scheduler's instrument set.

Three instrument kinds, all label-aware, all guarded by ONE registry
lock (recording sites run inside the round's hot path on the driver
thread; ``render`` runs on the metrics server's handler thread — the
shared lock is the documented discipline, declared in
``analysis/contracts.py`` under PTA004):

- ``Counter``: monotonically increasing float (``inc``);
- ``Gauge``: last-write-wins float (``set``);
- ``Histogram``: fixed cumulative buckets + sum + count (``observe``).
  Buckets are FIXED at registration — no dynamic re-bucketing on the
  hot path, one tuple shared by every labelset.

``render()`` emits Prometheus text exposition format (version 0.0.4):
``# HELP`` / ``# TYPE`` headers, ``name{label="v"} value`` samples,
``_bucket``/``_sum``/``_count`` for histograms with cumulative ``le``.

``SchedulerMetrics`` declares every instrument the scheduler feeds and
owns the recording helpers the bridge / resident solver / watcher /
express lane call. The contract: recording happens at finish/actuate
time ONLY, from host-side values the caller already holds (stats
fields, perf-counter durations, outcome counts) — never a device
fetch, never an O(cluster) walk. The helpers are registered as
PTA001/PTA002 scopes so the linter enforces that, and
``tests/test_obs.py`` + bench config 10 (``observability_overhead``)
prove the surface costs <2% of a flagship churned-warm round.
"""

from __future__ import annotations

import collections
import math
import threading

from poseidon_tpu.obs.lifecycle import bounded_lane

# Default latency buckets (milliseconds): spans sub-ms express repairs
# through multi-second degraded rounds. One shared tuple — the bucket
# loop on the hot path is a fixed 13 iterations, not data-dependent.
LATENCY_BUCKETS_MS = (
    0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
    1000.0, 2500.0, 5000.0,
)

# Express event-to-bind buckets: the lane's budget is single-digit ms,
# so the resolution lives there.
E2B_BUCKETS_MS = (
    0.25, 0.5, 1.0, 2.0, 3.0, 5.0, 8.0, 13.0, 25.0, 50.0, 100.0, 250.0,
)

# Lifecycle event-to-confirmed buckets: the tick lane waits for a
# round (polling periods are seconds), the express lane binds in
# single-digit ms, the restart lane spans a process death — one set
# covers ms through minutes.
E2C_BUCKETS_MS = (
    1.0, 5.0, 10.0, 25.0, 100.0, 500.0, 1000.0, 5000.0, 15_000.0,
    60_000.0, 300_000.0,
)

# XLA compile latency buckets (ms): warmup compiles run 100ms-10s+
COMPILE_BUCKETS_MS = (
    10.0, 50.0, 100.0, 250.0, 500.0, 1000.0, 2500.0, 5000.0,
    10_000.0, 30_000.0,
)


def _labelkey(labels: dict) -> tuple:
    """Canonical hashable key for one labelset."""
    return tuple(sorted(labels.items()))


def _fmt_value(v: float) -> str:
    """Prometheus sample value: integers render without the '.0';
    non-finite values use the exposition format's spellings (an inf
    gauge — e.g. an SLO percentile beyond the top histogram bucket —
    must not crash every subsequent scrape)."""
    f = float(v)
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    if math.isnan(f):
        return "NaN"
    return str(int(f)) if f == int(f) else repr(f)


def _fmt_labels(key: tuple, extra: str = "") -> str:
    parts = [f'{k}="{v}"' for k, v in key]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


class _Instrument:
    """Base: name, help text, and the registry's shared lock."""

    kind = "untyped"

    def __init__(self, name: str, help_: str, lock: threading.Lock):
        self.name = name
        self.help = help_
        self._lock = lock

    def _render(self, out: list[str]) -> None:  # pragma: no cover
        raise NotImplementedError


class Counter(_Instrument):
    kind = "counter"

    def __init__(self, name: str, help_: str, lock: threading.Lock):
        super().__init__(name, help_, lock)
        self._values: dict[tuple, float] = {}

    def inc(self, amount: float = 1.0, **labels) -> None:
        if amount == 0:
            return
        key = _labelkey(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def _render(self, out: list[str]) -> None:
        for key, v in sorted(self._values.items()):
            out.append(f"{self.name}{_fmt_labels(key)} {_fmt_value(v)}")


class Gauge(_Instrument):
    kind = "gauge"

    def __init__(self, name: str, help_: str, lock: threading.Lock):
        super().__init__(name, help_, lock)
        self._values: dict[tuple, float] = {}

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._values[_labelkey(labels)] = float(value)

    def snapshot(self) -> dict[tuple, float]:
        """Labelset -> value copy under the lock (the SLO engine's
        read surface)."""
        with self._lock:
            return dict(self._values)

    def _render(self, out: list[str]) -> None:
        for key, v in sorted(self._values.items()):
            out.append(f"{self.name}{_fmt_labels(key)} {_fmt_value(v)}")


class Histogram(_Instrument):
    kind = "histogram"

    def __init__(
        self, name: str, help_: str, lock: threading.Lock,
        buckets: tuple[float, ...],
    ):
        super().__init__(name, help_, lock)
        self.buckets = tuple(sorted(buckets))
        # labelset -> (per-bucket counts list, sum, count)
        self._values: dict[tuple, list] = {}

    def observe(self, value: float, **labels) -> None:
        key = _labelkey(labels)
        with self._lock:
            slot = self._values.get(key)
            if slot is None:
                slot = [[0] * len(self.buckets), 0.0, 0]
                self._values[key] = slot
            counts, _, _ = slot
            for i, le in enumerate(self.buckets):
                if value <= le:
                    counts[i] += 1
            slot[1] += value
            slot[2] += 1

    def snapshot(self) -> dict[tuple, tuple]:
        """Labelset -> (bucket counts copy, sum, count) under the
        lock — the SLO engine's windowed-burn read surface."""
        with self._lock:
            return {
                key: (list(counts), total, n)
                for key, (counts, total, n) in self._values.items()
            }

    def _render(self, out: list[str]) -> None:
        for key, (counts, total, n) in sorted(self._values.items()):
            for le, c in zip(self.buckets, counts):
                le_label = 'le="%s"' % _fmt_value(le)
                out.append(
                    f"{self.name}_bucket"
                    f"{_fmt_labels(key, le_label)} {c}"
                )
            inf_label = 'le="+Inf"'
            out.append(
                f"{self.name}_bucket"
                f"{_fmt_labels(key, inf_label)} {n}"
            )
            out.append(
                f"{self.name}_sum{_fmt_labels(key)} {_fmt_value(total)}"
            )
            out.append(f"{self.name}_count{_fmt_labels(key)} {n}")


class MetricsRegistry:
    """Owns every instrument + the one lock they all record under.

    Registration is idempotent (same name returns the existing
    instrument; a kind mismatch raises — two subsystems silently
    sharing a name as different kinds is a bug).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, _Instrument] = {}

    def _register(self, cls, name: str, help_: str, **kw) -> _Instrument:
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if not isinstance(existing, cls):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{existing.kind}, not {cls.kind}"
                    )
                return existing
            m = cls(name, help_, self._lock, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help_: str = "") -> Counter:
        return self._register(Counter, name, help_)

    def gauge(self, name: str, help_: str = "") -> Gauge:
        return self._register(Gauge, name, help_)

    def histogram(
        self, name: str, help_: str = "",
        buckets: tuple[float, ...] = LATENCY_BUCKETS_MS,
    ) -> Histogram:
        return self._register(Histogram, name, help_, buckets=buckets)

    def render(self) -> str:  # pta: background-thread
        """Prometheus text exposition; called from the metrics server's
        handler thread (the shared lock is the cross-thread contract)."""
        out: list[str] = []
        with self._lock:
            for name in sorted(self._metrics):
                m = self._metrics[name]
                if m.help:
                    out.append(f"# HELP {name} {m.help}")
                out.append(f"# TYPE {name} {m.kind}")
                m._render(out)
        return "\n".join(out) + "\n"


# ---------------------------------------------------------------------------
# the scheduler's instrument set
# ---------------------------------------------------------------------------

# resync-storm detection: gauge flips to 1 when the last STORM_WINDOW
# rounds saw >= STORM_RESYNCS full-LIST resyncs (a flapping watch
# stream re-listing the cluster every tick is an operational incident,
# not a per-event log line)
STORM_WINDOW = 8
STORM_RESYNCS = 3

# deliberate oracle ROUTING (small instances, non-taxonomy graphs) is
# dispatch, not degradation — mirrors the bridge's degrades_total rule
_ROUTED_WHYS = ("small-instance", "not-scheduling-shaped")


def _backend_family(backend: str) -> str:
    if not backend:
        return "empty"
    if backend.startswith("oracle:"):
        return "oracle"
    return "dense"


def resync_reason_label(reason: str) -> str:
    """Bounded label for a free-text resync reason (Prometheus label
    cardinality must stay finite)."""
    if "410" in reason:
        return "gone"
    if "watch_max_lag" in reason:
        return "stale"
    if "unparseable" in reason or "undecodable" in reason:
        return "decode"
    return "error"


# every label value a record_* helper mints is folded onto one of
# these bounded vocabularies BEFORE it reaches an instrument — an
# out-of-vocabulary value becomes "other", never a fresh series
# (unbounded label churn is how a metrics endpoint ODs its scraper;
# tests/test_observatory.py fuzzes every fold)

# driver lane compositions (cli builds "watch+pipelined+sharded"...)
_LANE_PARTS = frozenset({
    "poll", "watch", "express", "pipelined", "sharded", "agg",
    "round", "service", "bench",
})

_DEGRADE_WHYS = frozenset({
    "memory-envelope", "cost-domain", "uncertified", "kernel-envelope",
    "general-unconverged", "general-infeasible", "general-guard",
    "small-instance", "not-scheduling-shaped",
})

_BUILD_MODES = frozenset({"delta", "full", "legacy", "none"})

_RESOURCES = frozenset({"nodes", "pods"})


def lane_label(lane: str) -> str:
    """Fold a driver lane composition onto the bounded vocabulary:
    every '+'-part must be known, else the whole value is "other"."""
    if not lane:
        return "round"
    if all(p in _LANE_PARTS for p in lane.split("+")):
        return lane
    return "other"


def degrade_why_label(why: str) -> str:
    return why if why in _DEGRADE_WHYS else "other"


def build_mode_label(mode: str) -> str:
    mode = mode or "none"
    return mode if mode in _BUILD_MODES else "other"


def resource_label(resource: str) -> str:
    return resource if resource in _RESOURCES else "other"


class SchedulerMetrics:
    """Every instrument the scheduler feeds, plus recording helpers.

    One instance per daemon, shared by the bridge, the resident solver,
    and the watcher. All ``record_*`` methods take host-side values the
    caller already holds — they are registered PTA001/PTA002 hot
    scopes, so the linter rejects any device sync or cluster-sized walk
    slipping in later.
    """

    def __init__(self, registry: MetricsRegistry):
        self.registry = registry
        self.rounds = registry.counter(
            "poseidon_rounds_total",
            "scheduling rounds completed, by lane and backend family",
        )
        self.round_latency = registry.histogram(
            "poseidon_round_latency_ms",
            "per-round host critical path (SchedulerStats.total_ms), "
            "by lane and build mode",
        )
        self.round_phase = registry.gauge(
            "poseidon_round_phase_ms",
            "last round's per-phase host timers, by phase",
        )
        self.pods = registry.gauge(
            "poseidon_pods",
            "pod counts at the last round, by state",
        )
        self.round_cost = registry.gauge(
            "poseidon_round_cost",
            "last round's exact solve objective",
        )
        self.deltas = registry.counter(
            "poseidon_deltas_total",
            "scheduling deltas emitted, by kind "
            "(place/migrate/preempt/noop/deferred)",
        )
        self.evictions = registry.counter(
            "poseidon_evictions_total",
            "tasks evicted by node loss",
        )
        self.bind_failures = registry.counter(
            "poseidon_bind_failures_total",
            "binding/actuation POSTs that failed (pod re-queued)",
        )
        self.fetch_timeouts = registry.counter(
            "poseidon_fetch_timeouts_total",
            "pipelined placement fetches that missed "
            "--max_solver_runtime",
        )
        self.degrades = registry.counter(
            "poseidon_degrades_total",
            "dense-lane degrades to the CPU oracle, by why "
            "(deliberate small-instance routing is not counted)",
        )
        self.degraded = registry.gauge(
            "poseidon_degraded",
            "1 while the most recent SOLVING round degraded to the "
            "oracle, by why; cleared by the next non-degraded solve "
            "(certified dense or deliberate oracle routing); empty "
            "no-solve rounds leave it unchanged",
        )
        self.watch_resyncs = registry.counter(
            "poseidon_watch_resyncs_total",
            "watch degradations to a full LIST resync, by reason "
            "(gone/decode/stale/error)",
        )
        self.watch_reconnects = registry.counter(
            "poseidon_watch_reconnects_total",
            "error-path watch stream reconnects, by resource",
        )
        self.resync_storm = registry.gauge(
            "poseidon_watch_resync_storm",
            f"1 while >= {STORM_RESYNCS} resyncs landed within the "
            f"last {STORM_WINDOW} rounds",
        )
        self.express_batches = registry.counter(
            "poseidon_express_batches_total",
            "express-lane batches that completed certified "
            "(including retire/completion-only batches with zero "
            "placements)",
        )
        self.express_places = registry.counter(
            "poseidon_express_places_total",
            "pods bound between round ticks by the express lane",
        )
        self.express_degrades = registry.counter(
            "poseidon_express_degrades_total",
            "express batches that fell back to the round path, by why",
        )
        self.express_corrected = registry.counter(
            "poseidon_express_corrected_total",
            "express placements the correction round moved",
        )
        self.express_e2b = registry.histogram(
            "poseidon_express_e2b_ms",
            "express event-to-bind-decision latency",
            buckets=E2B_BUCKETS_MS,
        )
        self.solver_fetches = registry.counter(
            "poseidon_solver_fetches_total",
            "sanctioned device->host placement fetches, by lane "
            "(round/express/stream)",
        )
        self.stream_flushes = registry.counter(
            "poseidon_stream_flushes_total",
            "stream-lane flushes (K accumulated windows scanned as "
            "one device program with one fetch)",
        )
        self.placements_per_fetch = registry.gauge(
            "poseidon_placements_per_fetch",
            "placements per sanctioned fetch in the last stream "
            "flush (the sync-floor amortization the stream lane "
            "buys; the synced express lane pins this at its "
            "per-batch placement count)",
        )
        self.solver_warm = registry.gauge(
            "poseidon_solver_warm",
            "1 while a warm on-HBM DenseState is live",
        )
        self.express_context_ready = registry.gauge(
            "poseidon_express_context_ready",
            "1 while a warm express context is patchable between ticks",
        )
        self.ready = registry.gauge(
            "poseidon_ready",
            "the /readyz latch: 1 after seed LIST + first round over "
            "real state (certified solve or proven-empty)",
        )
        # ---- the quality observatory (obs/lifecycle|audit|slo) ----
        self.pod_e2c = registry.histogram(
            "poseidon_pod_e2c_ms",
            "per-pod event-to-confirmed latency by (bounded) "
            "lifecycle lane (tick/express/service/restart/other); "
            "restart-lane samples are wall-differenced across the "
            "process boundary (the documented clock-contract "
            "exception)",
            buckets=E2C_BUCKETS_MS,
        )
        self.unsched_wait = registry.gauge(
            "poseidon_unsched_wait_rounds",
            "wait-age distribution of STANDING unscheduled pods at "
            "the last round, by quantile (p50/p95/max)",
        )
        self.lifecycle_dropped = registry.counter(
            "poseidon_lifecycle_dropped_total",
            "pod timelines dropped because the lifecycle tracker was "
            "at its open-timeline bound",
        )
        self.trace_dropped = registry.counter(
            "poseidon_trace_dropped_total",
            "trace events overwritten by the bounded in-memory ring "
            "before any flush (a post-mortem trace missing them is "
            "partial, not complete)",
        )
        self.audit_regret = registry.gauge(
            "poseidon_audit_regret",
            "shadow audit: status-quo placement cost minus the "
            "certified optimum of the same re-priced instance (0 = "
            "placing optimally within the stated hysteresis)",
        )
        self.audit_drift = registry.gauge(
            "poseidon_audit_drift_pods",
            "shadow audit: running pods whose placement differs from "
            "the audit optimum (tie-noisy; regret is the alertable "
            "number)",
        )
        self.audit_frag = registry.gauge(
            "poseidon_audit_frag_slots",
            "shadow audit fragmentation index: largest free seat "
            "count on any single machine, by (bounded) SKU class — "
            "the biggest one-machine gang that could still land",
        )
        self.audit_ms = registry.gauge(
            "poseidon_audit_ms",
            "wall time of the most recent shadow audit (background "
            "thread; not on any round's critical path)",
        )
        self.audit_runs = registry.counter(
            "poseidon_audit_runs_total",
            "completed shadow audits, by outcome (ok/error)",
        )
        self.slo_healthy = registry.gauge(
            "poseidon_slo_healthy",
            "1 while the objective's burn-rate alert is inactive, by "
            "slo (operator-declared specs: bounded by construction)",
        )
        self.slo_burn = registry.gauge(
            "poseidon_slo_burn_rate",
            "error-budget burn rate by slo and window (short/long); "
            ">1 sustained in both windows trips the breach latch",
        )
        self.slo_value = registry.gauge(
            "poseidon_slo_value",
            "current point value of the objective's source (display "
            "estimate; the burn math uses exact bucket counts)",
        )
        self.slo_breaches = registry.counter(
            "poseidon_slo_breaches_total",
            "SLO breach-latch trips (exactly one per breach window), "
            "by slo",
        )
        # ---- device telemetry (satellite: live HBM + compiles) ----
        self.device_hbm = registry.gauge(
            "poseidon_device_hbm_bytes",
            "device memory by kind: live = the backend's own "
            "bytes-in-use (platforms that expose memory_stats), "
            "predicted = check_table_budget's per-device estimate "
            "for the last dense round — the budget guard's math "
            "cross-checked against real hardware",
        )
        self.xla_compile = registry.histogram(
            "poseidon_xla_compile_ms",
            "XLA backend compile latency (fed from the CompileCounter "
            "monitoring seam; nonzero only during warmup/growth)",
            buckets=COMPILE_BUCKETS_MS,
        )
        self.flightrec_dumps = registry.counter(
            "poseidon_flightrec_dumps_total",
            "anomaly flight-recorder dumps written, by (bounded) "
            "reason (degrade/express-degrade/fetch-timeout/"
            "resync-storm/manual)",
        )
        # ---- failure-domain survival (ISSUE 15) ----
        self.guard_holds = registry.counter(
            "poseidon_eviction_guard_holds_total",
            "mass-eviction guard holds (an implausible >50% snapshot "
            "shrink held pending strikes/grace), by kind (node/pod)",
        )
        self.guard_releases = registry.counter(
            "poseidon_eviction_guard_releases_total",
            "mass-eviction guard releases, by kind and outcome "
            "(accepted = honored as true death after the strike/grace "
            "bound; recovered = the snapshot healed mid-hold)",
        )
        self.guard_active = registry.gauge(
            "poseidon_eviction_guard_active",
            "1 while the mass-eviction guard is holding a shrink, by "
            "kind (node/pod)",
        )
        self.outage = registry.gauge(
            "poseidon_outage",
            "1 while the apiserver-outage degradation ladder is "
            "active (consecutive transport failures crossed "
            "--outage_threshold); rounds keep solving from last-known "
            "state, POSTs park in the actuation outbox",
        )
        self.outage_episodes = registry.counter(
            "poseidon_outage_episodes_total",
            "apiserver outage windows entered (ONE per window, "
            "however many POSTs/polls failed inside it)",
        )
        self.outbox_pending = registry.gauge(
            "poseidon_outbox_pending",
            "actuations parked in the outbox awaiting a reachable "
            "apiserver",
        )
        self.outbox_retries = registry.counter(
            "poseidon_outbox_retries_total",
            "outbox retry attempts (jittered backoff per entry)",
        )
        self.outbox_settled = registry.counter(
            "poseidon_outbox_settled_total",
            "outboxed actuations settled, by outcome (replayed/"
            "already-applied/stale)",
        )
        self.outbox_dead_letters = registry.counter(
            "poseidon_outbox_dead_letters_total",
            "outboxed actuations that exhausted their retry budget "
            "(pod re-queued through binding_failed), by op",
        )
        self.express_shed = registry.counter(
            "poseidon_express_shed_total",
            "express windows shed to the tick path because the pods "
            "stream queue exceeded --express_shed_queue (overload "
            "backpressure: the full round absorbs the burst)",
        )
        self.round_deadline_misses = registry.counter(
            "poseidon_round_deadline_misses_total",
            "rounds whose wall span exceeded --round_deadline_ms "
            "(the overload watchdog)",
        )
        self.overload = registry.gauge(
            "poseidon_overload",
            "1 while consecutive round-deadline misses have declared "
            "degraded=overload (express windows shed to the tick "
            "path); cleared by a round meeting the deadline",
        )
        # ---- crash safety / HA (poseidon_tpu/ha/) ----
        self.checkpoint_bytes = registry.gauge(
            "poseidon_checkpoint_bytes",
            "size of the most recent completed warm-state checkpoint "
            "(npz + manifest)",
        )
        self.checkpoint_age = registry.gauge(
            "poseidon_checkpoint_age_seconds",
            "seconds since the most recent completed warm-state "
            "checkpoint (alert when this exceeds a few cadences: the "
            "writer is wedged or failing)",
        )
        self.journal_replays = registry.counter(
            "poseidon_journal_replays_total",
            "incomplete journaled actuations replayed at restart, by "
            "outcome (replayed/already-applied/stale/failed/conflict)",
        )
        self.restores = registry.counter(
            "poseidon_restores_total",
            "warm-state restores performed at startup",
        )
        self.build_info = registry.gauge(
            "poseidon_build_info",
            "constant 1; the labels carry the build identity "
            "(package version, jax version, backend, mesh_width) — "
            "join on it to slice any series by deploy",
        )
        # ---- the service lane (multi-tenant batching, service/) ----
        # tenant labels are BOUNDED at the service layer: the first
        # service.MAX_TENANT_LABELS registered tenants keep their id,
        # later ones collapse into "other" (finite series forever)
        self.service_rounds = registry.counter(
            "poseidon_service_rounds_total",
            "service-lane tenant rounds completed, by (bounded) tenant",
        )
        self.service_round_ms = registry.histogram(
            "poseidon_service_round_ms",
            "per-tenant submit-to-result round latency in the service "
            "lane, by (bounded) tenant",
        )
        self.service_placements = registry.counter(
            "poseidon_service_placements_total",
            "pods placed across ALL tenants by the service lane (the "
            "aggregate pods/sec numerator)",
        )
        self.service_dispatches = registry.counter(
            "poseidon_service_dispatches_total",
            "batched bucket dispatches (one upload + one batched "
            "fetch each), by bucket shape TpxMpxP",
        )
        self.service_bucket_occupancy = registry.gauge(
            "poseidon_service_bucket_occupancy",
            "tenant instances in the most recent dispatch of each "
            "bucket shape",
        )
        self.service_compiles = registry.counter(
            "poseidon_service_compiles_total",
            "XLA compiles triggered by service launches (nonzero only "
            "during warmup / bucket growth; 0 in steady state)",
        )
        # degraded-gauge bookkeeping: whys currently set to 1, so a
        # recovery round can clear exactly what an earlier round set
        self._degraded_whys: set[str] = set()
        # fragmentation-gauge bookkeeping: SKU labels set by the last
        # audit, so a class that drains out of the fleet is zeroed
        self._frag_skus: set[str] = set()
        self._resync_window: collections.deque[int] = collections.deque(
            maxlen=STORM_WINDOW
        )

    # ---- per-round recording (bridge finish/begin path) ---------------

    def record_round(self, stats) -> None:
        """Record one completed round from its ``SchedulerStats`` —
        every input is a host float/int the bridge already computed."""
        lane = lane_label(stats.lane)
        family = _backend_family(stats.backend)
        self.rounds.inc(lane=lane, backend=family)
        if stats.backend:
            # latency/cost/phase describe a SOLVE: an idle cluster's
            # empty rounds (one per tick, ~µs total_ms, cost 0) would
            # otherwise collapse the histogram's p50 toward zero and
            # clobber the last real round's gauges — the same rounds
            # the trace report excludes ("no solve to time")
            self.round_latency.observe(
                stats.total_ms, lane=lane,
                build_mode=build_mode_label(stats.build_mode),
            )
            for phase, dur in (
                ("observe", stats.observe_ms),
                ("build", stats.build_ms),
                ("price", stats.price_ms),
                ("solve", stats.solve_ms),
                ("decompose", stats.decompose_ms),
                ("dispatch", stats.dispatch_ms),
                ("fetch_wait", stats.fetch_wait_ms),
                ("overlap", stats.overlap_ms),
            ):
                self.round_phase.set(dur, phase=phase)
            self.round_cost.set(stats.cost)
        self.pods.set(stats.pods_total, state="total")
        self.pods.set(stats.pods_pending, state="pending")
        self.pods.set(stats.pods_placed, state="placed")
        self.pods.set(stats.pods_unscheduled, state="unscheduled")
        self.deltas.inc(stats.deltas_place, kind="place")
        self.deltas.inc(stats.deltas_migrate, kind="migrate")
        self.deltas.inc(stats.deltas_preempt, kind="preempt")
        self.deltas.inc(stats.deltas_noop, kind="noop")
        self.deltas.inc(stats.deltas_deferred, kind="deferred")
        self.evictions.inc(stats.evictions)
        self.bind_failures.inc(stats.bind_failures)
        self.fetch_timeouts.inc(stats.fetch_timeouts)
        self.express_corrected.inc(stats.express_corrected)
        # degraded-to-oracle state as a labeled gauge tracking the
        # most recent SOLVE: set on a degraded round, cleared by any
        # non-degraded solve (certified dense or deliberately-routed
        # oracle). Empty rounds carry no solve evidence either way.
        why = ""
        if stats.backend.startswith("oracle:"):
            w = stats.backend.split(":", 1)[1]
            if w not in _ROUTED_WHYS:
                why = degrade_why_label(w)
        if why:
            self.degraded.set(1, why=why)
            self._degraded_whys.add(why)
        elif stats.backend:
            for w in self._degraded_whys:
                self.degraded.set(0, why=w)
            self._degraded_whys.clear()
        # resync storm over a sliding round window
        self._resync_window.append(stats.watch_resyncs)
        self.resync_storm.set(
            1 if sum(self._resync_window) >= STORM_RESYNCS else 0
        )

    def record_degrade(self, why: str) -> None:
        """One non-deliberate dense-lane degrade (the DEGRADE event's
        metrics twin)."""
        self.degrades.inc(why=degrade_why_label(why))

    def record_flightrec_dump(self, reason: str) -> None:
        """One flight-recorder dump written (reason is the recorder's
        own bounded vocabulary, flightrec.DUMP_REASONS)."""
        self.flightrec_dumps.inc(reason=reason)

    # ---- crash safety / HA (poseidon_tpu/ha/) --------------------------

    def record_checkpoint(self, nbytes: int) -> None:
        """One completed checkpoint write (writer thread; host ints —
        the registry lock is the cross-thread discipline)."""
        self.checkpoint_bytes.set(nbytes)
        self.checkpoint_age.set(0.0)

    def record_checkpoint_age(self, age_s: float) -> None:
        """Driver-thread per-round age refresh (host float)."""
        self.checkpoint_age.set(age_s)

    def record_journal_replay(self, outcome: str) -> None:
        self.journal_replays.inc(outcome=outcome)

    def record_restore(self) -> None:
        self.restores.inc()

    # ---- failure-domain survival (ISSUE 15) ----------------------------

    def record_guard_hold(self, kind: str) -> None:
        """One mass-eviction-guard hold (bridge observe path; kind is
        the bridge's own node/pod vocabulary — folded for safety)."""
        kind = kind if kind in ("node", "pod") else "other"
        self.guard_holds.inc(kind=kind)
        self.guard_active.set(1, kind=kind)

    def record_guard_release(self, kind: str, outcome: str) -> None:
        kind = kind if kind in ("node", "pod") else "other"
        outcome = outcome if outcome in ("accepted", "recovered") \
            else "other"
        self.guard_releases.inc(kind=kind, outcome=outcome)
        self.guard_active.set(0, kind=kind)

    def record_outage(self, active: bool) -> None:
        """The outage ladder flipped (ONE episode tick per entry)."""
        self.outage.set(1 if active else 0)
        if active:
            self.outage_episodes.inc()

    def record_outbox(
        self, pending: int, *, retries: int = 0, settled: str = "",
        dead_letter_op: str = "",
    ) -> None:
        """Outbox bookkeeping after a pump/enqueue (host ints the
        outbox already holds)."""
        self.outbox_pending.set(pending)
        if retries:
            self.outbox_retries.inc(retries)
        if settled:
            outcome = settled if settled in (
                "replayed", "already-applied", "stale"
            ) else "other"
            self.outbox_settled.inc(outcome=outcome)
        if dead_letter_op:
            op = dead_letter_op if dead_letter_op in (
                "bind", "evict", "migrate"
            ) else "other"
            self.outbox_dead_letters.inc(op=op)

    def record_express_shed(self) -> None:
        self.express_shed.inc()

    def record_deadline_miss(self, overloaded: bool) -> None:
        """One round-deadline miss; ``overloaded`` is the watchdog's
        current degraded-state verdict."""
        self.round_deadline_misses.inc()
        self.overload.set(1 if overloaded else 0)

    def record_overload_cleared(self) -> None:
        self.overload.set(0)

    # ---- the quality observatory ---------------------------------------

    def record_pod_e2c(self, e2c_ms: float, lane: str) -> None:
        """One closed pod timeline. The tracker pre-folds its lanes;
        the fold here keeps the PUBLIC seam bounded for any other
        caller (module-level import — no per-call cost)."""
        self.pod_e2c.observe(e2c_ms, lane=bounded_lane(lane))

    def record_unsched_wait(
        self, p50: float, p95: float, mx: float
    ) -> None:
        """The round's standing-unscheduled wait-age quantiles (host
        floats the lifecycle tracker already computed)."""
        self.unsched_wait.set(p50, q="p50")
        self.unsched_wait.set(p95, q="p95")
        self.unsched_wait.set(mx, q="max")

    def record_lifecycle_dropped(self) -> None:
        self.lifecycle_dropped.inc()

    def record_trace_dropped(self, n: int) -> None:
        """Trace-ring overwrites since the last round (bridge-reported
        delta; zero increments are free)."""
        self.trace_dropped.inc(n)

    def record_audit(self, res) -> None:
        """One completed shadow audit (worker thread; host ints — the
        registry lock is the cross-thread discipline, the same pattern
        as record_checkpoint)."""
        self.audit_runs.inc(outcome="error" if res.error else "ok")
        self.audit_ms.set(res.audit_ms)
        if res.error:
            return
        self.audit_regret.set(res.regret)
        self.audit_drift.set(res.drift_pods)
        for sku, slots in res.frag_slots.items():
            self.audit_frag.set(slots, sku=sku)
        # a SKU class that drained out of the fleet must not keep
        # reporting its last capacity forever: zero vanished labels
        # (labelsets cannot be deleted, so 0 is the tombstone)
        for sku in self._frag_skus - set(res.frag_slots):
            self.audit_frag.set(0, sku=sku)
        self._frag_skus = set(res.frag_slots)

    def record_slo(
        self, spec: str, *, healthy: bool, burn_short: float,
        burn_long: float, value, breached: bool,
    ) -> None:
        """One objective's evaluation tick (SLO engine, driver
        thread; ``spec`` is operator-declared, bounded by
        construction)."""
        self.slo_healthy.set(1 if healthy else 0, slo=spec)
        self.slo_burn.set(burn_short, slo=spec, window="short")
        self.slo_burn.set(burn_long, slo=spec, window="long")
        if value is not None:
            self.slo_value.set(value, slo=spec)
        if breached:
            self.slo_breaches.inc(slo=spec)

    # ---- device telemetry ----------------------------------------------

    def record_predicted_bytes(self, nbytes: int) -> None:
        """The dense round's per-device table estimate (the
        check_table_budget math; host int the solver already
        computed)."""
        self.device_hbm.set(nbytes, kind="predicted")

    def record_live_hbm(self) -> int | None:
        """Read the default backend's own memory stats and publish
        bytes-in-use (platforms without memory_stats — CPU — publish
        nothing). Called from the driver loop once per tick, never
        inside the round window: the runtime query is allocator
        bookkeeping, not a device sync, but it has no business on the
        hot path either."""
        import jax

        try:
            stats = jax.local_devices()[0].memory_stats()
        except Exception:  # backends without the API
            return None
        if not stats or "bytes_in_use" not in stats:
            return None
        live = int(stats["bytes_in_use"])
        self.device_hbm.set(live, kind="live")
        return live

    def record_compile(self, duration_ms: float) -> None:
        """One XLA backend compile (guards.py monitoring seam; may be
        called from any thread — the registry lock covers it)."""
        self.xla_compile.observe(duration_ms)

    def set_build_info(self, info: dict) -> None:
        """Publish the build-identity gauge (value 1, labels = the
        ``build_info()`` dict). Called once at daemon startup; also
        echoed in the /healthz JSON body."""
        self.build_info.set(1, **{
            k: str(v) for k, v in info.items()
        })

    # ---- express lane --------------------------------------------------

    def record_express_batch(self, e2b_ms_samples) -> None:
        """One certified express dispatch: per-placement
        event-to-bind-decision samples (already computed from
        perf-counter stamps; empty for a retire/completion-only
        batch)."""
        self.express_batches.inc()
        self.express_places.inc(len(e2b_ms_samples))
        for e2b in e2b_ms_samples:
            self.express_e2b.observe(e2b)

    def record_express_degrade(self, why: str) -> None:
        self.express_degrades.inc(why=_bounded_why(why))

    # ---- watch subsystem ----------------------------------------------

    def record_resync(self, reason: str) -> None:
        self.watch_resyncs.inc(reason=resync_reason_label(reason))

    def record_reconnect(self, resource: str, amount: int = 1) -> None:
        """``amount`` > 1 folds a stream's coalesced (queue-
        suppressed) reconnects in one increment (watch.py outage
        bounding)."""
        self.watch_reconnects.inc(
            amount, resource=resource_label(resource)
        )

    # ---- resident solver ----------------------------------------------

    def record_solver_round(
        self, fetches: int, warm: bool, express_ready: bool
    ) -> None:
        """Called by the solver at finish time: sanctioned-fetch count
        and warm-state liveness (host ints/bools it already holds)."""
        self.solver_fetches.inc(fetches, lane="round")
        self.solver_warm.set(1 if warm else 0)
        self.express_context_ready.set(1 if express_ready else 0)

    def record_express_fetch(self) -> None:
        self.solver_fetches.inc(lane="express")

    # ---- the streaming lane --------------------------------------------

    def record_stream_fetch(self) -> None:
        self.solver_fetches.inc(lane="stream")

    def record_stream_flush(
        self, windows: int, placements: int
    ) -> None:
        """One stream flush joined: K windows' placements landed on
        ONE sanctioned fetch (host ints the bridge already holds)."""
        self.stream_flushes.inc()
        self.placements_per_fetch.set(placements)

    # ---- the service lane ----------------------------------------------

    def record_service_round(
        self, tenant: str, total_ms: float, placed: int
    ) -> None:
        """One tenant round finished by the service pipeline: the
        submit-to-result latency and its placement count (host values
        the service already computed; ``tenant`` is pre-bounded)."""
        self.service_rounds.inc(tenant=tenant)
        self.service_round_ms.observe(total_ms, tenant=tenant)
        self.service_placements.inc(placed)

    def record_service_dispatch(
        self, bucket: str, occupancy: int
    ) -> None:
        """One batched bucket dispatch (one upload + one batched
        fetch): its shape key and how many tenant instances rode it."""
        self.service_dispatches.inc(bucket=bucket)
        self.service_bucket_occupancy.set(occupancy, bucket=bucket)

    def record_service_compiles(self, compiles: int) -> None:
        self.service_compiles.inc(compiles)


# express degrade reasons are free text (they embed uids/counts);
# collapse to a bounded vocabulary for the label
_WHY_BUCKETS = (
    ("unconfirmed", "unconfirmed"),
    ("domain", "domain"),
    ("uncertified", "uncertified"),
    ("change cap", "change-cap"),
    ("change_cap", "change-cap"),
    ("stream", "stream"),
    ("arrivals >", "batch-size"),
    ("rows exhausted", "rows-exhausted"),
    ("no-context", "no-context"),
    ("no warm state", "no-context"),
    ("round-in-flight", "round-in-flight"),
    ("class", "aggregation"),
    ("prefs", "prefs"),
)


def _bounded_why(why: str) -> str:
    for needle, label in _WHY_BUCKETS:
        if needle in why:
            return label
    return "vocabulary"


def build_info(mesh_width: int = 0) -> dict:
    """The build-identity labelset shared by the
    ``poseidon_build_info`` gauge and the ``/healthz`` JSON body:
    package version, jax version, the resolved jax backend, and the
    configured mesh width. Called once at daemon startup (resolving
    the backend initializes it — never on the hot path)."""
    import jax

    import poseidon_tpu

    try:
        backend = jax.default_backend()
    except RuntimeError:  # no backend available at all
        backend = "none"
    return {
        "package": "poseidon-tpu",
        "version": poseidon_tpu.__version__,
        "jax": jax.__version__,
        "backend": backend,
        "mesh_width": mesh_width,
    }
