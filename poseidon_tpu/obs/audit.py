"""Shadow audit: measured placement quality, strictly off the hot path.

The quality question nothing answered before this module: the round
solve is certified exact *for the instance it was given*, but the
CLUSTER drifts between and around those instances — place-only
placements go stale as load moves, express repairs promise only
optimal-within-hysteresis, aggregation/top-k are stated
approximations, and deferred migrations park improvements behind the
churn budget. The correction pass's implicit promise ("any remaining
per-pod gap is under the hysteresis") was never a measured number.

``ShadowAuditor`` makes it one. On a sampled cadence the bridge
captures a host snapshot of the live cluster (machines, tasks, the
KnowledgeBase pricing aggregates — the flight-recorder capture style:
list/array copies on the driver thread, no device traffic) and a
background worker re-solves it from scratch:

- build a REBALANCING-mode graph over the snapshot's RUNNING tasks
  (every one gets its continuation arc at the bridge's own
  hysteresis, so the audit measures exactly the promise the
  correction pass makes). Pending pods are deliberately OUT of the
  audit instance: their story is the wait-age distribution
  (obs/lifecycle.py) and the per-pod unscheduled diagnosis
  (obs/explain.py) — folding them in would make regret oscillate
  with the aging-pressure lag between rounds (a parked pod's
  unsched price rises every round, so the state decided under LAST
  round's prices always trails an optimum priced with this round's)
  instead of measuring placement quality;
- price it with the registry cost model pinned to the **CPU
  backend** (the service lane's TenantSolver idiom) — the audit
  thread never dispatches to the accelerator, so it cannot contend
  with an in-flight round between dispatch and fetch;
- solve it exactly on the subprocess oracle via the host DIMACS path
  (``oracle.solve_dimacs`` — no ``FlowNetwork``, no jax arrays);
- price the STATUS QUO (every task where it actually is,
  ``transport.assignment_cost``) over the same instance.

Published per audit (``poseidon_audit_*`` gauges + the SLO engine's
``regret`` source):

- **regret** = status-quo cost − certified optimum: bit-zero on a
  settled steady state, measurably positive the moment drift /
  aggregation / express repair / budget deferral has cost anything
  beyond the stated hysteresis bound;
- **drift pods**: placements that differ from the audit optimum
  (informational — ties make this noisier than regret);
- **fragmentation index**: per machine-SKU class, the largest
  schedulable gang slot (max free seats on any single machine of the
  class) — the "could a k-gang still land anywhere" capacity surface;
- audit wall time and failure count.

Thread discipline (PTA004/PTA006, declared in analysis/contracts.py):
the capture runs on the driver thread and hands the immutable
snapshot through a bounded ``queue.Queue``; results and counters are
written under ``_lock`` on the worker and read under it from the
driver/scrape side. The capture helper is a PTA001 hot scope (no
device syncs) — like the checkpoint capture it is deliberately NOT an
O(churn) scope: the amortized-cadence O(cluster) list copy is its
documented design (bench config 14 pins the amortized cost <2% of a
churned-warm round).
"""

from __future__ import annotations

import dataclasses
import logging
import queue
import threading
import time

import numpy as np

log = logging.getLogger(__name__)

# default sampling cadence (rounds between captures); the driver
# overrides via --audit_every
AUDIT_EVERY_DEFAULT = 16

# bounded SKU-class label cardinality for the fragmentation gauge:
# classes beyond this fold into "other" (a heterogeneous fleet has a
# handful of SKUs; a metrics label must not scale with machine count)
MAX_SKU_CLASSES = 8


@dataclasses.dataclass
class AuditSnapshot:
    """One sampled capture of the live cluster (driver thread; every
    field is an owned copy — the worker never touches bridge state)."""

    round_num: int
    cost_model: str
    hysteresis: int
    machines: list                 # Machine dataclasses (immutable)
    tasks: list                    # Task dataclasses (immutable)
    # KnowledgeBase aggregates in snapshot order (uids/names below)
    uids: list
    names: list
    task_usage: np.ndarray
    machine_load: np.ndarray
    machine_mem_free: np.ndarray


@dataclasses.dataclass
class AuditResult:
    """One completed audit."""

    round_num: int
    status_quo_cost: int = 0
    optimal_cost: int = 0
    regret: int = 0
    drift_pods: int = 0
    frag_slots: dict = dataclasses.field(default_factory=dict)
    audit_ms: float = 0.0
    error: str = ""


class ShadowAuditor:
    """Sampled background re-solve of the live placement's quality.

    ``background=False`` (tests, bench determinism) skips the worker
    thread; ``run_pending()`` then processes captures inline.
    """

    def __init__(
        self,
        *,
        metrics=None,
        sample_every: int = AUDIT_EVERY_DEFAULT,
        background: bool = True,
        oracle_timeout_s: float = 120.0,
    ):
        self.metrics = metrics
        self.sample_every = max(int(sample_every), 1)
        self.oracle_timeout_s = oracle_timeout_s
        self._lock = threading.Lock()
        # bounded handoff: if the worker is still chewing on the last
        # snapshot, the next capture is simply skipped (counted) —
        # the audit is a sample, not a log
        self._q: queue.Queue[AuditSnapshot | None] = queue.Queue(
            maxsize=2
        )
        self.last: AuditResult | None = None
        self.completed = 0
        self.failures = 0
        self.skipped = 0
        # grow-only padding floors for the CPU pricing (worker-thread
        # private): without them every audit's slightly different
        # task/arc counts mint fresh compiled shapes on the CPU
        # backend — harmless to the round but a per-audit compile tax
        # and noise in any zero-recompile budget (bench config 14)
        self._t_floor = 16
        self._m_floor = 16
        self._e_floor = 256
        self._thread: threading.Thread | None = None
        if background:
            self._thread = threading.Thread(
                target=self._worker, name="shadow-audit", daemon=True
            )
            self._thread.start()

    # ---- the driver-thread side ----------------------------------------

    def prewarm(
        self, *, tasks: int, machines: int, arcs: int = 0
    ) -> None:
        """Pin the pricing-shape floors ahead of growth.

        The floors are grow-only either way; pinning them to the
        cluster's expected bounds up front means the worker's CPU
        pricing compiles ONE shape at the first sample instead of one
        per bucket crossing while a ramping cluster grows through
        them (benign background compiles, but noise in any
        zero-recompile budget — bench config 14 calls this before
        its measured window). ``arcs`` defaults to a generous
        rebalancing-mode estimate from the task/machine counts."""
        from poseidon_tpu.graph.network import pad_bucket

        if not arcs:
            arcs = tasks * 8 + machines * 4
        with self._lock:  # the worker grows the same floors
            self._t_floor = pad_bucket(
                max(tasks, 1), minimum=self._t_floor
            )
            self._m_floor = pad_bucket(
                max(machines, 1), minimum=self._m_floor
            )
            self._e_floor = pad_bucket(
                max(arcs, 1), minimum=self._e_floor
            )

    def due(self, round_num: int) -> bool:
        """Is this round a sample? (the bridge's cadence gate)."""
        return round_num % self.sample_every == 0

    def capture(
        self,
        *,
        round_num: int,
        cost_model: str,
        hysteresis: int,
        machines: dict,
        tasks: dict,
        knowledge,
    ) -> bool:
        """Snapshot the live cluster for the worker (driver thread —
        a PTA001 hot scope: list/array copies of host data only; the
        O(cluster) copy amortizes over the sampling cadence exactly
        like the checkpoint capture). Returns False when the worker is
        still busy with the previous sample (capture skipped)."""
        from poseidon_tpu.cluster import TaskPhase

        # the audit instance is the RUNNING placement (module
        # docstring: pending pods' story is wait-age + diagnosis)
        running = [
            t for t in tasks.values()
            if t.phase == TaskPhase.RUNNING and t.machine in machines
        ]
        if not running:
            return False
        uids = [t.uid for t in running]
        names = list(machines.keys())
        snap = AuditSnapshot(
            round_num=round_num,
            cost_model=cost_model,
            hysteresis=int(hysteresis),
            machines=list(machines.values()),
            tasks=running,
            uids=uids,
            names=names,
            task_usage=np.array(knowledge.task_cpu_usage(uids)),
            machine_load=np.array(knowledge.machine_load(names)),
            machine_mem_free=np.array(
                knowledge.machine_mem_free(names)
            ),
        )
        try:
            self._q.put_nowait(snap)
            return True
        except queue.Full:
            with self._lock:
                self.skipped += 1
            return False

    def stop(self) -> None:
        """Stop the worker (daemon close path) WITHOUT stalling:
        pending snapshots are discarded (a shutdown does not owe the
        queue an audit), and a worker stuck in a long oracle solve is
        abandoned to its daemon-thread fate after the join timeout —
        a blocking ``put`` on the bounded queue here could hold the
        SIGTERM path for a whole oracle timeout."""
        if self._thread is None:
            return
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        try:
            self._q.put_nowait(None)
        except queue.Full:
            pass  # worker mid-pop refilled nothing; it will re-block
        self._thread.join(timeout=10.0)
        self._thread = None

    def run_pending(self) -> AuditResult | None:
        """Synchronous mode: process every queued capture inline
        (tests/bench determinism; returns the last result)."""
        out = None
        while True:
            try:
                snap = self._q.get_nowait()
            except queue.Empty:
                return out
            if snap is not None:
                out = self._process(snap)

    # ---- the worker ----------------------------------------------------

    def _worker(self) -> None:  # pta: background-thread
        while True:
            snap = self._q.get()
            if snap is None:
                return
            self._process(snap)

    def _process(self, snap: AuditSnapshot) -> AuditResult:
        t0 = time.perf_counter()
        try:
            res = self._audit(snap)
        except Exception as e:  # never crash the daemon for an audit
            log.exception("shadow audit failed (round %d)",
                          snap.round_num)
            res = AuditResult(round_num=snap.round_num, error=str(e))
        res.audit_ms = (time.perf_counter() - t0) * 1000
        with self._lock:
            self.last = res
            if res.error:
                self.failures += 1
            else:
                self.completed += 1
        if self.metrics is not None:
            self.metrics.record_audit(res)
        return res

    def _audit(self, snap: AuditSnapshot) -> AuditResult:
        """The actual re-solve: host numpy + CPU-pinned pricing + the
        subprocess oracle. Never an accelerator dispatch."""
        from poseidon_tpu.cluster import ClusterState
        from poseidon_tpu.graph.builder import FlowGraphBuilder
        from poseidon_tpu.graph.decompose import extract_placements
        from poseidon_tpu.graph.dimacs import write_dimacs_host
        from poseidon_tpu.models.costs import build_cost_inputs_host
        from poseidon_tpu.oracle import solve_dimacs
        from poseidon_tpu.ops.transport import (
            assignment_cost,
            extract_topology,
            instance_from_topology,
        )

        cluster = ClusterState(
            machines=snap.machines, tasks=snap.tasks
        )
        # rebalancing-mode graph at the bridge's OWN hysteresis: the
        # audit measures the correction pass's stated promise, not a
        # stricter one it never made
        fb = FlowGraphBuilder(
            preemption=True, migration_hysteresis=snap.hysteresis
        )
        cols = fb.merge_columns(fb.extract_columns(cluster))
        arrays, meta = fb.assemble(cols)
        # the pricing aggregates, re-ordered from the snapshot's
        # capture order onto the build's canonical order
        usage = dict(zip(snap.uids, snap.task_usage))
        load = dict(zip(snap.names, snap.machine_load))
        memf = dict(zip(snap.names, snap.machine_mem_free))
        cur = cols.current_m
        used = (
            np.bincount(
                cur[cur >= 0], minlength=len(meta.machine_names)
            ).astype(np.int32)
            if cur is not None
            else np.zeros(len(meta.machine_names), np.int32)
        )
        from poseidon_tpu.graph.network import pad_bucket

        # pricing shapes ride grow-only bucketed floors (the solver's
        # anti-recompile idiom): the CPU backend compiles one variant
        # per bucket, not one per audit. The lock covers the race with
        # a driver-thread prewarm().
        with self._lock:
            self._e_floor = pad_bucket(
                meta.n_arcs, minimum=self._e_floor
            )
            self._t_floor = pad_bucket(
                len(meta.task_uids), minimum=self._t_floor
            )
            self._m_floor = pad_bucket(
                len(meta.machine_names), minimum=self._m_floor
            )
            e_floor, t_floor, m_floor = (
                self._e_floor, self._t_floor, self._m_floor
            )
        inputs = build_cost_inputs_host(
            e_floor, meta,
            t_min=t_floor,
            m_min=m_floor,
            task_cpu_milli=cols.cpu_milli,
            task_mem_kb=cols.mem_kb,
            task_usage=np.array(
                [usage[u] for u in meta.task_uids]
            ),
            machine_load=np.array(
                [load[n] for n in meta.machine_names]
            ),
            machine_mem_free=np.array(
                [memf[n] for n in meta.machine_names]
            ),
            machine_used_slots=used,
        )
        cost = _price_on_cpu(snap.cost_model, inputs, meta.n_arcs)
        topo = extract_topology(
            meta, arrays["src"], arrays["dst"], arrays["cap"]
        )
        inst = instance_from_topology(topo, cost)
        sq = assignment_cost(inst, meta.task_current)
        text = write_dimacs_host(
            arrays["src"], arrays["dst"], arrays["cap"], cost,
            arrays["supply"], meta.n_nodes, meta.n_arcs,
        )
        o = solve_dimacs(
            text, meta.n_arcs, algorithm="cost_scaling",
            timeout_s=self.oracle_timeout_s,
        )
        placements = extract_placements(
            np.asarray(o.flows, np.int64), meta,
            arrays["src"], arrays["dst"],
        )
        names = meta.machine_names
        drift = sum(
            1 for i, uid in enumerate(meta.task_uids)
            if int(meta.task_current[i]) >= 0
            and placements.get(uid)
            != names[int(meta.task_current[i])]
        )
        return AuditResult(
            round_num=snap.round_num,
            status_quo_cost=int(sq),
            optimal_cost=int(o.cost),
            regret=int(sq) - int(o.cost),
            drift_pods=int(drift),
            frag_slots=fragmentation_index(snap),
        )


def _price_on_cpu(
    cost_model: str, inputs, n_arcs: int
) -> np.ndarray:
    """Run the registry cost model with every operand pinned to the
    CPU backend (the TenantSolver idiom, service/dispatch.py): on a
    TPU host the audit's pricing math runs on host cores, never on the
    accelerator the round owns."""
    import jax

    from poseidon_tpu.models import get_cost_model

    try:
        cpu = jax.local_devices(backend="cpu")[0]
    except RuntimeError:  # no CPU backend registered: single-backend
        cpu = None
    dev_inputs = (
        jax.device_put(inputs, cpu)
        if cpu is not None else jax.device_put(inputs)
    )
    out = get_cost_model(cost_model)(dev_inputs)
    return np.asarray(jax.device_get(out), np.int32)[:n_arcs]


def fragmentation_index(snap: AuditSnapshot) -> dict[str, int]:
    """Largest schedulable gang slot per machine-SKU class.

    SKU class = (cpu capacity, memory capacity, max_tasks), labeled
    by CONTENT (``8c-16g-12s``) so the label's meaning can never be
    silently remapped by fleet churn (a positional ``sku0``/``sku1``
    scheme renumbers every class the moment a new SKU sorts first).
    The value is the MAX free seat count on any single machine of the
    class — the biggest all-on-one-machine gang that could still land
    there. Only the ``MAX_SKU_CLASSES`` most-populous classes keep
    their own label; the tail folds into ``"other"`` (label
    cardinality stays bounded on any fleet)."""
    used: dict[str, int] = {}
    for t in snap.tasks:
        if t.machine:
            used[t.machine] = used.get(t.machine, 0) + 1
    largest: dict[tuple, int] = {}
    members: dict[tuple, int] = {}
    for m in snap.machines:
        key = (m.cpu_capacity, m.memory_capacity_kb, m.max_tasks)
        free = max(int(m.max_tasks) - used.get(m.name, 0), 0)
        if free > largest.get(key, -1):
            largest[key] = free
        members[key] = members.get(key, 0) + 1
    keep = sorted(
        largest, key=lambda k: (-members[k], k)
    )[:MAX_SKU_CLASSES]
    out: dict[str, int] = {}
    for key in sorted(largest):
        cpu, mem_kb, slots = key
        label = (
            f"{cpu:g}c-{int(mem_kb) >> 20}g-{int(slots)}s"
            if key in keep else "other"
        )
        out[label] = max(out.get(label, 0), largest[key])
    return out
