"""Declarative SLO engine: objectives, burn rates, breach latching.

The pager question the metrics endpoint alone cannot answer: "is my
latency/quality objective burning error budget RIGHT NOW, fast enough
to care?". Scrape-side burn-rate alerting works, but every deployment
re-derives the same PromQL — and the quality observatory's own
sources (lifecycle e2c, shadow-audit regret) deserve first-class
objectives the daemon itself evaluates and traces.

**Objective grammar** (``--slo``, comma/repeat separated)::

    <source> <op> <threshold> [by <label>=<value> ...]
    <bool-source>

    e2b_p99_ms < 10 by lane=express     # express event-to-bind p99
    e2c_p95_ms < 5000 by lane=tick      # lifecycle event-to-confirmed
    round_p99_ms < 250                  # round host critical path
    regret == 0                         # shadow-audit placement regret
    ready                               # the /readyz latch holds

Histogram sources are ``<base>_p<NN>_ms``: the percentile IS the
error budget (``p99`` = 1% of observations may violate the threshold,
``p50`` = 50%, ``p999`` = 0.1%) — the standard reinterpretation of a
percentile objective as a good/bad-event ratio, which is what makes
multi-window burn rates well-defined. Thresholds snap DOWN to the
nearest histogram bucket edge (documented, deterministic; buckets are
fixed at registration). Gauge sources (``regret``, ``ready``,
``drift_pods``, and the failure-domain ladder's ``outage`` /
``overload`` — write ``outage == 0``: their healthy value is 0)
contribute one good/bad event per evaluation with a
``GAUGE_BUDGET`` (1%) budget. A ``by`` filter matches labelsets whose
matching keys agree; a key the instrument never carries matches all
samples (so ``e2b_p99_ms by lane=express`` reads naturally even
though the express histogram is single-lane by construction).

**Burn rate.** ``burn = (bad fraction in window) / budget`` over two
sliding windows measured in evaluations (one evaluation per completed
round — deterministic under test, cadence-proportional in
production): a short window (default 6) for detection speed and a
long window (default 60) for sustained-burn confirmation. The alert
goes ACTIVE when BOTH windows burn above ``burn_threshold`` (default
1.0 = "budget exhausts within the window"), and that transition emits
exactly one ``SLO_BREACH`` trace event + one
``poseidon_slo_breaches_total{slo}`` tick — latched until the short
window recovers, so a sustained breach pages once per breach window,
not once per round. Surfaces: ``poseidon_slo_healthy{slo}``,
``poseidon_slo_burn_rate{slo,window}``, ``poseidon_slo_value{slo}``,
the ``/slo`` endpoint (obs/server.py), and the ``trace report`` SLO
section.

``evaluate()`` runs on the driver thread once per round: histogram
snapshot deltas + a bounded deque of per-evaluation (good, total)
pairs per objective — host arithmetic only, cost pinned by bench
config 14 inside the observatory's <2% budget.
"""

from __future__ import annotations

import dataclasses
import logging
import math
import re
import threading

log = logging.getLogger(__name__)


def _json_value(v):
    """JSON-safe point value: a percentile beyond the top bucket is
    ``inf`` internally (the metrics renderer spells it ``+Inf``), but
    strict-JSON consumers of /slo and the trace get null instead of
    the non-standard ``Infinity`` token."""
    if v is None or not math.isfinite(v):
        return None
    return v

# histogram source vocabulary: friendly base -> registry family
HIST_SOURCES = {
    "e2b": "poseidon_express_e2b_ms",
    "e2c": "poseidon_pod_e2c_ms",
    "round": "poseidon_round_latency_ms",
}

# gauge source vocabulary: name -> (registry family, boolean?)
GAUGE_SOURCES = {
    "regret": ("poseidon_audit_regret", False),
    "ready": ("poseidon_ready", True),
    "drift_pods": ("poseidon_audit_drift_pods", False),
    # the failure-domain degradation ladder: 'outage == 0' /
    # 'overload == 0' alert on sustained degraded windows (non-bool:
    # the healthy value is 0, so the bare-name boolean default of
    # "== 1 is good" would invert them)
    "outage": ("poseidon_outage", False),
    "overload": ("poseidon_overload", False),
}

# error budget for gauge objectives (1 sample per evaluation): 1% of
# evaluations may violate before burn exceeds 1x
GAUGE_BUDGET = 0.01

SHORT_WINDOW_DEFAULT = 6
LONG_WINDOW_DEFAULT = 60
BURN_THRESHOLD_DEFAULT = 1.0

_OPS = {
    "<": lambda v, t: v < t,
    "<=": lambda v, t: v <= t,
    ">": lambda v, t: v > t,
    ">=": lambda v, t: v >= t,
    "==": lambda v, t: v == t,
    "!=": lambda v, t: v != t,
}

_COND_RE = re.compile(
    r"^\s*(?P<name>[A-Za-z_][\w]*)\s*"
    r"(?:(?P<op><=|>=|==|!=|<|>)\s*(?P<thr>-?\d+(?:\.\d+)?))?\s*$"
)
_HIST_RE = re.compile(r"^(?P<base>[a-z0-9]+)_p(?P<pct>\d+)_ms$")


class SloParseError(ValueError):
    """The objective spec does not parse (unknown source, bad op)."""


@dataclasses.dataclass(frozen=True)
class SloObjective:
    """One parsed objective."""

    spec: str                 # the normalized spec (the metric label)
    kind: str                 # "histogram" | "gauge"
    family: str               # registry instrument family
    op: str
    threshold: float
    budget: float             # allowed bad fraction
    labels: tuple             # ((k, v), ...) "by" filter


def parse_objective(spec: str) -> SloObjective:
    """Parse one objective spec (see the module docstring grammar)."""
    spec = " ".join(spec.split())
    cond, *by = spec.split(" by ")
    labels: list[tuple[str, str]] = []
    for clause in by:
        for part in clause.split():
            if "=" not in part:
                raise SloParseError(
                    f"bad 'by' clause {part!r} in {spec!r} "
                    f"(want label=value)"
                )
            k, v = part.split("=", 1)
            labels.append((k, v))
    m = _COND_RE.match(cond)
    if not m:
        raise SloParseError(f"cannot parse objective {spec!r}")
    name, op, thr = m.group("name"), m.group("op"), m.group("thr")
    hm = _HIST_RE.match(name)
    if hm and hm.group("base") in HIST_SOURCES:
        if op is None:
            raise SloParseError(
                f"histogram objective {name!r} needs '<op> "
                f"<threshold>' ({spec!r})"
            )
        if op not in ("<", "<="):
            # latency percentiles are upper-bound objectives; a '>'
            # objective would need the threshold snapped UP to stay
            # conservative, and cumulative buckets make that
            # half-broken — reject instead of under-counting burn
            raise SloParseError(
                f"histogram objective {spec!r}: only '<'/'<=' "
                f"thresholds are supported (latency percentiles are "
                f"upper bounds)"
            )
        pct_str = hm.group("pct")
        pct = int(pct_str)
        # p99 -> 0.99, p999 -> 0.999 (three+ digits read as 99.9);
        # a 3+-digit spelling with a trailing zero (p100, p950) is
        # ambiguous with its shorter form (p10, p95) and silently
        # guts the budget — reject it, like pct 0
        if pct == 0 or (len(pct_str) >= 3 and pct % 10 == 0):
            raise SloParseError(
                f"ambiguous percentile p{pct_str} in {spec!r}: "
                f"write p1..p99 or p999-style (no trailing zero)"
            )
        frac = pct / 100 if len(pct_str) <= 2 \
            else pct / 10 ** len(pct_str)
        budget = max(1.0 - frac, 1e-6)
        return SloObjective(
            spec=spec, kind="histogram",
            family=HIST_SOURCES[hm.group("base")],
            op=op, threshold=float(thr), budget=budget,
            labels=tuple(labels),
        )
    if name in GAUGE_SOURCES:
        family, is_bool = GAUGE_SOURCES[name]
        if op is None:
            if not is_bool:
                raise SloParseError(
                    f"gauge objective {name!r} needs '<op> "
                    f"<threshold>' ({spec!r})"
                )
            op, thr = "==", "1"
        return SloObjective(
            spec=spec, kind="gauge", family=family,
            op=op, threshold=float(thr), budget=GAUGE_BUDGET,
            labels=tuple(labels),
        )
    raise SloParseError(
        f"unknown SLO source {name!r} in {spec!r}; histogram bases: "
        f"{sorted(HIST_SOURCES)} (as <base>_pNN_ms), gauges: "
        f"{sorted(GAUGE_SOURCES)}"
    )


def _labels_match(key: tuple, want: tuple) -> bool:
    """A labelset matches when every 'by' key it CARRIES agrees; keys
    the instrument never mints match everything (documented)."""
    have = dict(key)
    return all(have.get(k, v) == v for k, v in want)


class _ObjectiveState:
    """Per-objective sliding windows + breach latch."""

    def __init__(self, obj: SloObjective, long_window: int):
        self.obj = obj
        # per-evaluation (good, total) deltas, newest last
        self.window: list[tuple[int, int]] = []
        self.long_window = long_window
        # histogram cumulative baseline from the previous evaluation:
        # {labelkey: (good_cum, total_cum)}
        self.prev: dict[tuple, tuple[int, int]] = {}
        self.active = False
        self.breaches = 0
        self.last_value: float | None = None

    def push(self, good: int, total: int) -> None:
        self.window.append((good, total))
        if len(self.window) > self.long_window:
            del self.window[: len(self.window) - self.long_window]

    def burn(self, n: int) -> float:
        tail = self.window[-n:]
        total = sum(t for _, t in tail)
        if total <= 0:
            return 0.0
        bad = total - sum(g for g, _ in tail)
        return (bad / total) / self.obj.budget


class SloEngine:
    """Evaluates declared objectives against the metrics registry.

    Driver-thread only (one ``evaluate()`` per completed round); the
    registry's own lock makes the snapshot reads safe against scrape
    threads. ``trace`` (a TraceGenerator) receives the SLO_BREACH
    events; ``metrics`` (SchedulerMetrics) the ``poseidon_slo_*``
    series.
    """

    def __init__(
        self,
        objectives: list[str] | list[SloObjective],
        *,
        metrics=None,
        trace=None,
        short_window: int = SHORT_WINDOW_DEFAULT,
        long_window: int = LONG_WINDOW_DEFAULT,
        burn_threshold: float = BURN_THRESHOLD_DEFAULT,
    ):
        self.metrics = metrics
        self.trace = trace
        self.short_window = max(int(short_window), 1)
        self.long_window = max(int(long_window), self.short_window)
        self.burn_threshold = float(burn_threshold)
        # evaluate() runs on the driver thread; status() serves the
        # obs server's handler threads — window state is read and
        # written under this lock (PTA004 discipline)
        self._lock = threading.Lock()
        self.states: list[_ObjectiveState] = []
        for spec in objectives:
            obj = (
                spec if isinstance(spec, SloObjective)
                else parse_objective(spec)
            )
            self._check_threshold(obj)
            self.states.append(_ObjectiveState(obj, self.long_window))
        self.evaluations = 0

    def _check_threshold(self, obj: SloObjective) -> None:
        """Reject a '<' histogram threshold below the family's
        smallest bucket edge: the documented snap-DOWN has no edge to
        snap to, and evaluating it would silently invert 'all good'
        into 'all bad' (a permanently-firing false breach)."""
        if obj.kind != "histogram" or obj.op not in ("<", "<="):
            return
        reg = self._registry()
        hist = reg._metrics.get(obj.family) if reg else None
        if hist is None:
            return  # family not registered: nothing to check against
        lo = min(hist.buckets)
        if obj.threshold < lo:
            raise SloParseError(
                f"objective {obj.spec!r}: threshold {obj.threshold:g} "
                f"is below {obj.family}'s smallest bucket edge "
                f"({lo:g}) — the threshold snaps down to a bucket "
                f"edge, so nothing could ever count as good"
            )

    # ---- source reads ---------------------------------------------------

    def _registry(self):
        return self.metrics.registry if self.metrics is not None \
            else None

    def _eval_histogram(self, st: _ObjectiveState) -> tuple[int, int]:
        reg = self._registry()
        hist = reg._metrics.get(st.obj.family) if reg else None
        if hist is None:
            return 0, 0
        snap = hist.snapshot()
        buckets = hist.buckets
        # snap the threshold DOWN to a bucket edge: counts at le <=
        # threshold are provably-good observations
        bi = -1
        for i, le in enumerate(buckets):
            if le <= st.obj.threshold:
                bi = i
        good = total = 0
        values = []
        for key, (counts, _sum, n) in snap.items():
            if not _labels_match(key, st.obj.labels):
                continue
            # '<'/'<=' only (parse_objective rejects the rest):
            # good = observations at or under the snapped edge
            g = counts[bi] if bi >= 0 else 0
            pg, pt = st.prev.get(key, (0, 0))
            good += g - pg
            total += n - pt
            st.prev[key] = (g, n)
            values.append((counts, n))
        # display value: the objective's percentile over the
        # whole-life histogram (bucket upper-edge estimate)
        st.last_value = _percentile_estimate(
            values, buckets, 1.0 - st.obj.budget
        )
        return max(good, 0), max(total, 0)

    def _eval_gauge(self, st: _ObjectiveState) -> tuple[int, int]:
        reg = self._registry()
        gauge = reg._metrics.get(st.obj.family) if reg else None
        if gauge is None:
            return 0, 0
        vals = [
            v for key, v in gauge.snapshot().items()
            if _labels_match(key, st.obj.labels)
        ]
        if not vals:
            return 0, 0
        ok = all(
            _OPS[st.obj.op](v, st.obj.threshold) for v in vals
        )
        st.last_value = vals[0] if len(vals) == 1 else max(vals)
        return (1, 1) if ok else (0, 1)

    # ---- the per-round evaluation --------------------------------------

    def evaluate(self, round_num: int = 0) -> None:
        """One evaluation tick (driver thread, once per completed
        round): refresh windows, update burn rates, latch breaches."""
        with self._lock:
            self.evaluations += 1
        for st in self.states:
            with self._lock:
                good, total = (
                    self._eval_histogram(st)
                    if st.obj.kind == "histogram"
                    else self._eval_gauge(st)
                )
                st.push(good, total)
                short = st.burn(self.short_window)
                long_ = st.burn(self.long_window)
                breaching = (
                    short > self.burn_threshold
                    and long_ > self.burn_threshold
                )
                fired = False
                if breaching and not st.active:
                    # the once-per-breach-window edge: latched until
                    # the short window recovers
                    st.active = True
                    st.breaches += 1
                    fired = True
                elif st.active and short <= self.burn_threshold:
                    st.active = False
                healthy = not st.active
                value = st.last_value
            if fired:
                log.warning(
                    "SLO breach: %s (burn short=%.2f long=%.2f)",
                    st.obj.spec, short, long_,
                )
                if self.trace is not None:
                    self.trace.emit(
                        "SLO_BREACH", round_num=round_num,
                        detail={
                            "slo": st.obj.spec,
                            "burn_short": round(short, 3),
                            "burn_long": round(long_, 3),
                            "value": _json_value(value),
                        },
                    )
                    self.trace.flush()
            if self.metrics is not None:
                self.metrics.record_slo(
                    st.obj.spec, healthy=healthy,
                    burn_short=short, burn_long=long_,
                    value=value, breached=fired,
                )

    # ---- the /slo endpoint's data model --------------------------------

    def status(self) -> dict:  # pta: background-thread
        """JSON-able evaluation state (the ``/slo`` endpoint body and
        the smoke test's assertion surface); served from the obs
        server's handler threads under the engine lock."""
        with self._lock:
            return {
                "evaluations": self.evaluations,
                "short_window": self.short_window,
                "long_window": self.long_window,
                "burn_threshold": self.burn_threshold,
                "objectives": [
                    {
                        "spec": st.obj.spec,
                        "kind": st.obj.kind,
                        "family": st.obj.family,
                        "budget": st.obj.budget,
                        "healthy": not st.active,
                        "burn_short": round(
                            st.burn(self.short_window), 4
                        ),
                        "burn_long": round(
                            st.burn(self.long_window), 4
                        ),
                        "breaches": st.breaches,
                        "value": _json_value(st.last_value),
                    }
                    for st in self.states
                ],
            }


def _percentile_estimate(values, buckets, q: float) -> float | None:
    """Bucket-edge percentile estimate over summed labelsets (display
    only — the burn math uses exact bucket counts)."""
    if not values:
        return None
    total = sum(n for _, n in values)
    if total <= 0:
        return None
    acc = [0] * len(buckets)
    for counts, _n in values:
        for i, c in enumerate(counts):
            acc[i] += c
    want = q * total
    for i, c in enumerate(acc):
        if c >= want:
            return float(buckets[i])
    return float("inf")
