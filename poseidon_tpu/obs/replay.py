"""Deterministic offline replay of flight-recorder dumps.

``python -m poseidon_tpu.obs.replay <dump.json>`` reconstructs every
recorded round/express batch from the dump, re-runs the REAL solve
path (``ops/resident.ResidentSolver`` — the same compiled chain, the
same certificates, the same degrade routing) offline, and asserts
bit-identity with the recorded assignment and cost. A mismatch is
REPORTED as a divergence (per record: what differed and how), never an
assert crash — a doctored or cross-version dump yields a readable
diff and exit code 1.

Fidelity mechanics:

- each round record carries the solver's grow-only padding floors and
  (when clean) a host mirror of the warm state the solve started from
  (``RoundRecord.pad_floors`` / ``warm_seed``, both riding the round's
  ONE fetch on the live path) — the replay seeds both, so the replayed
  round runs the exact compiled program from the exact starting state;
- express batches are replayed through ``express_round`` against the
  replayed round's own on-HBM context, reproducing the inter-round
  warm-state mutations deterministically — a subsequent round whose
  warm seed was express-dirty (``warm_seed=None``) chains off that
  replayed state;
- sharded rounds (``mesh_width=N``) replay on the recorded mesh when
  the host has the devices, else on the plain single-device layout —
  bit-identical either way (the scale lane's own pinned invariant,
  tests/test_scale.py).

``--explain <uid>`` additionally runs the explainer
(``obs/explain.py``) against the LAST replayed round — term breakdown,
runner-up margin, and (for unscheduled pods) the diagnosis + minimal
relaxation.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys

import numpy as np

from poseidon_tpu.obs.flightrec import (
    ExpressRecord,
    RoundRecord,
    load_dump,
)

_SOLVER_FLAG_DEFAULTS = {
    "mesh_width": 0,
    "aggregate_classes": False,
    "topk_prefs": 0,
    "express_lane": False,
    "express_max_batch": 16,
    "small_to_oracle": True,
}


@dataclasses.dataclass
class RecordReplay:
    """One record's replay verdict."""

    kind: str
    round_num: int
    ok: bool | None          # None = nothing recorded to compare
    backend: str = ""
    cost: int | None = None
    divergence: dict | None = None
    note: str = ""


def _build_solver(flags: dict, notes: list[str]):
    import jax

    from poseidon_tpu.ops.resident import ResidentSolver

    f = {**_SOLVER_FLAG_DEFAULTS, **(flags or {})}
    mesh = int(f["mesh_width"] or 0)
    if mesh and mesh > jax.device_count():
        notes.append(
            f"recorded mesh_width={mesh} > {jax.device_count()} local "
            f"device(s); replaying on the plain layout (bit-identical "
            f"by the scale lane's pinned invariant)"
        )
        mesh = 0
    return ResidentSolver(
        mesh_width=mesh,
        aggregate_classes=bool(f["aggregate_classes"]),
        topk_prefs=int(f["topk_prefs"] or 0),
        express_lane=bool(f["express_lane"]),
        express_max_batch=int(f["express_max_batch"] or 16),
        small_to_oracle=bool(f["small_to_oracle"]),
    )


def _replay_round(solver, rec: RoundRecord) -> tuple:
    """(RecordReplay, outcome) for one round record."""
    if not rec.warm_used:
        # the live round ran cold (first round / floors reset):
        # drop any chained replay state so the variant matches
        solver.reset()
    solver.restore_for_replay(rec.pad_floors or None, rec.warm_seed)
    outcome = solver.run_round(
        rec.arrays, rec.meta,
        cost_model=rec.cost_model,
        cost_input_kwargs={
            k: v for k, v in rec.cost_kwargs.items() if v is not None
        },
    )
    rr = RecordReplay(
        kind="round", round_num=rec.round_num, ok=None,
        backend=outcome.backend, cost=outcome.cost,
    )
    if rec.result is None:
        rr.note = (
            "no recorded result (round was abandoned live); replay "
            "solved it"
        )
        return rr, outcome
    div = {}
    rec_asg = np.asarray(rec.result["assignment"], np.int64)
    got_asg = np.asarray(outcome.assignment, np.int64)
    if rec_asg.shape != got_asg.shape:
        div["assignment"] = (
            f"shape {rec_asg.shape} recorded vs {got_asg.shape} "
            f"replayed"
        )
    elif not np.array_equal(rec_asg, got_asg):
        bad = np.flatnonzero(rec_asg != got_asg)
        div["assignment"] = {
            "differing_tasks": int(bad.size),
            "first": {
                "uid": rec.meta.task_uids[int(bad[0])],
                "recorded": int(rec_asg[bad[0]]),
                "replayed": int(got_asg[bad[0]]),
            },
        }
    if int(rec.result["cost"]) != int(outcome.cost):
        div["cost"] = {
            "recorded": int(rec.result["cost"]),
            "replayed": int(outcome.cost),
        }
    if rec.result.get("backend", "") != outcome.backend:
        # informational unless the numbers diverged too: the same
        # instance can legitimately route differently on a host with
        # a different HBM budget / missing oracle
        rr.note = (
            f"backend differs: recorded "
            f"{rec.result.get('backend')} vs replayed "
            f"{outcome.backend}"
        )
    rr.ok = not div
    rr.divergence = div or None
    return rr, outcome


def _replay_express(solver, rec: ExpressRecord) -> RecordReplay:
    from poseidon_tpu.ops.resident import ExpressArrival, ExpressBatch

    batch = ExpressBatch(
        arrivals=[
            ExpressArrival(
                uid=a["uid"],
                wait_rounds=int(a["wait_rounds"]),
                cpu_milli=int(a["cpu_milli"]),
                mem_kb=int(a["mem_kb"]),
                prefs=tuple(tuple(p) for p in a["prefs"]),
            )
            for a in rec.arrivals
        ],
        retires=[tuple(r) for r in rec.retires],
        removals=list(rec.removals),
        slot_deltas=[tuple(s) for s in rec.slot_deltas],
    )
    outcome = solver.express_round(batch)
    rr = RecordReplay(
        kind="express", round_num=rec.round_num, ok=None,
        backend="express" if outcome.ok
        else f"express-degrade:{outcome.reason}",
        cost=outcome.cost if outcome.ok else None,
    )
    if rec.result is None:
        rr.note = "no recorded outcome; replay ran the batch"
        return rr
    div = {}
    if bool(rec.result.get("ok")) != outcome.ok:
        div["ok"] = {
            "recorded": bool(rec.result.get("ok")),
            "replayed": outcome.ok,
            "replayed_reason": outcome.reason,
        }
    elif outcome.ok:
        want = sorted(
            (str(u), str(m)) for u, m in rec.result["placements"]
        )
        got = sorted(
            (str(u), str(m)) for u, m in outcome.placements
        )
        if want != got:
            div["placements"] = {"recorded": want, "replayed": got}
        if int(rec.result["cost"]) != int(outcome.cost):
            div["cost"] = {
                "recorded": int(rec.result["cost"]),
                "replayed": int(outcome.cost),
            }
    rr.ok = not div
    rr.divergence = div or None
    return rr


def replay_dump(
    dump: dict, *, explain_uid: str = ""
) -> dict:
    """Replay every record in order through ONE solver; returns the
    report data model (JSON-able)."""
    records = dump["records"]
    notes: list[str] = []
    first_round = next(
        (r for r in records if r.kind == "round"), None
    )
    if first_round is None:
        return {
            "identical": None,
            "notes": ["dump contains no round records"],
            "records": [],
        }
    solver = _build_solver(first_round.flags, notes)
    out: list[RecordReplay] = []
    last_round_rec = None
    last_outcome = None
    for rec in records:
        if rec.kind == "round":
            rr, outcome = _replay_round(solver, rec)
            last_round_rec, last_outcome = rec, outcome
        else:
            rr = _replay_express(solver, rec)
        out.append(rr)
    compared = [r for r in out if r.ok is not None]
    report = {
        "identical": all(r.ok for r in compared) if compared else None,
        "compared": len(compared),
        "notes": notes,
        "records": [dataclasses.asdict(r) for r in out],
    }
    if explain_uid and last_round_rec is not None:
        report["explain"] = _explain_replayed(
            last_round_rec, last_outcome, explain_uid
        )
    return report


def _explain_replayed(rec: RoundRecord, outcome, uid: str) -> dict:
    """Run the explainer against the REPLAYED round (not the recorded
    numbers): the whole point of replay is trusting the offline
    re-derivation."""
    from poseidon_tpu.graph.deltas import extract_deltas
    from poseidon_tpu.obs.explain import (
        ExplainError,
        RoundExplainer,
        render_explanation,
    )

    dset = extract_deltas(
        rec.meta, outcome.assignment,
        max_migrations=(
            int(rec.flags.get("max_migrations_per_round", 0))
            if rec.flags.get("enable_preemption") else 0
        ),
        task_cost=outcome.task_cost,
        task_margin=outcome.task_margin,
    )
    try:
        ex = RoundExplainer(
            meta=rec.meta,
            arrays=rec.arrays,
            cost_model=rec.cost_model,
            cost_kwargs=rec.cost_kwargs,
            assignment=outcome.assignment,
            flags=rec.flags,
            unscheduled=tuple(dset.unscheduled),
            deferred=tuple(d.task for d in dset.deferred),
        )
        expl = ex.explain(uid)
    except ExplainError as e:
        # a typo'd / long-retired uid must yield a readable line, not
        # a traceback after the whole replay already ran
        return {
            "rendered": f"explain {uid}: {e}",
            "error": str(e),
        }
    return {
        "rendered": render_explanation(expl),
        "explanation": dataclasses.asdict(expl),
    }


def render_report(report: dict) -> str:
    out = ["== poseidon-tpu flight replay =="]
    for n in report.get("notes", ()):
        out.append(f"note: {n}")
    for r in report["records"]:
        tag = {True: "BIT-IDENTICAL", False: "DIVERGED",
               None: "(nothing recorded)"}[r["ok"]]
        line = (
            f"r{r['round_num']:>5} {r['kind']:<8} {tag}"
            f"  backend={r['backend']}"
        )
        if r["cost"] is not None:
            line += f" cost={r['cost']}"
        out.append(line)
        if r["note"]:
            out.append(f"        {r['note']}")
        if r["divergence"]:
            out.append(
                "        divergence: "
                + json.dumps(r["divergence"], default=str)
            )
    verdict = report["identical"]
    out.append(
        "verdict: "
        + ("all compared records bit-identical" if verdict
           else "nothing to compare" if verdict is None
           else "DIVERGENCE — recorded run is not reproducible from "
                "this dump on this host")
    )
    if "explain" in report:
        out.append("")
        out.append(report["explain"]["rendered"])
    return "\n".join(out)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m poseidon_tpu.obs.replay",
        description="replay a flight-recorder dump offline and assert "
                    "bit-identity with the recorded rounds",
    )
    p.add_argument("dump", help="dump manifest (.json) or .npz path")
    p.add_argument("--explain", default="", metavar="UID",
                   help="also explain one uid against the replayed "
                        "last round")
    p.add_argument("--json", action="store_true",
                   help="emit the raw report data model as JSON")
    args = p.parse_args(argv)
    try:
        dump = load_dump(args.dump)
    except (OSError, ValueError, KeyError) as e:
        print(f"cannot load dump: {e}", file=sys.stderr)
        return 2
    report = replay_dump(dump, explain_uid=args.explain)
    if args.json:
        print(json.dumps(report, indent=2, default=str))
    else:
        print(render_report(report))
    return 0 if report["identical"] in (True, None) else 1


if __name__ == "__main__":
    sys.exit(main())
