"""The explainer: per-decision cost attribution + unscheduled-pod
diagnosis.

Answers the two questions the on-call actually asks (ISSUE 12):

- **"why did pod X land on machine Y"** — for any decided uid,
  decompose the chosen route's cost into the cost model's NAMED terms
  (locality, load, wait-aging, hysteresis discount, preemption
  penalty, fixed channel fees; ``models/costs.py::arc_cost_terms``)
  such that the terms provably sum to the solver's exact int64 arc
  cost, and report the runner-up alternative and its margin;
- **"why is pod Z still unscheduled"** — a machine-checkable diagnosis
  from the closed vocabulary {``priced-out``, ``capacity-exhausted``,
  ``pref-pruned``, ``churn-budget-deferred``}, plus the MINIMAL
  relaxation (unsched-cost slack, seat count, pref rank, or churn
  budget) that would place the pod — and ``validate()`` re-solves the
  round with that relaxation applied to PROVE the pod places.

The explainer works over one round's full host-side inputs — exactly
what the flight recorder captures (``obs/flightrec.py::RoundRecord``),
so it serves both the live daemon (``--explain`` against the last
captured round) and the offline replay harness (``--explain`` against
a replayed dump). Everything here is offline/on-demand analysis: it
recomputes the priced arc table host-side with the same registry model
the solve ran (bit-identical — the models are elementwise integer/
float32 chains with no reassociation), never touches the hot path, and
cross-checks itself against the decision log's device-fetched costs in
``tests/test_explain.py``.

Route vocabulary: a decision's cost is the sum of the arc costs along
its chosen channel (task->unsched->sink | task->cluster->machine->sink
| pref arc (+ rack hop) ->machine->sink), mirroring the dense solver's
``_finalize`` channel selection including its tie-breaks (cluster wins
ties, earlier pref columns win later ones).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from poseidon_tpu.graph.aggregate import prune_topology_prefs
from poseidon_tpu.graph.builder import GraphMeta
from poseidon_tpu.models.costs import (
    arc_cost_terms,
    build_cost_inputs_host,
    resolve_cost_model_name,
)
from poseidon_tpu.ops.transport import (
    INF,
    TransportInstance,
    extract_topology,
    instance_from_topology,
)

DIAGNOSES = (
    "priced-out",            # parking beat every seat-available route
    "capacity-exhausted",    # affordable routes exist, seats do not
    "pref-pruned",           # top-k dropped the winning preference arc
    "churn-budget-deferred", # the decision lost the migration budget
    "anomaly",               # the inputs say it SHOULD have placed —
                             # replay/divergence material, not a state
)


class ExplainError(ValueError):
    """The uid/round cannot be explained (unknown uid, missing data)."""


@dataclasses.dataclass
class DecisionExplanation:
    """One decided uid, fully attributed."""

    uid: str
    kind: str                # PLACE | MIGRATE | PREEMPT | NOOP | UNSCHEDULED
    machine: str             # chosen machine ("" for unscheduled)
    from_machine: str        # current machine ("" for pending)
    channel: str             # "cluster" | "pref[k]" | "unsched"
    cost: int                # exact route cost (sums from terms)
    terms: dict              # {term_name: int} — nonzero terms only
    runner_up: str           # next-best alternative ("" = none finite)
    runner_up_cost: int | None
    margin: int | None       # runner_up_cost - cost (negative =
                             # capacity forced a worse-than-best seat)
    diagnosis: str = ""      # one of DIAGNOSES (unscheduled only)
    relaxation: dict | None = None  # the minimal change that places it


class RoundExplainer:
    """Attribution + diagnosis over one round's captured inputs.

    Construct via ``from_record`` (a flight-recorder ``RoundRecord``,
    live or loaded from a dump). The assignment is the round's final
    base-machine assignment (post class-expansion)."""

    def __init__(
        self,
        *,
        meta: GraphMeta,
        arrays: dict,
        cost_model: str,
        cost_kwargs: dict | None = None,
        assignment: np.ndarray,
        flags: dict | None = None,
        unscheduled: tuple = (),
        deferred: tuple = (),
    ):
        self.meta = meta
        self.cost_model = resolve_cost_model_name(cost_model)
        self.assignment = np.asarray(assignment, np.int64)
        self.flags = dict(flags or {})
        self.unscheduled = set(unscheduled)
        self.deferred = list(deferred)
        self._uid_idx = {u: i for i, u in enumerate(meta.task_uids)}
        full_topo = extract_topology(
            meta, arrays["src"], arrays["dst"], arrays["cap"]
        )
        topk = int(self.flags.get("topk_prefs", 0) or 0)
        self.topk = topk
        self.full_topo = full_topo
        self.topo = (
            prune_topology_prefs(
                full_topo, meta.arc_weight, meta.arc_discount, topk
            )
            if topk else full_topo
        )
        # the priced arc table + its term decomposition, host-side,
        # with the SAME registry model/inputs construction the solver
        # priced with (arc_cost_terms asserts terms sum to the model)
        inputs = build_cost_inputs_host(
            meta.n_arcs, meta, **{
                k: v for k, v in (cost_kwargs or {}).items()
                if v is not None
            },
        )
        self.terms = {
            k: np.asarray(v, np.int64)[: meta.n_arcs]
            for k, v in arc_cost_terms(self.cost_model, inputs).items()
        }
        # the priced arc table IS the term sum: arc_cost_terms already
        # verified the terms sum bit-exactly to the registry model's
        # output, so summing here avoids pricing the table a second
        # time (and a second device round-trip) per explainer
        cost = np.zeros(meta.n_arcs, np.int64)
        for v in self.terms.values():
            cost += v
        self.cost = cost
        self.inst = instance_from_topology(self.topo, self.cost)
        self.inst_full = (
            instance_from_topology(full_topo, self.cost)
            if topk else self.inst
        )
        # seats left after THIS round's assignment (base machines)
        occ = np.bincount(
            self.assignment[self.assignment >= 0],
            minlength=self.inst.n_machines,
        )
        self.free = np.asarray(self.topo.slots, np.int64) - occ

    @classmethod
    def from_record(cls, rec) -> "RoundExplainer":
        """Build from a flight-recorder ``RoundRecord`` (live ring or
        loaded dump). The record must carry a finished result."""
        if rec is None or rec.result is None:
            raise ExplainError(
                "no finished round record to explain (the flight "
                "recorder captures results at finish_round)"
            )
        return cls(
            meta=rec.meta,
            arrays=rec.arrays,
            cost_model=rec.cost_model,
            cost_kwargs=rec.cost_kwargs,
            assignment=rec.result["assignment"],
            flags=rec.flags,
            unscheduled=tuple(rec.result.get("unscheduled", ())),
            deferred=tuple(rec.result.get("deferred", ())),
        )

    # ---- per-task route machinery --------------------------------------

    def _tidx(self, uid: str) -> int:
        try:
            return self._uid_idx[uid]
        except KeyError:
            raise ExplainError(
                f"uid {uid!r} is not a task of this round"
            ) from None

    def _route(self, t: int, m: int, inst: TransportInstance):
        """(cost, channel_code, arc_list) of the cheapest channel from
        task t to machine m — the host mirror of ``_finalize``'s
        selection, tie-breaks included."""
        topo = self.topo if inst is self.inst else self.full_topo
        best = int(inst.w[t] + inst.d[m])
        ch = "cluster"
        arcs = [
            int(topo.arc_cluster[t]), int(topo.arc_c2m[m]),
            int(topo.arc_m2s[m]),
        ]
        for k in range(inst.max_prefs):
            pm = int(inst.pref_machine[t, k])
            pr = int(inst.pref_rack[t, k])
            pc = inst.pref_cost[t, k]
            if pc >= INF:
                continue
            if pm == m:
                val = int(pc)
                cand = [int(topo.arc_pref[t, k]), int(topo.arc_m2s[m])]
            elif pr >= 0 and pr == int(inst.rack_of[m]) \
                    and inst.ra[m] < INF:
                val = int(pc + inst.ra[m])
                cand = [
                    int(topo.arc_pref[t, k]), int(topo.arc_r2m[m]),
                    int(topo.arc_m2s[m]),
                ]
            else:
                continue
            if val < best:
                best, ch, arcs = val, f"pref[{k}]", cand
        return best, ch, arcs

    def _row(self, t: int, inst: TransportInstance) -> np.ndarray:
        """Route cost from task t to EVERY machine (int64[M]; INF =
        unreachable). Vectorized; one task at a time (offline)."""
        row = inst.w[t] + inst.d
        for k in range(inst.max_prefs):
            pm = int(inst.pref_machine[t, k])
            pr = int(inst.pref_rack[t, k])
            pc = inst.pref_cost[t, k]
            if pc >= INF:
                continue
            if pm >= 0:
                row = row.copy()
                row[pm] = min(row[pm], int(pc))
            elif pr >= 0:
                hit = inst.rack_of == pr
                row = np.minimum(
                    row, np.where(hit, pc + inst.ra, INF)
                )
        return np.minimum(row, INF)

    # ---- the decision side ---------------------------------------------

    def explain(self, uid: str) -> DecisionExplanation:
        """Attribute one decided uid: chosen route, exact term
        breakdown (sums to the solver's arc cost), runner-up +
        margin; unscheduled pods additionally get their diagnosis."""
        t = self._tidx(uid)
        asg = int(self.assignment[t])
        cur = int(self.meta.task_current[t])
        names = self.meta.machine_names
        if asg < 0:
            return self._explain_unscheduled(uid, t, cur)
        cost, channel, arcs = self._route(t, asg, self.inst)
        terms = self._sum_terms(arcs)
        row = self._row(t, self.inst)
        masked = row.copy()
        masked[asg] = INF
        alt_m = int(masked.min(initial=INF))
        u = int(self.inst.u[t])
        if alt_m <= u:
            ru_cost, ru = alt_m, names[int(masked.argmin())]
        else:
            ru_cost, ru = u, "unscheduled"
        if ru_cost >= INF:
            ru, ru_cost, margin = "", None, None
        else:
            margin = ru_cost - cost
        if cur >= 0:
            kind = "NOOP" if asg == cur else "MIGRATE"
        else:
            kind = "PLACE"
        expl = DecisionExplanation(
            uid=uid, kind=kind, machine=names[asg],
            from_machine=names[cur] if cur >= 0 else "",
            channel=channel, cost=cost, terms=terms,
            runner_up=ru, runner_up_cost=ru_cost, margin=margin,
        )
        if uid in self.deferred:
            # the solver DECIDED this move but the churn budget
            # deferred its actuation: the pod is still where it was
            expl.diagnosis, expl.relaxation = self._diagnose(
                uid, t, row, u
            )
        return expl

    def _sum_terms(self, arcs: list[int]) -> dict:
        out = {}
        for name, vec in self.terms.items():
            v = int(sum(int(vec[a]) for a in arcs))
            if v != 0:
                out[name] = v
        return out

    # ---- the unscheduled side ------------------------------------------

    def _explain_unscheduled(
        self, uid: str, t: int, cur: int
    ) -> DecisionExplanation:
        topo_u = self.topo
        u_arcs = [int(topo_u.arc_unsched[t]), int(topo_u.arc_u2s[t])]
        u = int(self.inst.u[t])
        terms = self._sum_terms(u_arcs)
        row = self._row(t, self.inst)
        alt = int(row.min(initial=INF))
        ru = (
            self.meta.machine_names[int(row.argmin())]
            if alt < INF else ""
        )
        diagnosis, relaxation = self._diagnose(uid, t, row, u)
        kind = "PREEMPT" if cur >= 0 else "UNSCHEDULED"
        return DecisionExplanation(
            uid=uid, kind=kind, machine="",
            from_machine=(
                self.meta.machine_names[cur] if cur >= 0 else ""
            ),
            channel="unsched", cost=u, terms=terms,
            runner_up=ru,
            runner_up_cost=alt if alt < INF else None,
            margin=(alt - u) if alt < INF else None,
            diagnosis=diagnosis, relaxation=relaxation,
        )

    def _diagnose(self, uid: str, t: int, row: np.ndarray, u: int):
        """One reason from DIAGNOSES + the minimal relaxation that
        places the pod (validated by ``validate``'s re-solve)."""
        if uid in self.deferred:
            # the decision existed but lost the per-round churn
            # budget: granting (position+1) budget slots actuates it
            return "churn-budget-deferred", {
                "kind": "churn-budget",
                "max_migrations_per_round":
                    self.deferred.index(uid) + 1 + int(
                        self.flags.get("max_migrations_per_round", 0)
                    ),
            }
        free = self.free
        affordable = row < u
        if bool((affordable & (free > 0)).any()):
            # a strictly-cheaper seat sat free and the solver parked
            # the pod anyway: that contradicts exactness — this is
            # replay/divergence material, not a cluster state
            return "anomaly", None
        if self.topk:
            row_full = self._row(t, self.inst_full)
            win = (row_full < u) & (free > 0)
            if bool(win.any()):
                m = int(np.where(win, row_full, INF).argmin())
                return "pref-pruned", {
                    "kind": "restore-prefs",
                    "machine": self.meta.machine_names[m],
                    "topk_prefs": self._pref_rank(t, m),
                }
        if bool(affordable.any()):
            # affordable machines exist but every one is out of seats
            m = int(np.where(affordable, row, INF).argmin())
            return "capacity-exhausted", {
                "kind": "add-seats",
                "machine": self.meta.machine_names[m],
                "seats": 1,
            }
        feasible_free = (row < INF) & (free > 0)
        if bool(feasible_free.any()):
            best = int(np.where(feasible_free, row, INF).min())
            m = int(np.where(feasible_free, row, INF).argmin())
            return "priced-out", {
                "kind": "unsched-slack",
                "machine": self.meta.machine_names[m],
                "slack": best - u + 1,
            }
        # no free seat anywhere AND no affordable route: seats first,
        # plus the slack that makes the freed seat worth taking
        feasible = row < INF
        if not bool(feasible.any()):
            return "capacity-exhausted", None  # unreachable entirely
        m = int(np.where(feasible, row, INF).argmin())
        return "capacity-exhausted", {
            "kind": "add-seats",
            "machine": self.meta.machine_names[m],
            "seats": 1,
            "slack": max(int(row[m]) - u + 1, 0),
        }

    def _pref_rank(self, t: int, m: int) -> int:
        """How many prefs (by the pruner's heaviest-first order) must
        be kept for task t's pref on machine m to survive — the
        minimal ``--topk_prefs``."""
        topo = self.full_topo
        ap = topo.arc_pref[t]
        w = np.where(
            ap >= 0,
            self.meta.arc_weight[np.maximum(ap, 0)].astype(np.int64),
            -1,
        )
        order = np.argsort(-w, kind="stable")
        for rank, k in enumerate(order):
            pm = int(topo.pref_machine[t, k])
            pr = int(topo.pref_rack[t, k])
            if pm == m or (
                pr >= 0 and pr == int(topo.rack_of[m])
            ):
                return rank + 1
        return int((ap >= 0).sum())

    # ---- relaxation validation (the machine-checkable part) ------------

    def validate(self, expl: DecisionExplanation) -> dict:
        """Apply the explanation's minimal relaxation and RE-SOLVE the
        round offline; returns {"ok": bool, "placed_on": name, ...}.
        For ``churn-budget-deferred`` the re-check is the delta
        extractor with the relaxed budget (the decision actuates); for
        the others the dense solver must place the pod. A diagnosis
        whose relaxation does not place the pod is a bug — tests
        assert ok for every fuzzed unscheduled pod."""
        from poseidon_tpu.graph.deltas import extract_deltas

        if expl.relaxation is None:
            return {"ok": False, "why": "no relaxation"}
        t = self._tidx(expl.uid)
        r = expl.relaxation
        if r["kind"] == "churn-budget":
            dset = extract_deltas(
                self.meta, self.assignment,
                max_migrations=r["max_migrations_per_round"],
            )
            granted = {
                d.task for d in
                dset.place + dset.migrate + dset.preempt
            }
            return {
                "ok": expl.uid in granted,
                "budget": r["max_migrations_per_round"],
            }
        inst = self.inst
        if r["kind"] == "restore-prefs":
            inst = self.inst_full
        u2 = np.array(inst.u, np.int64)
        if r.get("slack"):
            u2 = u2.copy()
            u2[t] += int(r["slack"])
        slots2 = np.array(inst.slots, np.int32)
        seats = int(r.get("seats", 0))
        midx = (
            self.meta.machine_names.index(r["machine"])
            if "machine" in r else -1
        )
        placed_on, seats_used = "", seats
        # seats may need to grow past 1 when other unscheduled pods
        # outbid this one for the freed seat: search upward, bounded
        # by the unscheduled population (each extra seat places at
        # least one of them ahead of this pod)
        for extra in range(max(seats, 0), len(self.unscheduled) + 1):
            s = slots2
            if midx >= 0 and extra:
                s = slots2.copy()
                s[midx] += extra
            res = self._resolve(
                dataclasses.replace(inst, u=u2, slots=s)
            )
            if int(res.assignment[t]) >= 0:
                placed_on = self.meta.machine_names[
                    int(res.assignment[t])
                ]
                seats_used = extra
                break
            if r["kind"] != "add-seats":
                break  # slack/pref relaxations are one-shot checks
        out = {"ok": bool(placed_on), "placed_on": placed_on}
        if r["kind"] == "add-seats":
            out["seats"] = seats_used
        return out

    @staticmethod
    def _resolve(inst: TransportInstance):
        from poseidon_tpu.ops.dense_auction import (
            solve_transport_dense,
        )

        res, _ = solve_transport_dense(inst)
        if not res.converged:
            raise ExplainError(
                "relaxation re-solve did not certify; cannot validate"
            )
        return res


def render_explanation(expl: DecisionExplanation) -> str:
    """The operator-facing transcript (cli --explain / replay
    --explain)."""
    out = [f"== explain {expl.uid} =="]
    if expl.kind == "UNSCHEDULED":
        out.append("decision: UNSCHEDULED (parked, aging)")
    elif expl.kind == "PREEMPT":
        out.append(
            f"decision: PREEMPT off {expl.from_machine} (parked)"
        )
    elif expl.kind == "MIGRATE":
        out.append(
            f"decision: MIGRATE {expl.from_machine} -> "
            f"{expl.machine} via {expl.channel}"
        )
    else:
        out.append(
            f"decision: {expl.kind} -> {expl.machine} "
            f"via {expl.channel}"
        )
    out.append(f"cost: {expl.cost}")
    width = max((len(k) for k in expl.terms), default=4)
    for name, v in sorted(
        expl.terms.items(), key=lambda kv: -abs(kv[1])
    ):
        out.append(f"  {name:<{width}}  {v:+d}")
    out.append(f"  {'=':<{width}}  {expl.cost:+d} (sums exactly)")
    if expl.runner_up:
        out.append(
            f"runner-up: {expl.runner_up} at {expl.runner_up_cost} "
            f"(margin {expl.margin:+d})"
        )
    else:
        out.append("runner-up: none (no finite alternative)")
    if expl.diagnosis:
        out.append(f"diagnosis: {expl.diagnosis}")
        if expl.relaxation:
            out.append(
                "minimal relaxation: "
                + ", ".join(
                    f"{k}={v}" for k, v in expl.relaxation.items()
                    if k != "kind"
                )
                + f" ({expl.relaxation['kind']})"
            )
    return "\n".join(out)
