"""PTA010: lock-order deadlock detection + no-blocking-under-lock.

PRs 13-15 grew the daemon from one lock to a dozen — checkpoint
writer, journal, outbox pump, auditor queue, SLO engine, metrics
registry, health latch — each guarding its own class, most of them
touched from both the driver thread and a background thread. Two
whole-program hazards come with that, and nothing verified either
statically until this pass:

1. **Lock-order cycles.** If thread A acquires L1 then L2 while
   thread B acquires L2 then L1, the daemon deadlocks the first time
   the interleaving lands — and a flow-scheduler daemon that stops
   scheduling is strictly worse than one that crashes (the node-failure
   storm tests of PR 15 exist precisely because liveness IS the
   product). This pass records the held-set at every acquisition site
   (``with self._lock:`` / ``with stream._lock:`` through the same
   cross-class type inference PTA006 uses, plus explicit
   ``.acquire()`` calls on lock-named attributes), closes the
   call graph so a lock taken three frames below a ``with`` still
   counts, builds the acquisition-order digraph over (class, attr)
   lock nodes, and reports every strongly-connected component of two
   or more nodes — and every self-edge, because ``threading.Lock`` is
   non-reentrant, so re-acquiring the lock you hold deadlocks a single
   thread with no second party needed (the repo deliberately has no
   RLock: "unknown lock hold times" is exactly the disease this pass
   treats).

2. **Blocking under a lock.** A lock region that performs a blocking
   operation — ``fsync``, a socket round-trip, ``queue.put`` with
   ``block=True``, ``thread.join``, ``time.sleep``, a solver dispatch
   — stalls every thread contending for that lock for the operation's
   full latency. The journal's fsync can take tens of milliseconds on
   a loaded disk; holding the journal lock across it would freeze the
   POST pool's ``_mark`` calls for exactly that long. The vocabulary
   of blocking terminal names lives in
   ``Contracts.blocking_call_names``; two shapes are recognized
   structurally because a name list cannot express them:

   - ``x.join()`` with **zero positional arguments** is a thread
     join (``",".join(parts)`` and ``os.path.join(a, b)`` carry
     positional args and never match; ``t.join(timeout=2.0)``, being
     keyword-only, still matches — a bounded join under a lock still
     stalls contenders for the full timeout);
   - ``q.put(...)`` without ``block=False`` is a blocking enqueue
     (``put_nowait`` and ``put(x, block=False)`` are fine).

   ``.wait()`` is deliberately NOT in the vocabulary:
   ``Condition.wait`` *releases* the underlying lock while waiting —
   flagging it would indict the one pattern that is actually correct
   under a lock. Plain ``.write()``/``.flush()`` are also exempt:
   buffered writes under a lock are how the journal orders its
   records; it is the *barrier* (fsync) that must leave the region.

Both analyses share one method-summary fixpoint: every method's
direct acquisitions, blocking calls, and intra/cross-class callees
(``self.m()``, ``typed_obj.m()``) are collected with the held-set
*inside* the method, then call sites lift callee effects into the
caller under the union of both held-sets until nothing changes. A
blocking call is reported once, at its own site, naming every lock
that can be held when it runs; nested defs and lambdas reset the
held-set (their bodies run later, not under the enclosing ``with``).

Known limitations (deliberate, mirroring PTA006): locks reached
through untyped aliases get file-scoped nodes (sound for blocking
detection — any lock is a lock — but two unresolved aliases of one
lock are two graph nodes, so a cycle through an alias can be missed);
``Lock()`` objects passed as bare function arguments are invisible;
executor-pool submission is not treated as a call edge.
"""

from __future__ import annotations

import ast
import dataclasses

from poseidon_tpu.analysis.core import (
    RepoContext,
    Violation,
    files_enforcing,
    repo_rule,
)
from poseidon_tpu.analysis.threads import (
    _collect_classes,
    _local_types,
    _terminal_name,
)

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)

# Attribute names treated as locks when they appear as a ``with``
# context manager: anything lock-ish, plus conditions (entering a
# Condition acquires its underlying lock).
_LOCKISH = ("lock", "cond", "mutex")


def _is_lock_attr(attr: str) -> bool:
    a = attr.lower()
    return any(tok in a for tok in _LOCKISH)


@dataclasses.dataclass(frozen=True)
class _Node:
    """One lock in the acquisition-order graph: (owner, attr).

    ``owner`` is a class name when the base object resolves through
    the thread model's type inference (``self`` / a typed local),
    otherwise ``<file>::<name>`` so unrelated unresolved bases never
    collapse into one node.
    """

    owner: str
    attr: str

    def label(self) -> str:
        return f"{self.owner}.{self.attr}"


@dataclasses.dataclass(frozen=True)
class _Site:
    path: str
    line: int
    col: int
    where: str   # "Class.method"


@dataclasses.dataclass
class _Summary:
    """Per-method effects, with the held-set internal to the method."""

    # (held frozenset[_Node], acquired _Node, _Site)
    acqs: set = dataclasses.field(default_factory=set)
    # (held frozenset[_Node], kind str, _Site)
    blocks: set = dataclasses.field(default_factory=set)
    # (held frozenset[_Node], (class, method))
    calls: set = dataclasses.field(default_factory=set)


def _blocking_kind(call: ast.Call, vocab: frozenset) -> str | None:
    """Why this call blocks, or None."""
    name = _terminal_name(call.func)
    if name in vocab:
        return name
    if not isinstance(call.func, ast.Attribute):
        return None
    if call.func.attr == "join" and not call.args:
        # zero positional args: thread join, not str/path join
        return "join"
    if call.func.attr == "put":
        for kw in call.keywords:
            if kw.arg == "block" and \
                    isinstance(kw.value, ast.Constant) and \
                    kw.value.value is False:
                return None
        return "put"
    return None


def _summarize_method(
    rel: str,
    cls_name: str,
    fn: ast.AST,
    classes: dict,
):
    """Returns (summary, rec) — the caller drives ``rec`` over the
    method body so module-level code could reuse the walker later."""
    info = classes.get(cls_name)
    self_name = None
    if fn.args.args:
        self_name = fn.args.args[0].arg
    ltypes = _local_types(fn, set(classes), self_name, info)
    summ = _Summary()

    def owner_of(base: ast.AST) -> str | None:
        if isinstance(base, ast.Name):
            if base.id == self_name:
                return cls_name
            if base.id in ltypes:
                return ltypes[base.id]
            return f"{rel}::{base.id}"
        return None

    def lock_node(expr: ast.AST) -> _Node | None:
        """``<base>.<lockish-attr>`` -> a graph node."""
        if isinstance(expr, ast.Attribute) and _is_lock_attr(expr.attr):
            owner = owner_of(expr.value)
            if owner is not None:
                return _Node(owner, expr.attr)
        return None

    where = f"{cls_name}.{getattr(fn, 'name', '<lambda>')}"

    def site(n: ast.AST) -> _Site:
        return _Site(rel, n.lineno, n.col_offset, where)

    def rec(n: ast.AST, held: frozenset, vocab: frozenset) -> None:
        if isinstance(n, (ast.With, ast.AsyncWith)):
            cur = held
            for item in n.items:
                rec(item.context_expr, cur, vocab)
                node = lock_node(item.context_expr)
                if node is not None:
                    summ.acqs.add((cur, node, site(item.context_expr)))
                    cur = cur | {node}
            for stmt in n.body:
                rec(stmt, cur, vocab)
            return
        if isinstance(n, ast.Call):
            # explicit .acquire() on a lock-named attribute is an
            # acquisition event (held-set unknown past this statement,
            # so it contributes order edges but opens no region)
            if isinstance(n.func, ast.Attribute) and \
                    n.func.attr == "acquire":
                node = lock_node(n.func.value)
                if node is not None:
                    summ.acqs.add((held, node, site(n)))
            kind = _blocking_kind(n, vocab)
            if kind is not None:
                summ.blocks.add((held, kind, site(n)))
            # call edges: self.m() and typed_obj.m()
            if isinstance(n.func, ast.Attribute) and \
                    isinstance(n.func.value, ast.Name):
                base, meth = n.func.value.id, n.func.attr
                target = None
                if base == self_name and info is not None and \
                        meth in info.methods:
                    target = (cls_name, meth)
                elif base in ltypes and \
                        meth in classes[ltypes[base]].methods:
                    target = (ltypes[base], meth)
                if target is not None:
                    summ.calls.add((held, target))
            for child in ast.iter_child_nodes(n):
                rec(child, held, vocab)
            return
        if isinstance(n, _FUNC_NODES + (ast.Lambda,)) and n is not fn:
            # a nested def/lambda runs later, not under this lock
            for child in ast.iter_child_nodes(n):
                rec(child, frozenset(), vocab)
            return
        for child in ast.iter_child_nodes(n):
            rec(child, held, vocab)

    return summ, rec


def _build_summaries(
    repo: RepoContext, files: dict, classes: dict
) -> dict:
    vocab = frozenset(repo.contracts.blocking_call_names)
    summaries: dict[tuple[str, str], _Summary] = {}
    for cls_name, info in classes.items():
        for mname, fn in info.methods.items():
            summ, rec = _summarize_method(
                info.path, cls_name, fn, classes
            )
            for stmt in fn.body:
                rec(stmt, frozenset(), vocab)
            summaries[(cls_name, mname)] = summ
    return summaries


def _close_summaries(summaries: dict) -> None:
    """Lift callee effects into callers until fixpoint. Monotone over
    finite sets of (held, payload) pairs, so this terminates; the cap
    is a backstop against pathological call chains."""
    for _ in range(32):
        changed = False
        for summ in summaries.values():
            for held, target in list(summ.calls):
                callee = summaries.get(target)
                if callee is None:
                    continue
                for h2, node, s in callee.acqs:
                    eff = (held | h2, node, s)
                    if eff not in summ.acqs:
                        summ.acqs.add(eff)
                        changed = True
                for h2, kind, s in callee.blocks:
                    eff = (held | h2, kind, s)
                    if eff not in summ.blocks:
                        summ.blocks.add(eff)
                        changed = True
        if not changed:
            return


def _cycles(edges: dict) -> list[list[_Node]]:
    """SCCs of size >= 2, plus self-loop nodes, as node lists."""
    index: dict[_Node, int] = {}
    low: dict[_Node, int] = {}
    on_stack: set[_Node] = set()
    stack: list[_Node] = []
    counter = [0]
    out: list[list[_Node]] = []
    nodes = set(edges)
    for targets in edges.values():
        nodes.update(targets)

    def strong(v: _Node) -> None:
        # iterative Tarjan (the lock graph is tiny, but recursion
        # depth should not depend on analyzed-repo shape)
        work = [(v, iter(sorted(edges.get(v, {}),
                                key=lambda n: (n.owner, n.attr))))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(
                        edges.get(w, {}),
                        key=lambda n: (n.owner, n.attr)))))
                    advanced = True
                    break
                if w in on_stack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                scc = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.append(w)
                    if w is node:
                        break
                if len(scc) >= 2 or (
                    len(scc) == 1 and scc[0] in edges.get(scc[0], {})
                ):
                    out.append(sorted(
                        scc, key=lambda n: (n.owner, n.attr)
                    ))

    for v in sorted(nodes, key=lambda n: (n.owner, n.attr)):
        if v not in index:
            strong(v)
    return out


@repo_rule("PTA010", "lock-order")
def lock_order(repo: RepoContext) -> list[Violation]:
    files = files_enforcing(repo, "PTA010")
    if not files:
        return []
    classes = _collect_classes(repo, files)
    summaries = _build_summaries(repo, files, classes)
    _close_summaries(summaries)

    out: list[Violation] = []

    # ---- no blocking under a lock ------------------------------------
    # report each blocking site once, naming every lock that can be
    # held when it runs (direct region or any calling chain)
    by_site: dict[_Site, tuple[str, set]] = {}
    for summ in summaries.values():
        for held, kind, s in summ.blocks:
            if not held:
                continue
            kind0, locks = by_site.setdefault(s, (kind, set()))
            locks.update(held)
    for s in sorted(by_site, key=lambda s: (s.path, s.line, s.col)):
        kind, locks = by_site[s]
        names = ", ".join(sorted(n.label() for n in locks))
        out.append(Violation(
            code="PTA010", rule="lock-order",
            path=s.path, line=s.line, col=s.col,
            message=(
                f"blocking call '{kind}' in {s.where} runs while "
                f"holding {names} — every thread contending for the "
                "lock stalls for the call's full latency; move the "
                "call outside the lock region (snapshot under the "
                "lock, block after release) or add a reasoned "
                "'# noqa: PTA010 -- why' if the lock MUST cover it"
            ),
        ))

    # ---- acquisition-order cycles ------------------------------------
    # edge held-lock -> acquired-lock, keeping one witness site per
    # edge (the earliest in file order, for a stable report)
    edges: dict[_Node, dict[_Node, _Site]] = {}
    for summ in summaries.values():
        for held, node, s in summ.acqs:
            for h in held:
                tgt = edges.setdefault(h, {})
                prev = tgt.get(node)
                if prev is None or (s.path, s.line) < \
                        (prev.path, prev.line):
                    tgt[node] = s
    for scc in _cycles(edges):
        # describe the cycle through its witness edges
        parts = []
        anchor: _Site | None = None
        scc_set = set(scc)
        for a in scc:
            for b, s in sorted(
                edges.get(a, {}).items(),
                key=lambda kv: (kv[0].owner, kv[0].attr),
            ):
                if b in scc_set and (len(scc) > 1 or a == b):
                    parts.append(
                        f"{a.label()} -> {b.label()} "
                        f"(in {s.where} at {s.path}:{s.line})"
                    )
                    if anchor is None or (s.path, s.line) < \
                            (anchor.path, anchor.line):
                        anchor = s
        if anchor is None:
            continue
        out.append(Violation(
            code="PTA010", rule="lock-order",
            path=anchor.path, line=anchor.line, col=anchor.col,
            message=(
                "lock acquisition-order cycle (deadlock): "
                + "; ".join(parts)
                + " — two threads taking these locks in opposite "
                "order deadlock on the first bad interleaving "
                "(and a self-edge deadlocks a single thread: "
                "threading.Lock is non-reentrant); pick one global "
                "order and acquire in it everywhere"
            ),
        ))

    out.sort(key=lambda v: (v.path, v.line, v.col))
    return out
