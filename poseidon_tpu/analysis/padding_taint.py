"""PTA009: padding-mask dataflow audit over the traced kernels.

PTA008 pins WHAT the compiled programs contain (primitive census,
consts, dtypes); this pass checks HOW values flow through them. Every
real exactness bug this repo has shipped and then caught — the padded-
row leaks the bit-identity fuzzers found, the express-lane cost
regressions — was the same shape: a PADDED lane (a row beyond the
t/m/p grow-only floors, a ``-1`` index sentinel, a zero-slot machine;
the contract declared at ``ops/resident.py`` ``DenseTopology``)
escaping into a cross-axis reduction without a dominating mask. The
reduction then folds garbage — a model-priced zero pod, an INF that
was supposed to be masked, a stale level — into a scalar the whole
round trusts.

The analysis is a forward taint pass over the closed jaxprs of the
production kernels (the same traces PTA008 audits):

- **sources**: every array-rank kernel input is padding-tainted —
  by the pad contract every table carries lanes beyond the true
  t/m/p extents (scalars like ``n_tasks`` / epoch counters are
  clean); iota/literal-derived index math stays clean;
- **propagation**: elementwise ops, gathers, scatters, sorts,
  cumsums, slices — any tainted input taints the outputs; nested
  ``pjit``/``scan``/``while``/``cond`` bodies are entered with the
  call-site taint (carries run to a fixpoint);
- **sinks**: a cross-axis reduction (``reduce_min/max/sum/prod``,
  ``argmin/argmax``, ``reduce_and/or``) folding a padding-tainted
  operand fires unless the mask DOMINATES the fold: the operand is
  the output of ``select_n`` (``jnp.where``'s lowering) or ``clamp``,
  reached through dtype/layout-transparent ops only
  (``convert_element_type``, ``reshape``, ``broadcast_in_dim``, ...).
  This is exactly the repo's established fold idiom —
  ``finmax``/``finmin``/``gat`` in ``ops/resident.py`` apply
  ``jnp.where`` INSIDE the reduction call. A mask applied further
  upstream does NOT count: the express-lane bug this pass exists to
  catch was a fold over model output that WAS where-masked upstream —
  on the wrong axis (arc validity, not arrival-slot validity). Mask
  at the fold, or sanction the site. Counting folds over bool masks
  (``jnp.sum(report)``) are exempt — mask algebra is how padding
  predicates are BUILT — but ``reduce_and/or`` over an unmasked
  tainted mask still fire (an unmasked ``jnp.all`` is how a padded
  row poisons a convergence certificate).

Reductions that are safe by CONSTRUCTION rather than by a visible
mask (e.g. ``_task_options`` folding ``dev.c`` columns the builder
already filled with INF) are sanctioned in
``Contracts.kernel_mask_contracts`` — one reasoned entry per
(kernel, primitive, function). The sanction list is verified live in
both directions, the same discipline as the PTA006 handoff allowlist:
an entry no current trace exercises is reported as STALE.

The acceptance tests keep the pass honest the way PR 10 did —
reverting the real ``_express_step`` arrival-lane mask must fire
PTA009.
"""

from __future__ import annotations

import pathlib

import numpy as np

from poseidon_tpu.analysis.core import Violation

# cross-axis folds: these collapse lanes, so a padded lane reaching
# one unmasked contaminates the scalar/row the whole kernel trusts
_ARITH_SINKS = frozenset({
    "reduce_min", "reduce_max", "reduce_sum", "reduce_prod",
    "argmin", "argmax",
})
_BOOL_SINKS = frozenset({"reduce_and", "reduce_or"})
_REDUCE_SINKS = _ARITH_SINKS | _BOOL_SINKS

# dominating-mask producers
_MASK_PRIMS = frozenset({"select_n", "clamp"})

# ops transparent to mask domination: they change dtype/layout, never
# lane contents, so a select_n stays dominating through them
_TRANSPARENT = frozenset({
    "convert_element_type", "reshape", "squeeze", "broadcast_in_dim",
    "transpose", "copy", "stop_gradient",
})

# higher-order primitives whose bodies are entered positionally
# (pjit/closed_call: body invars mirror the eqn invars)
_CALL_PRIMS = frozenset({
    "pjit", "closed_call", "core_call", "remat2", "checkpoint",
    "custom_jvp_call", "custom_vjp_call",
})


def _is_literal(atom) -> bool:
    # jax Literals carry .val; Vars don't (duck-typed across versions)
    return hasattr(atom, "val")


def _is_bool_var(var) -> bool:
    aval = getattr(var, "aval", None)
    dtype = getattr(aval, "dtype", None)
    if dtype is None:
        return False
    try:
        return np.dtype(dtype) == np.bool_
    except TypeError:
        return False


def _rank(var) -> int:
    aval = getattr(var, "aval", None)
    return len(getattr(aval, "shape", ()) or ())


def _user_frame(eqn):
    """(file_name, function_name, line) of the trace-time user frame,
    best-effort across jax versions."""
    try:
        from jax._src import source_info_util

        frame = source_info_util.user_frame(eqn.source_info)
    except Exception:
        frame = None
    if frame is None:
        return None, None, 0
    return (
        getattr(frame, "file_name", None),
        getattr(frame, "function_name", None),
        int(getattr(frame, "start_line", 0) or 0),
    )


class _Candidate:
    """One unmasked tainted reduction site (pre-sanction)."""

    __slots__ = ("kernel", "primitive", "function", "file", "line")

    def __init__(self, kernel, primitive, function, file, line):
        self.kernel = kernel
        self.primitive = primitive
        self.function = function
        self.file = file
        self.line = line

    def key(self):
        return (self.kernel, self.primitive, self.function, self.line)


class _State:
    """(tainted, masked, boolish) per var. ``tainted`` grows
    monotonically, ``masked`` (select_n-dominated through transparent
    ops) shrinks — both converge under the carry fixpoints.
    ``boolish`` marks a bool value or its dtype-converted image
    (``jnp.sum(mask, dtype=...)`` converts before reducing — the
    counting exemption must survive that)."""

    __slots__ = ("taint", "masked", "boolish")

    def __init__(self):
        self.taint: dict = {}
        self.masked: dict = {}
        self.boolish: dict = {}

    def get(self, atom) -> tuple[bool, bool, bool]:
        if _is_literal(atom):
            return False, True, False  # a literal is trivially safe
        return (self.taint.get(atom, False),
                self.masked.get(atom, False),
                self.boolish.get(atom, False))

    def put(self, var, tainted: bool, masked: bool,
            boolish: bool) -> None:
        self.taint[var] = bool(tainted)
        self.masked[var] = bool(masked)
        self.boolish[var] = bool(boolish) or _is_bool_var(var)


def _run_jaxpr(jaxpr, in_flags, kernel, out):
    """Forward (taint, masked) pass over one open jaxpr given per-
    invar flags; returns outvar flags. ``out`` is the shared candidate
    dict keyed for dedup (fixpoint re-runs re-report the same
    sites)."""
    st = _State()
    for v, (t, m, b) in zip(jaxpr.invars, in_flags):
        st.put(v, t, m, b)
    for v in jaxpr.constvars:
        st.put(v, False, False, False)

    _merge = lambda a, c: (a[0] or c[0], a[1] and c[1], a[2] and c[2])

    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        ins = [st.get(a) for a in eqn.invars]
        any_taint = any(t for t, _, _ in ins)
        params = eqn.params
        out_flags: list[tuple[bool, bool, bool]] | None = None

        if name in _CALL_PRIMS:
            closed = params.get("jaxpr") or params.get("call_jaxpr")
            if closed is not None and hasattr(closed, "jaxpr"):
                body = closed.jaxpr
                mapped = ins[: len(body.invars)]
                mapped += [(False, False, False)] * (
                    len(body.invars) - len(mapped)
                )
                outs = _run_jaxpr(body, mapped, kernel, out)
                out_flags = list(outs[: len(eqn.outvars)])
        elif name == "scan":
            closed = params.get("jaxpr")
            if closed is not None:
                body = closed.jaxpr
                nc = int(params.get("num_consts", 0))
                ncar = int(params.get("num_carry", 0))
                consts = ins[:nc]
                carry = list(ins[nc:nc + ncar])
                xs = ins[nc + ncar:]
                # fixpoint: taint only grows, masked only shrinks
                for _ in range(2 * ncar + 1):
                    outs = _run_jaxpr(
                        body, consts + carry + xs, kernel, out
                    )
                    new = [
                        _merge(a, c)
                        for a, c in zip(carry, outs[:ncar])
                    ]
                    if new == carry:
                        break
                    carry = new
                out_flags = list(carry) + list(outs[ncar:])
        elif name == "while":
            cond_c = params.get("cond_jaxpr")
            body_c = params.get("body_jaxpr")
            if cond_c is not None and body_c is not None:
                cn = int(params.get("cond_nconsts", 0))
                bn = int(params.get("body_nconsts", 0))
                cc = ins[:cn]
                bc = ins[cn:cn + bn]
                carry = list(ins[cn + bn:])
                for _ in range(2 * len(carry) + 1):
                    _run_jaxpr(cond_c.jaxpr, cc + carry, kernel, out)
                    outs = _run_jaxpr(
                        body_c.jaxpr, bc + carry, kernel, out
                    )
                    new = [_merge(a, c) for a, c in zip(carry, outs)]
                    if new == carry:
                        break
                    carry = new
                out_flags = list(carry)
        elif name == "cond":
            branches = params.get("branches") or ()
            if branches:
                ops = ins[1:]  # invars[0] is the branch index
                acc = [(False, True, True)] * len(eqn.outvars)
                for br in branches:
                    outs = _run_jaxpr(br.jaxpr, ops, kernel, out)
                    acc = [_merge(a, c) for a, c in zip(acc, outs)]
                out_flags = acc

        if name in _REDUCE_SINKS:
            axes = params.get("axes", ())
            cross_axis = axes is None or len(tuple(axes)) > 0
            unmasked_taint = any(
                t and not m and (name in _BOOL_SINKS or not b)
                for (t, m, b), a in zip(ins, eqn.invars)
                if not _is_literal(a)
            )
            if cross_axis and unmasked_taint:
                fname, func, line = _user_frame(eqn)
                cand = _Candidate(kernel, name, func, fname, line)
                out.setdefault(cand.key(), cand)

        if out_flags is None:
            if name in _MASK_PRIMS:
                # the fold-dominating mask forms; taint stops here
                # from the sinks' point of view
                out_flags = [
                    (any_taint, True, False)
                ] * len(eqn.outvars)
            elif name in _TRANSPARENT:
                out_flags = [
                    ins[0] if ins else (False, False, False)
                ] * len(eqn.outvars)
            else:
                out_flags = [
                    (any_taint, False, False)
                ] * len(eqn.outvars)

        for v, (t, m, b) in zip(eqn.outvars, out_flags):
            st.put(v, t, m, b)

    return [st.get(v) for v in jaxpr.outvars]


def analyze_kernel(name: str, closed) -> list[_Candidate]:
    """All unmasked tainted reductions in one closed jaxpr. Sources:
    every array-rank kernel input (the pad contract: all tables carry
    padded lanes); scalars and closure consts are clean."""
    out: dict = {}
    in_flags = [
        (_rank(v) >= 1, False, _is_bool_var(v))
        for v in closed.jaxpr.invars
    ]
    _run_jaxpr(closed.jaxpr, in_flags, name, out)
    return sorted(
        out.values(),
        key=lambda c: (c.kernel, c.function or "", c.line,
                       c.primitive),
    )


# ---------------------------------------------------------------------------
# the audit entry point
# ---------------------------------------------------------------------------


def run_padding_audit(
    root: pathlib.Path, *, traces=None, contracts=None
) -> tuple[list[Violation], int]:
    """Run the taint pass over the production kernel set and reconcile
    against ``Contracts.kernel_mask_contracts``. Returns (violations,
    kernels audited). ``traces`` reuses an already-traced set (one
    trace drives PTA008 and PTA009)."""
    from poseidon_tpu.analysis.contracts import DEFAULT_CONTRACTS
    from poseidon_tpu.analysis.jaxpr_check import (
        trace_production_kernels,
    )

    if contracts is None:
        contracts = DEFAULT_CONTRACTS
    if traces is None:
        traces = trace_production_kernels()

    violations: list[Violation] = []
    # "*" sanctions every kernel tracing the site: the solve-family
    # internals (_task_options, auction_round, ...) appear in five of
    # the six traces — per-kernel entries would be sixfold noise
    sanctioned = {
        (kernel, prim, func): reason
        for kernel, entries in contracts.kernel_mask_contracts.items()
        for prim, func, reason in entries
    }
    used: set = set()

    root = pathlib.Path(root).resolve()
    for kernel in sorted(traces):
        for cand in analyze_kernel(kernel, traces[kernel]):
            skey = (kernel, cand.primitive, cand.function)
            wkey = ("*", cand.primitive, cand.function)
            hit = skey if skey in sanctioned else (
                wkey if wkey in sanctioned else None
            )
            if hit is not None:
                used.add(hit)
                continue
            path = "poseidon_tpu/analysis/kernel_fingerprints.json"
            line = 1
            if cand.file:
                p = pathlib.Path(cand.file)
                try:
                    path = p.resolve().relative_to(root).as_posix()
                except ValueError:
                    path = p.as_posix()
                line = cand.line or 1
            violations.append(Violation(
                code="PTA009", rule="padding-taint",
                path=path, line=line, col=0,
                message=(
                    f"{kernel}: {cand.primitive} in "
                    f"{cand.function or '<unknown>'} folds a padding-"
                    "tainted operand with no dominating mask — padded "
                    "lanes (rows beyond the t/m/p floors, -1 "
                    "sentinels, zero-slot machines) reach this "
                    "reduction unmasked; fold through jnp.where(valid,"
                    " x, <identity>) at the reduction, or add a "
                    "reasoned entry to Contracts.kernel_mask_contracts"
                ),
            ))

    # stale-sanction audit (the PTA006 handoff discipline): an entry
    # the current traces never exercise silently blesses the NEXT
    # unmasked reduction someone writes at that site
    for skey in sorted(set(sanctioned) - used,
                       key=lambda k: (k[0], k[2] or "", k[1])):
        kernel, prim, func = skey
        violations.append(Violation(
            code="PTA009", rule="padding-taint",
            path="poseidon_tpu/analysis/contracts.py", line=1, col=0,
            message=(
                f"stale kernel_mask_contracts entry: ({prim!r}, "
                f"{func!r}) in kernel {kernel!r} matches no tainted "
                "reduction in the current traces — the site was "
                "masked or removed; delete the entry"
            ),
        ))
    return violations, len(traces)
