"""Contract linter: whole-program analysis enforcing this repo's
performance and concurrency invariants.

The codebase *states* its contracts — rounds are O(churn), the resident
round is one fused program with exactly one host sync, the bridge is
single-threaded with documented cross-thread handoffs — but a contract
nobody checks is a comment. This package makes them machine-checked:

- ``python -m poseidon_tpu.analysis`` runs every registered rule over
  the shipped tree (``poseidon_tpu/``, ``bench.py``, ``scripts/``,
  and ``tests/`` under a narrowed per-rule scope) and exits non-zero
  on any violation; CI runs it as a blocking step (with
  ``--audit-suppressions``, so DEAD ``# noqa`` comments fail too).
- Rules are repo-specific, declared against ``contracts.py``. The
  file-local set (``rules.py``, PTA001-PTA005) covers host syncs,
  cluster loops, jit hygiene, marker-based lock discipline, and the
  trace/flag surface. The whole-program set goes further: PTA006
  (``threads.py``) builds a repo-wide thread model from the markers
  PLUS spawn-site inference and runs an Eraser-style lockset race
  check that VERIFIES the PTA004 handoff allowlist (stale entries are
  violations); PTA007 (``recompile.py``) is dataflow over static-arg
  and pad-shape provenance, catching the grow-only-floor recompile
  bug class PR 8 had to flush out at runtime.
- ``--jaxpr`` (``jaxpr_check.py``, PTA008) traces the production
  kernels on tiny shapes and audits their closed jaxprs: zero host
  callbacks, zero smuggled transfers/constants, no f64 leaks, and a
  pinned per-kernel primitive-count fingerprint
  (``kernel_fingerprints.json``) so a fusion break is a CI diff, not
  a perf regression three PRs later.
- Violations are suppressed inline with ``# noqa: PTA001 -- reason``;
  the reason is REQUIRED (a bare suppression is itself a violation,
  PTA000), the suppression covers its whole statement span, and the
  suppression audit reports entries whose rule no longer fires.

The static passes pair with runtime teeth in ``poseidon_tpu/guards.py``
(``jax.transfer_guard`` around the resident round, a compile counter
for the recompile budget, the fetch deadline) — the linter catches the
pattern at review time, the guards catch whatever slips through at run
time.
"""

from poseidon_tpu.analysis.contracts import Contracts, DEFAULT_CONTRACTS
from poseidon_tpu.analysis.core import (
    Violation,
    analyze_and_audit,
    analyze_file,
    analyze_tree,
    audit_suppressions,
    default_targets,
    format_human,
    format_json,
)

__all__ = [
    "Contracts",
    "DEFAULT_CONTRACTS",
    "Violation",
    "analyze_and_audit",
    "analyze_file",
    "analyze_tree",
    "audit_suppressions",
    "default_targets",
    "format_human",
    "format_json",
]
