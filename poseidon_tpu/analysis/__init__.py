"""Contract linter: AST analysis enforcing this repo's performance and
concurrency invariants.

The codebase *states* its contracts — rounds are O(churn), the resident
round is one fused program with exactly one host sync, the bridge is
single-threaded with documented cross-thread handoffs — but a contract
nobody checks is a comment. This package makes them machine-checked:

- ``python -m poseidon_tpu.analysis`` runs every registered rule over
  the shipped tree (``poseidon_tpu/``, ``bench.py``, ``scripts/``) and
  exits non-zero on any violation; CI runs it as a blocking step.
- Rules are repo-specific, declared against ``contracts.py`` (the hot-
  path scopes, the cluster-sized collection names, the thread classes
  and their documented handoff points, the trace vocabulary and flag
  surface). See ``rules.py`` for the rule set (PTA001-PTA005) with
  bad/good examples.
- Violations are suppressed inline with ``# noqa: PTA001 -- reason``;
  the reason is REQUIRED (a bare suppression is itself a violation,
  PTA000) so every sanctioned exception documents why it is sanctioned.

The static pass pairs with runtime teeth in ``poseidon_tpu/guards.py``
(``jax.transfer_guard`` around the resident round, a compile counter
for the recompile budget, the fetch deadline) — the linter catches the
pattern at review time, the guards catch whatever slips through at run
time.
"""

from poseidon_tpu.analysis.contracts import Contracts, DEFAULT_CONTRACTS
from poseidon_tpu.analysis.core import (
    Violation,
    analyze_file,
    analyze_tree,
    default_targets,
    format_human,
    format_json,
)

__all__ = [
    "Contracts",
    "DEFAULT_CONTRACTS",
    "Violation",
    "analyze_file",
    "analyze_tree",
    "default_targets",
    "format_human",
    "format_json",
]
