"""The lint framework: rule registry, suppressions, file walking, output.

Rule logic lives in ``rules.py``; the repo-specific declarations in
``contracts.py``. This module owns everything rule-agnostic:

- ``FileContext``: one parsed file (AST + comment map + suppression
  map + background-thread markers), built once and shared by every
  file-scoped rule;
- ``RepoContext``: every parsed file plus the repo root, for rules
  that check cross-file surfaces (PTA005);
- suppressions: ``# noqa: PTA001 -- reason`` on the violation's line.
  The reason is mandatory — a bare ``# noqa: PTA001`` is itself
  reported as PTA000 (suppression-hygiene), so CI fails until the
  author writes down WHY the exception is sanctioned;
- output: human one-line-per-violation or a JSON document for CI.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import json
import pathlib
import re
import tokenize
from typing import Callable

from poseidon_tpu.analysis.contracts import (
    BACKGROUND_MARKER,
    Contracts,
    DEFAULT_CONTRACTS,
)

# files/dirs never scanned
_SKIP_DIRS = {"__pycache__", ".git", "build", "build-asan", "build-tsan"}

# ``# noqa: PTA001 -- reason`` / ``# noqa: PTA001,PTA004 -- reason``.
# Only PTA codes are claimed; plain ``# noqa`` lines belong to ruff.
_NOQA_RE = re.compile(
    r"#\s*noqa:\s*(?P<codes>PTA\d{3}(?:\s*,\s*PTA\d{3})*)"
    r"(?:\s*--\s*(?P<reason>\S.*))?"
)


@dataclasses.dataclass(frozen=True)
class Violation:
    code: str          # "PTA001"
    rule: str          # "no-host-sync"
    path: str          # repo-root-relative POSIX path
    line: int
    col: int
    message: str

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class FileContext:
    """One parsed source file plus everything the rules derive from it."""

    path: str                       # repo-relative POSIX
    source: str
    tree: ast.AST
    comments: dict[int, str]        # line -> comment text
    suppressions: dict[int, set[str]]   # line -> suppressed PTA codes
    background_lines: set[int]      # lines carrying the PTA004 marker
    contracts: Contracts

    def in_scope(self, scopes: dict[str, tuple[str, ...]],
                 qualname: str) -> bool:
        """True when ``qualname`` (dot-joined def nesting, no class
        dots collapsed) matches a declared scope for this file. A
        nested function inherits its enclosing function's scope."""
        for suffix, names in scopes.items():
            if not self.path.endswith(suffix):
                continue
            for name in names:
                if qualname == name or qualname.startswith(name + "."):
                    return True
        return False


@dataclasses.dataclass
class RepoContext:
    root: pathlib.Path
    files: dict[str, FileContext]   # repo-relative path -> context
    contracts: Contracts

    def read_text(self, rel: str) -> str | None:
        p = self.root / rel
        try:
            return p.read_text()
        except OSError:
            return None


FileRule = Callable[[FileContext], list[Violation]]
RepoRule = Callable[[RepoContext], list[Violation]]

FILE_RULES: list[tuple[str, str, FileRule]] = []
REPO_RULES: list[tuple[str, str, RepoRule]] = []


def file_rule(code: str, name: str):
    def deco(fn: FileRule) -> FileRule:
        FILE_RULES.append((code, name, fn))
        return fn
    return deco


def repo_rule(code: str, name: str):
    def deco(fn: RepoRule) -> RepoRule:
        REPO_RULES.append((code, name, fn))
        return fn
    return deco


# ---- parsing -----------------------------------------------------------


def _scan_comments(source: str) -> dict[int, str]:
    out: dict[int, str] = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                out[tok.start[0]] = tok.string
    except (tokenize.TokenError, IndentationError):
        pass  # the ast.parse error is the authoritative one
    return out


def build_file_context(
    path: pathlib.Path, rel: str, contracts: Contracts
) -> tuple[FileContext | None, list[Violation]]:
    """Parse one file. Returns (context, violations-so-far); a syntax
    error yields (None, [PTA-syntax violation])."""
    source = path.read_text()
    try:
        tree = ast.parse(source, filename=rel)
    except SyntaxError as e:
        return None, [Violation(
            code="PTA000", rule="parse-error", path=rel,
            line=e.lineno or 1, col=e.offset or 0,
            message=f"file does not parse: {e.msg}",
        )]
    comments = _scan_comments(source)
    suppressions: dict[int, set[str]] = {}
    violations: list[Violation] = []
    background_lines: set[int] = set()
    for line, text in comments.items():
        if BACKGROUND_MARKER in text:
            background_lines.add(line)
        m = _NOQA_RE.search(text)
        if not m:
            continue
        codes = {c.strip() for c in m.group("codes").split(",")}
        if not m.group("reason"):
            violations.append(Violation(
                code="PTA000", rule="suppression-hygiene", path=rel,
                line=line, col=0,
                message=(
                    "suppression without a reason: write "
                    f"'# noqa: {','.join(sorted(codes))} -- <why this "
                    "is sanctioned>'"
                ),
            ))
            continue  # a reasonless suppression suppresses nothing
        suppressions.setdefault(line, set()).update(codes)
    ctx = FileContext(
        path=rel, source=source, tree=tree, comments=comments,
        suppressions=suppressions, background_lines=background_lines,
        contracts=contracts,
    )
    return ctx, violations


def _apply_suppressions(
    violations: list[Violation], ctx: FileContext
) -> list[Violation]:
    out = []
    for v in violations:
        if v.code in ctx.suppressions.get(v.line, ()):
            continue
        out.append(v)
    return out


# ---- driving -----------------------------------------------------------


def default_targets(root: pathlib.Path) -> list[pathlib.Path]:
    """The shipped tree: the package, the bench harness, scripts/.
    Tests are not scanned — they deliberately contain seeded-violation
    snippets (as data) and drive private APIs the contracts exempt."""
    out: list[pathlib.Path] = []
    for base in ("poseidon_tpu", "scripts"):
        d = root / base
        if d.is_dir():
            out.extend(
                p for p in sorted(d.rglob("*.py"))
                if not any(part in _SKIP_DIRS for part in p.parts)
            )
    for single in ("bench.py",):
        p = root / single
        if p.is_file():
            out.append(p)
    return out


def _ensure_rules_loaded() -> None:
    """Rule registration is an import-time side effect of the rules
    module; every public entry point must force it or it would run
    with an empty registry and report anything as clean."""
    import poseidon_tpu.analysis.rules  # noqa: F401 (registry side effect)


def analyze_file(
    path: pathlib.Path,
    root: pathlib.Path,
    contracts: Contracts = DEFAULT_CONTRACTS,
) -> list[Violation]:
    _ensure_rules_loaded()
    rel = path.relative_to(root).as_posix()
    ctx, violations = build_file_context(path, rel, contracts)
    if ctx is None:
        return violations
    found: list[Violation] = []
    for _code, _name, rule in FILE_RULES:
        found.extend(rule(ctx))
    return violations + _apply_suppressions(found, ctx)


def analyze_tree(
    root: pathlib.Path,
    paths: list[pathlib.Path] | None = None,
    contracts: Contracts = DEFAULT_CONTRACTS,
) -> tuple[list[Violation], int]:
    """Run every rule over ``paths`` (default: the shipped tree).
    Returns (violations, files_scanned)."""
    _ensure_rules_loaded()
    root = root.resolve()
    targets = paths if paths is not None else default_targets(root)
    files: dict[str, FileContext] = {}
    violations: list[Violation] = []
    for path in targets:
        rel = path.resolve().relative_to(root).as_posix()
        ctx, pre = build_file_context(path, rel, contracts)
        violations.extend(pre)
        if ctx is None:
            continue
        files[rel] = ctx
        found: list[Violation] = []
        for _code, _name, rule in FILE_RULES:
            found.extend(rule(ctx))
        violations.extend(_apply_suppressions(found, ctx))
    repo_ctx = RepoContext(root=root, files=files, contracts=contracts)
    for _code, _name, rule in REPO_RULES:
        found = rule(repo_ctx)
        # repo-rule violations anchored in a scanned file honor that
        # file's suppressions too
        kept: list[Violation] = []
        for v in found:
            fctx = files.get(v.path)
            if fctx is not None and v.code in fctx.suppressions.get(
                v.line, ()
            ):
                continue
            kept.append(v)
        violations.extend(kept)
    violations.sort(key=lambda v: (v.path, v.line, v.col, v.code))
    return violations, len(files)


# ---- output ------------------------------------------------------------


def format_human(violations: list[Violation], files_scanned: int) -> str:
    lines = [
        f"{v.path}:{v.line}:{v.col}: {v.code} [{v.rule}] {v.message}"
        for v in violations
    ]
    lines.append(
        f"{len(violations)} violation(s) in {files_scanned} file(s) scanned"
        if violations
        else f"clean: 0 violations in {files_scanned} file(s) scanned"
    )
    return "\n".join(lines)


def format_json(violations: list[Violation], files_scanned: int) -> str:
    return json.dumps(
        {
            "violations": [v.as_dict() for v in violations],
            "count": len(violations),
            "files_scanned": files_scanned,
        },
        indent=2,
    )
