"""The lint framework: rule registry, suppressions, file walking, output.

Rule logic lives in ``rules.py``; the repo-specific declarations in
``contracts.py``. This module owns everything rule-agnostic:

- ``FileContext``: one parsed file (AST + comment map + suppression
  map + background-thread markers), built once and shared by every
  file-scoped rule;
- ``RepoContext``: every parsed file plus the repo root, for rules
  that check cross-file surfaces (PTA005);
- suppressions: ``# noqa: PTA001 -- reason`` on the violation's
  statement. A suppression covers the whole span of the statement it
  sits on (a decorated ``def``'s span runs from its first decorator
  through the ``def`` line, a multi-line call from its first line to
  its closing paren), so a ``# noqa`` on the ``def`` line covers a
  violation reported on the decorator line and vice versa. The reason
  is mandatory — a bare ``# noqa: PTA001`` is itself reported as
  PTA000 (suppression-hygiene), so CI fails until the author writes
  down WHY the exception is sanctioned;
- per-path rule scoping: ``Contracts.path_rules`` narrows which rule
  codes are enforced under a path prefix (``tests/`` runs only the
  jit-hygiene/vocabulary/hygiene rules — test files deliberately
  contain seeded-violation snippets for the other rules);
- the suppression audit (``audit_suppressions``): a reasoned ``# noqa``
  whose rule no longer fires anywhere in its statement's span is DEAD
  and reported as PTA000, so stale exceptions rot out of the tree
  instead of silently sanctioning future violations;
- output: human one-line-per-violation or a JSON document for CI.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import json
import pathlib
import re
import tokenize
from typing import Callable

from poseidon_tpu.analysis.contracts import (
    BACKGROUND_MARKER,
    Contracts,
    DEFAULT_CONTRACTS,
)

# files/dirs never scanned
_SKIP_DIRS = {"__pycache__", ".git", "build", "build-asan", "build-tsan"}

# Suppression comments: ``noqa: PTA001 -- reason`` with one or more
# comma-separated codes after the hash. Only PTA codes are claimed;
# plain ruff noqas are ignored. (Spelled without a leading hash here
# so this documentation is not itself parsed as a suppression — the
# dead-suppression audit caught exactly that.)
_NOQA_RE = re.compile(
    r"#\s*noqa:\s*(?P<codes>PTA\d{3}(?:\s*,\s*PTA\d{3})*)"
    r"(?:\s*--\s*(?P<reason>\S.*))?"
)


@dataclasses.dataclass(frozen=True)
class Violation:
    code: str          # "PTA001"
    rule: str          # "no-host-sync"
    path: str          # repo-root-relative POSIX path
    line: int
    col: int
    message: str

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class FileContext:
    """One parsed source file plus everything the rules derive from it."""

    path: str                       # repo-relative POSIX
    source: str
    tree: ast.AST
    comments: dict[int, str]        # line -> comment text
    suppressions: dict[int, set[str]]   # line -> suppressed PTA codes
                                        # (expanded over statement spans)
    # the raw reasoned-suppression comments, pre-span-expansion:
    # (comment line, statement span (start, end), codes) — the audit
    # checks each of these against the raw violation set
    suppression_comments: list[tuple[int, tuple[int, int], set[str]]] = \
        dataclasses.field(default_factory=list)
    background_lines: set[int] = dataclasses.field(default_factory=set)
    contracts: Contracts = None

    def in_scope(self, scopes: dict[str, tuple[str, ...]],
                 qualname: str) -> bool:
        """True when ``qualname`` (dot-joined def nesting, no class
        dots collapsed) matches a declared scope for this file. A
        nested function inherits its enclosing function's scope."""
        for suffix, names in scopes.items():
            if not self.path.endswith(suffix):
                continue
            for name in names:
                if qualname == name or qualname.startswith(name + "."):
                    return True
        return False


@dataclasses.dataclass
class RepoContext:
    root: pathlib.Path
    files: dict[str, FileContext]   # repo-relative path -> context
    contracts: Contracts

    def read_text(self, rel: str) -> str | None:
        p = self.root / rel
        try:
            return p.read_text()
        except OSError:
            return None


FileRule = Callable[[FileContext], list[Violation]]
RepoRule = Callable[[RepoContext], list[Violation]]

FILE_RULES: list[tuple[str, str, FileRule]] = []
REPO_RULES: list[tuple[str, str, RepoRule]] = []


def file_rule(code: str, name: str):
    def deco(fn: FileRule) -> FileRule:
        FILE_RULES.append((code, name, fn))
        return fn
    return deco


def repo_rule(code: str, name: str):
    def deco(fn: RepoRule) -> RepoRule:
        REPO_RULES.append((code, name, fn))
        return fn
    return deco


# ---- parsing -----------------------------------------------------------


def _stmt_header_spans(tree: ast.AST) -> list[tuple[int, int]]:
    """(start, end) line span of every statement's HEADER.

    For compound statements (def/class/with/for/if/try...) the span
    covers the decorators and header lines up to the first body
    statement — NOT the body (a ``# noqa`` on a ``with`` line must not
    blanket-suppress the block under it). For simple statements the
    span is the whole (possibly multi-line) statement.
    """
    spans: list[tuple[int, int]] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.stmt):
            continue
        start = node.lineno
        decorators = getattr(node, "decorator_list", [])
        if decorators:
            start = min(start, min(d.lineno for d in decorators))
        body = getattr(node, "body", None)
        if isinstance(body, list) and body and \
                isinstance(body[0], ast.stmt):
            end = max(start, body[0].lineno - 1)
        else:
            end = getattr(node, "end_lineno", None) or node.lineno
        spans.append((start, end))
    return spans


def _span_for_line(spans: list[tuple[int, int]], line: int) -> tuple[int, int]:
    """The innermost statement-header span containing ``line`` (the one
    with the latest start); a comment on its own line between
    statements keeps line-exact behavior."""
    best: tuple[int, int] | None = None
    for start, end in spans:
        if start <= line <= end:
            if best is None or start > best[0] or (
                start == best[0] and end < best[1]
            ):
                best = (start, end)
    return best if best is not None else (line, line)


def _scan_comments(source: str) -> dict[int, str]:
    out: dict[int, str] = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                out[tok.start[0]] = tok.string
    except (tokenize.TokenError, IndentationError):
        pass  # the ast.parse error is the authoritative one
    return out


def build_file_context(
    path: pathlib.Path, rel: str, contracts: Contracts
) -> tuple[FileContext | None, list[Violation]]:
    """Parse one file. Returns (context, violations-so-far); a syntax
    error yields (None, [PTA-syntax violation])."""
    source = path.read_text()
    try:
        tree = ast.parse(source, filename=rel)
    except SyntaxError as e:
        return None, [Violation(
            code="PTA000", rule="parse-error", path=rel,
            line=e.lineno or 1, col=e.offset or 0,
            message=f"file does not parse: {e.msg}",
        )]
    comments = _scan_comments(source)
    spans = _stmt_header_spans(tree)
    suppressions: dict[int, set[str]] = {}
    suppression_comments: list[tuple[int, tuple[int, int], set[str]]] = []
    violations: list[Violation] = []
    background_lines: set[int] = set()
    for line, text in comments.items():
        if BACKGROUND_MARKER in text:
            background_lines.add(line)
        m = _NOQA_RE.search(text)
        if not m:
            continue
        codes = {c.strip() for c in m.group("codes").split(",")}
        if not m.group("reason"):
            violations.append(Violation(
                code="PTA000", rule="suppression-hygiene", path=rel,
                line=line, col=0,
                message=(
                    "suppression without a reason: write "
                    f"'# noqa: {','.join(sorted(codes))} -- <why this "
                    "is sanctioned>'"
                ),
            ))
            continue  # a reasonless suppression suppresses nothing
        # a suppression covers its whole statement: normalize from the
        # comment's line to the enclosing statement-header span, so a
        # # noqa on a decorated def covers violations reported on the
        # decorator line (and vice versa)
        start, end = _span_for_line(spans, line)
        suppression_comments.append((line, (start, end), codes))
        for ln in range(start, end + 1):
            suppressions.setdefault(ln, set()).update(codes)
    ctx = FileContext(
        path=rel, source=source, tree=tree, comments=comments,
        suppressions=suppressions,
        suppression_comments=suppression_comments,
        background_lines=background_lines,
        contracts=contracts,
    )
    return ctx, violations


def _apply_suppressions(
    violations: list[Violation], ctx: FileContext
) -> list[Violation]:
    out = []
    for v in violations:
        if v.code in ctx.suppressions.get(v.line, ()):
            continue
        out.append(v)
    return out


# ---- driving -----------------------------------------------------------


def default_targets(root: pathlib.Path) -> list[pathlib.Path]:
    """The shipped tree: the package, the bench harness, scripts/, and
    tests/. Tests run under a NARROWED rule set
    (``Contracts.path_rules``): jit hygiene and the trace/flag
    vocabulary apply to test code too (a test leaking fresh jit
    wrappers or emitting undeclared events is a real bug), but the
    hot-path/O(churn)/thread rules do not — test files deliberately
    contain seeded-violation snippets (as data) and drive private APIs
    the contracts exempt."""
    out: list[pathlib.Path] = []
    for base in ("poseidon_tpu", "scripts", "tests"):
        d = root / base
        if d.is_dir():
            out.extend(
                p for p in sorted(d.rglob("*.py"))
                if not any(part in _SKIP_DIRS for part in p.parts)
            )
    for single in ("bench.py",):
        p = root / single
        if p.is_file():
            out.append(p)
    return out


def _allowed_codes(contracts: Contracts, path: str) -> tuple[str, ...] | None:
    """The rule codes enforced for ``path`` (None = every rule). First
    matching ``path_rules`` prefix wins."""
    for prefix, codes in contracts.path_rules:
        if path.startswith(prefix):
            return codes
    return None


def files_enforcing(
    repo: "RepoContext", code: str
) -> dict[str, FileContext]:
    """The scanned files whose EVIDENCE a whole-program pass for
    ``code`` may use: where path_rules enforce that code. Excluded
    files (tests/) must not feed access maps or registries either —
    a test poking privates would otherwise fabricate main-thread
    'evidence' anchored in production code, which the violation-path
    filter alone cannot undo."""
    out: dict[str, FileContext] = {}
    for rel, fctx in repo.files.items():
        allowed = _allowed_codes(repo.contracts, rel)
        if allowed is None or code in allowed:
            out[rel] = fctx
    return out


def _path_scope_filter(
    violations: list[Violation], contracts: Contracts
) -> list[Violation]:
    out = []
    for v in violations:
        allowed = _allowed_codes(contracts, v.path)
        if allowed is not None and v.code not in allowed:
            continue
        out.append(v)
    return out


def _ensure_rules_loaded() -> None:
    """Rule registration is an import-time side effect of the rule
    modules; every public entry point must force it or it would run
    with an empty registry and report anything as clean."""
    import poseidon_tpu.analysis.locks  # noqa: F401 (registry side effect)
    import poseidon_tpu.analysis.recompile  # noqa: F401 (registry side effect)
    import poseidon_tpu.analysis.rules  # noqa: F401 (registry side effect)
    import poseidon_tpu.analysis.threads  # noqa: F401 (registry side effect)


def analyze_file(
    path: pathlib.Path,
    root: pathlib.Path,
    contracts: Contracts = DEFAULT_CONTRACTS,
) -> list[Violation]:
    _ensure_rules_loaded()
    rel = path.relative_to(root).as_posix()
    ctx, violations = build_file_context(path, rel, contracts)
    if ctx is None:
        return violations
    found: list[Violation] = []
    for _code, _name, rule in FILE_RULES:
        found.extend(rule(ctx))
    return _path_scope_filter(
        violations + _apply_suppressions(found, ctx), contracts
    )


def _run_rules(
    root: pathlib.Path,
    paths: list[pathlib.Path] | None,
    contracts: Contracts,
) -> tuple[list[Violation], list[Violation], dict[str, FileContext]]:
    """Shared driver: returns (kept, raw, contexts). ``raw`` is every
    rule finding BEFORE suppressions (but after path-rule scoping) —
    the suppression audit diffs the two."""
    _ensure_rules_loaded()
    root = root.resolve()
    targets = paths if paths is not None else default_targets(root)
    files: dict[str, FileContext] = {}
    kept: list[Violation] = []
    raw: list[Violation] = []
    for path in targets:
        rel = path.resolve().relative_to(root).as_posix()
        ctx, pre = build_file_context(path, rel, contracts)
        kept.extend(pre)
        if ctx is None:
            continue
        files[rel] = ctx
        found: list[Violation] = []
        for _code, _name, rule in FILE_RULES:
            found.extend(rule(ctx))
        found = _path_scope_filter(found, contracts)
        raw.extend(found)
        kept.extend(_apply_suppressions(found, ctx))
    repo_ctx = RepoContext(root=root, files=files, contracts=contracts)
    for _code, _name, rule in REPO_RULES:
        found = _path_scope_filter(rule(repo_ctx), contracts)
        raw.extend(found)
        # repo-rule violations anchored in a scanned file honor that
        # file's suppressions too
        for v in found:
            fctx = files.get(v.path)
            if fctx is not None and v.code in fctx.suppressions.get(
                v.line, ()
            ):
                continue
            kept.append(v)
    kept = _path_scope_filter(kept, contracts)
    kept.sort(key=lambda v: (v.path, v.line, v.col, v.code))
    return kept, raw, files


def analyze_tree(
    root: pathlib.Path,
    paths: list[pathlib.Path] | None = None,
    contracts: Contracts = DEFAULT_CONTRACTS,
) -> tuple[list[Violation], int]:
    """Run every rule over ``paths`` (default: the shipped tree).
    Returns (violations, files_scanned)."""
    kept, _raw, files = _run_rules(root, paths, contracts)
    return kept, len(files)


def audit_suppressions(
    root: pathlib.Path,
    paths: list[pathlib.Path] | None = None,
    contracts: Contracts = DEFAULT_CONTRACTS,
) -> tuple[list[Violation], int]:
    """Report DEAD suppressions: a reasoned ``# noqa: PTA0xx`` whose
    named rule no longer fires anywhere within its statement's span.

    Dead suppressions are how a linter rots: the exception outlives
    the code it sanctioned, then silently blesses the NEXT violation
    someone writes on that line. CI runs this after the main pass
    (``--audit-suppressions``) so stale entries are cleaned out while
    the reason is still in memory. Returns (violations,
    files_scanned) like ``analyze_tree``.
    """
    _kept, raw, files = _run_rules(root, paths, contracts)
    return _dead_suppressions(raw, files), len(files)


def _dead_suppressions(
    raw: list[Violation], files: dict[str, FileContext]
) -> list[Violation]:
    fired: dict[str, set[tuple[str, int]]] = {}
    for v in raw:
        fired.setdefault(v.path, set()).add((v.code, v.line))
    out: list[Violation] = []
    for rel, ctx in files.items():
        hits = fired.get(rel, set())
        for line, (start, end), codes in ctx.suppression_comments:
            for code in sorted(codes):
                if any(
                    (code, ln) in hits for ln in range(start, end + 1)
                ):
                    continue
                out.append(Violation(
                    code="PTA000", rule="dead-suppression", path=rel,
                    line=line, col=0,
                    message=(
                        f"dead suppression: {code} does not fire on "
                        "this statement any more — delete the noqa "
                        "(or the code it sanctioned has moved)"
                    ),
                ))
    out.sort(key=lambda v: (v.path, v.line, v.col, v.code))
    return out


def analyze_and_audit(
    root: pathlib.Path,
    paths: list[pathlib.Path] | None = None,
    contracts: Contracts = DEFAULT_CONTRACTS,
) -> tuple[list[Violation], int]:
    """One combined pass: rule violations MERGED with dead-suppression
    reports, from a single rule run (the CLI's --audit-suppressions
    lane — running ``analyze_tree`` and ``audit_suppressions``
    back-to-back would execute every rule twice)."""
    kept, raw, files = _run_rules(root, paths, contracts)
    merged = sorted(
        kept + _dead_suppressions(raw, files),
        key=lambda v: (v.path, v.line, v.col, v.code),
    )
    return merged, len(files)


# ---- output ------------------------------------------------------------


def format_human(violations: list[Violation], files_scanned: int) -> str:
    lines = [
        f"{v.path}:{v.line}:{v.col}: {v.code} [{v.rule}] {v.message}"
        for v in violations
    ]
    lines.append(
        f"{len(violations)} violation(s) in {files_scanned} file(s) scanned"
        if violations
        else f"clean: 0 violations in {files_scanned} file(s) scanned"
    )
    return "\n".join(lines)


def format_json(
    violations: list[Violation],
    files_scanned: int,
    kernels_audited: int | None = None,
) -> str:
    """The CLI's JSON document — the ONE writer of the schema CI and
    downstream tooling depend on (tests/test_analysis.py::
    TestJsonSchema locks it). ``kernels_audited`` appears only when
    the jaxpr audit ran."""
    doc = {
        "violations": [v.as_dict() for v in violations],
        "count": len(violations),
        "files_scanned": files_scanned,
    }
    if kernels_audited is not None:
        doc["kernels_audited"] = kernels_audited
    return json.dumps(doc, indent=2)
