"""PTA006: a whole-program lockset race detector over the thread model.

PTA004 (rules.py) checks lock discipline *file-locally* and trusts two
declarations: the ``# pta: background-thread`` def-line markers and the
``ThreadContract.handoffs`` allowlist. This pass VERIFIES both, in the
lockset style of Eraser-class race detection (compute the set of locks
held at every access; a shared attribute whose accesses hold no common
lock is a candidate race), but statically and repo-wide:

1. **Thread model.** Background contexts come from the markers PLUS
   inference the markers cannot drift from:

   - ``threading.Thread(target=self.m)`` spawn sites make ``m`` a
     thread root whether or not it carries a marker;
   - classes subclassing ``threading.Thread`` make ``run`` a root;
   - a lambda / local function passed to a declared spawn wrapper
     (``Contracts.thread_spawn_wrappers`` — ``_AsyncFetch``) is a
     background context: its body runs on the wrapper's daemon thread;
   - methods reachable from a root via ``self.m()`` calls inherit the
     background domain (an unmarked helper called only from ``run`` is
     still background code).

   All background contexts of a class collapse into one "background"
   domain (the classes here run one background thread each; two
   distinct background threads racing each other is out of scope and
   documented as such).

2. **Access maps, across classes.** Every ``self.attr`` read/write in
   a class's methods is recorded with its domain and the lockset held.
   Accesses from OTHER classes are attributed too, through light type
   inference: constructor assignments (``s = _WatchStream(...)``),
   parameter/attribute annotations (``nodes: _WatchStream | None``,
   ``self._streams: dict[str, _WatchStream]``), and container
   derivations (``.get(...)``, ``[...]``, ``.values()`` / ``.items()``
   iteration) — this is what lets the detector see that
   ``ClusterWatcher.tick`` reads ``stream.last_activity`` on the main
   thread while the reader thread writes it.

3. **Lockset intersection.** An attribute written outside ``__init__``
   and reachable from two domains must either hold one common lock on
   the SAME instance at every access (``with self._lock:`` in its own
   methods, ``with stream._lock:`` at a cross-class site) or be a
   declared handoff. ``__init__``'s main-thread accesses are exempt —
   construction happens-before any thread start — but a background
   context ``__init__`` itself creates (a state-touching lambda handed
   to a spawn wrapper) runs concurrently with every later access and
   is NOT exempt.

4. **Handoff verification.** Every declared handoff must correspond to
   a genuinely cross-thread, not-fully-locked attribute; otherwise the
   entry is STALE and reported — a stale allowlist entry is how the
   next real race on that attribute gets silently blessed.

Known limitations (deliberate): races between two distinct background
threads of one class, accesses through untyped aliases, executor-pool
submissions (``pool.map``/``submit`` — the one use in cli.py blocks the
main thread for the pool's lifetime), and attribute mutation through a
method call (``x.gone.set()`` mutates the Event, not the attribute
binding — Event/Queue objects are internally synchronized).
"""

from __future__ import annotations

import ast
import dataclasses

from poseidon_tpu.analysis.contracts import ThreadContract
from poseidon_tpu.analysis.core import (
    FileContext,
    RepoContext,
    Violation,
    files_enforcing,
    repo_rule,
)


_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)

MAIN = "main"
BACKGROUND = "background"


@dataclasses.dataclass
class Site:
    """One attribute access."""

    path: str
    line: int
    col: int
    write: bool
    domain: str          # MAIN or BACKGROUND
    lockset: frozenset   # lock attr names held on the SAME instance


@dataclasses.dataclass
class _ClassInfo:
    name: str
    path: str
    lineno: int
    node: ast.ClassDef
    methods: dict[str, ast.AST] = dataclasses.field(default_factory=dict)
    member_names: set[str] = dataclasses.field(default_factory=set)
    # method name -> why it is a background context (marker text /
    # "spawn-site" / "thread-subclass run" / "wrapper arg" / "reached
    # from <root>")
    bg_methods: dict[str, str] = dataclasses.field(default_factory=dict)
    # attr -> (kind, class name): light types for self attributes
    attr_types: dict[str, tuple[str, str]] = dataclasses.field(
        default_factory=dict
    )
    accesses: dict[str, list[Site]] = dataclasses.field(
        default_factory=dict
    )


def _terminal_name(node: ast.AST) -> str | None:
    """'Thread' for both ``Thread`` and ``threading.Thread``."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _annotation_type(
    node: ast.AST | None, known: set[str]
) -> tuple[str, str] | None:
    """(kind, class) from an annotation mentioning a known class.
    ``C`` / ``C | None`` / ``Optional[C]`` -> ("one", C);
    ``dict[str, C]`` / ``list[C]`` -> ("many", C)."""
    if node is None:
        return None
    names = {
        n.id for n in ast.walk(node) if isinstance(n, ast.Name)
    } | {
        n.attr for n in ast.walk(node) if isinstance(n, ast.Attribute)
    }
    hits = names & known
    if len(hits) != 1:
        return None
    cls = next(iter(hits))
    kind = "one"
    if isinstance(node, ast.Subscript):
        root = _terminal_name(node.value)
        if root in ("dict", "list", "set", "tuple", "frozenset",
                    "Dict", "List", "Set", "Tuple"):
            kind = "many"
    # string annotations ("C") parse as Constant: skip those (rare)
    return kind, cls


def _iter_class_defs(tree: ast.AST):
    """Every ClassDef in the file, including nested ones."""
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            yield node


# ---------------------------------------------------------------------------
# pass 1: classes, roots, attribute types
# ---------------------------------------------------------------------------


def _collect_classes(
    repo: RepoContext,
    files: dict[str, FileContext],
) -> dict[str, _ClassInfo]:
    """Index every class (by name — the contracts key on bare class
    names) with its methods, background roots, and self-attr types."""
    c = repo.contracts
    out: dict[str, _ClassInfo] = {}
    for rel, fctx in files.items():
        for node in _iter_class_defs(fctx.tree):
            info = _ClassInfo(
                name=node.name, path=rel, lineno=node.lineno, node=node
            )
            for stmt in node.body:
                if isinstance(stmt, _FUNC_NODES):
                    info.methods[stmt.name] = stmt
                    info.member_names.add(stmt.name)
                    if stmt.lineno in fctx.background_lines:
                        info.bg_methods[stmt.name] = "marker"
            if any(
                _terminal_name(b) == "Thread" for b in node.bases
            ) and "run" in info.methods:
                info.bg_methods.setdefault("run", "threading.Thread "
                                                  "subclass")
            # a later class of the same name would shadow the earlier
            # in this index; the repo has no duplicates and the
            # contracts key on bare names, so first wins deterministic
            out.setdefault(node.name, info)

    known = set(out)
    wrappers = set(c.thread_spawn_wrappers)
    for rel, fctx in files.items():
        for node in _iter_class_defs(fctx.tree):
            info = out.get(node.name)
            if info is None or info.path != rel:
                continue
            for meth in info.methods.values():
                args = meth.args.posonlyargs + meth.args.args
                self_name = args[0].arg if args else None
                for sub in ast.walk(meth):
                    # self.<a>: C = ... / self.<a> = C(...)
                    if isinstance(sub, ast.AnnAssign) and \
                            self_name is not None and \
                            isinstance(sub.target, ast.Attribute) and \
                            isinstance(sub.target.value, ast.Name) and \
                            sub.target.value.id == self_name:
                        t = _annotation_type(sub.annotation, known)
                        if t is not None:
                            info.attr_types.setdefault(
                                sub.target.attr, t
                            )
                    if isinstance(sub, ast.Assign) and \
                            isinstance(sub.value, ast.Call):
                        callee = _terminal_name(sub.value.func)
                        if callee in known:
                            for t in sub.targets:
                                if isinstance(t, ast.Attribute) and \
                                        isinstance(t.value, ast.Name) \
                                        and t.value.id == self_name:
                                    info.attr_types.setdefault(
                                        t.attr, ("one", callee)
                                    )
                    # threading.Thread(target=self.m) spawn inference
                    if isinstance(sub, ast.Call) and \
                            _terminal_name(sub.func) == "Thread":
                        for kw in sub.keywords:
                            if kw.arg != "target":
                                continue
                            v = kw.value
                            if isinstance(v, ast.Attribute) and \
                                    isinstance(v.value, ast.Name) and \
                                    v.value.id == self_name and \
                                    v.attr in info.methods:
                                info.bg_methods.setdefault(
                                    v.attr, "Thread(target=) spawn site"
                                )
                    # spawn wrappers: _AsyncFetch(self.m) makes m a
                    # root; _AsyncFetch(lambda: ...) / _AsyncFetch(fn)
                    # marks the class as having a background context
                    # (the lambda/local-def bodies get their domain in
                    # the access walk)
                    if isinstance(sub, ast.Call) and \
                            _terminal_name(sub.func) in wrappers:
                        for a in list(sub.args) + [
                            kw.value for kw in sub.keywords
                        ]:
                            if isinstance(a, ast.Attribute) and \
                                    isinstance(a.value, ast.Name) and \
                                    a.value.id == self_name and \
                                    a.attr in info.methods:
                                info.bg_methods.setdefault(
                                    a.attr,
                                    f"{_terminal_name(sub.func)} arg"
                                )
                            elif isinstance(a, (ast.Lambda, ast.Name)):
                                # pseudo-entry: never a method name, so
                                # it only flips the class interesting
                                info.bg_methods.setdefault(
                                    f"~wrapper:{meth.name}",
                                    "spawn-wrapper callable context",
                                )
            # call-graph closure: self.m() from a background method
            # makes m background too (unmarked helpers stay honest)
            changed = True
            while changed:
                changed = False
                for mname, meth in info.methods.items():
                    if mname not in info.bg_methods:
                        continue
                    args = meth.args.posonlyargs + meth.args.args
                    self_name = args[0].arg if args else None
                    if self_name is None:
                        continue
                    for sub in ast.walk(meth):
                        if isinstance(sub, ast.Call) and \
                                isinstance(sub.func, ast.Attribute) and \
                                isinstance(sub.func.value, ast.Name) \
                                and sub.func.value.id == self_name and \
                                sub.func.attr in info.methods and \
                                sub.func.attr not in info.bg_methods:
                            info.bg_methods[sub.func.attr] = (
                                f"reached from {mname}"
                            )
                            changed = True
    return out


# ---------------------------------------------------------------------------
# pass 2: attribute accesses with domains + locksets
# ---------------------------------------------------------------------------


def _local_types(
    fn: ast.AST,
    known: set[str],
    self_name: str | None,
    own_info: _ClassInfo | None,
) -> dict[str, str]:
    """Flow-insensitive name -> class for this function's locals."""
    types: dict[str, str] = {}

    def attr_kind(expr: ast.AST) -> tuple[str, str] | None:
        """Type of ``self.<a>`` per the owning class's attr_types."""
        if own_info is not None and isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name) \
                and expr.value.id == self_name:
            return own_info.attr_types.get(expr.attr)
        return None

    def value_type(expr: ast.AST) -> str | None:
        """Class of an expression that yields ONE instance."""
        if isinstance(expr, ast.Call):
            callee = _terminal_name(expr.func)
            if callee in known:
                return callee
            # self._streams.get("pods") -> element type
            if isinstance(expr.func, ast.Attribute) and \
                    expr.func.attr == "get":
                t = attr_kind(expr.func.value)
                if t is not None and t[0] == "many":
                    return t[1]
        if isinstance(expr, ast.Subscript):
            t = attr_kind(expr.value)
            if t is not None and t[0] == "many":
                return t[1]
        t = attr_kind(expr)
        if t is not None and t[0] == "one":
            return t[1]
        if isinstance(expr, ast.Name) and expr.id in types:
            return types[expr.id]
        return None

    def elem_type(it: ast.AST) -> tuple[str | None, bool]:
        """(class, values-are-second-tuple-elt) for an iteration
        source: ``self._streams.values()`` / ``.items()`` / a typed
        list attribute."""
        if isinstance(it, ast.Call) and \
                isinstance(it.func, ast.Attribute) and \
                it.func.attr in ("values", "items"):
            t = attr_kind(it.func.value)
            if t is not None and t[0] == "many":
                return t[1], it.func.attr == "items"
        t = attr_kind(it)
        if t is not None and t[0] == "many":
            return t[1], False
        return None, False

    # parameter annotations
    for a in fn.args.posonlyargs + fn.args.args + fn.args.kwonlyargs:
        t = _annotation_type(a.annotation, known)
        if t is not None and t[0] == "one":
            types[a.arg] = t[1]

    for _ in range(2):  # one hop of name->name propagation
        for sub in ast.walk(fn):
            if isinstance(sub, ast.Assign):
                cls = value_type(sub.value)
                if cls is not None:
                    for t in sub.targets:
                        if isinstance(t, ast.Name):
                            types[t.id] = cls
            elif isinstance(sub, ast.AnnAssign) and \
                    isinstance(sub.target, ast.Name):
                t = _annotation_type(sub.annotation, known)
                if t is not None and t[0] == "one":
                    types[sub.target.id] = t[1]
            elif isinstance(sub, (ast.For, ast.AsyncFor)):
                cls, is_items = elem_type(sub.iter)
                if cls is not None:
                    tgt = sub.target
                    if is_items and isinstance(tgt, ast.Tuple) and \
                            len(tgt.elts) == 2 and \
                            isinstance(tgt.elts[1], ast.Name):
                        types[tgt.elts[1].id] = cls
                    elif not is_items and isinstance(tgt, ast.Name):
                        types[tgt.id] = cls
            elif isinstance(sub, ast.comprehension):
                cls, is_items = elem_type(sub.iter)
                if cls is not None:
                    tgt = sub.target
                    if is_items and isinstance(tgt, ast.Tuple) and \
                            len(tgt.elts) == 2 and \
                            isinstance(tgt.elts[1], ast.Name):
                        types[tgt.elts[1].id] = cls
                    elif not is_items and isinstance(tgt, ast.Name):
                        types[tgt.id] = cls
    return types


def _collect_accesses(
    repo: RepoContext,
    files: dict[str, FileContext],
    classes: dict[str, _ClassInfo],
    interesting: set[str],
) -> None:
    """Walk every function in the repo recording attribute accesses on
    interesting classes — ``self.attr`` inside the class's own methods
    and ``x.attr`` through typed bases anywhere else — with the
    access's thread domain and held lockset."""
    wrappers = set(repo.contracts.thread_spawn_wrappers)
    known = set(classes)

    def record(cls: str, attr: str, path: str, node: ast.Attribute,
               domain: str, lockset: frozenset):
        info = classes[cls]
        if attr in info.member_names:
            return  # method/property references are calls, not state
        info.accesses.setdefault(attr, []).append(Site(
            path=path, line=node.lineno, col=node.col_offset,
            write=isinstance(node.ctx, (ast.Store, ast.Del)),
            domain=domain, lockset=lockset,
        ))

    def walk_fn(
        fn: ast.AST,
        fctx: FileContext,
        own: _ClassInfo | None,
        self_name: str | None,
        domain: str,
        record_main: bool = True,
    ) -> None:
        types = _local_types(fn, known, self_name, own)

        # lambdas / local defs passed to spawn wrappers run on the
        # wrapper's background thread
        bg_nodes: set[int] = set()
        local_defs: dict[str, ast.AST] = {}
        for sub in ast.walk(fn):
            if isinstance(sub, _FUNC_NODES) and sub is not fn:
                local_defs.setdefault(sub.name, sub)
        for sub in ast.walk(fn):
            if isinstance(sub, ast.Call) and \
                    _terminal_name(sub.func) in wrappers:
                for a in list(sub.args) + [
                    kw.value for kw in sub.keywords
                ]:
                    if isinstance(a, ast.Lambda):
                        bg_nodes.add(id(a))
                    elif isinstance(a, ast.Name) and \
                            a.id in local_defs:
                        bg_nodes.add(id(local_defs[a.id]))

        def base_repr(expr: ast.AST) -> str | None:
            if isinstance(expr, ast.Name):
                return expr.id
            return None

        def rec(n: ast.AST, dom: str, held: tuple):
            if isinstance(n, _FUNC_NODES + (ast.Lambda,)) and n is not fn:
                ndom = dom
                if id(n) in bg_nodes:
                    ndom = BACKGROUND
                elif isinstance(n, _FUNC_NODES) and \
                        n.lineno in fctx.background_lines:
                    ndom = BACKGROUND
                # a lock held at definition time is NOT held when the
                # closure later runs
                body = n.body if isinstance(n.body, list) else [n.body]
                for stmt in body:
                    rec(stmt, ndom, ())
                return
            if isinstance(n, ast.ClassDef):
                return  # nested classes analyzed as their own scopes
            now = held
            if isinstance(n, ast.With):
                for item in n.items:
                    ce = item.context_expr
                    if isinstance(ce, ast.Attribute) and \
                            isinstance(ce.value, ast.Name):
                        now = now + ((ce.value.id, ce.attr),)
            def resolve(attr_node: ast.Attribute) -> str | None:
                b = base_repr(attr_node.value)
                if b is None:
                    return None
                cls = None
                if b == self_name and own is not None:
                    cls = own.name
                elif b in types:
                    cls = types[b]
                if cls in classes and cls in interesting:
                    return cls
                return None

            def lockset_for(attr_node: ast.Attribute) -> frozenset:
                b = base_repr(attr_node.value)
                return frozenset(
                    la for (bb, la) in now if bb == b
                )

            if isinstance(n, ast.Attribute):
                cls = resolve(n)
                if cls is not None and (
                    record_main or dom == BACKGROUND
                ):
                    record(cls, n.attr, fctx.path, n, dom,
                           lockset_for(n))
            # ``self.d[k] = v`` / ``del self.d[k]`` mutate the mapping
            # the attribute holds: a WRITE of the attribute for race
            # purposes even though the attribute node itself is only
            # loaded (the metrics-registry pattern). Mutator METHOD
            # calls (``.append``/``.update``) stay reads — documented
            # limitation in the module docstring.
            if isinstance(n, ast.Subscript) and \
                    isinstance(n.ctx, (ast.Store, ast.Del)) and \
                    isinstance(n.value, ast.Attribute):
                cls = resolve(n.value)
                if cls is not None and (
                    record_main or dom == BACKGROUND
                ):
                    site_attr = n.value
                    info = classes[cls]
                    if site_attr.attr not in info.member_names:
                        info.accesses.setdefault(
                            site_attr.attr, []
                        ).append(Site(
                            path=fctx.path, line=n.lineno,
                            col=n.col_offset, write=True,
                            domain=dom,
                            lockset=lockset_for(site_attr),
                        ))
            for child in ast.iter_child_nodes(n):
                rec(child, dom, now)

        body = fn.body if isinstance(fn.body, list) else [fn.body]
        for stmt in body:
            rec(stmt, domain, ())

    for rel, fctx in files.items():
        # class methods (self-based + typed cross-class accesses)
        for node in _iter_class_defs(fctx.tree):
            info = classes.get(node.name)
            if info is None or info.node is not node:
                # a class shadowed in the name index still contributes
                # CROSS-CLASS typed evidence (self-accesses cannot be
                # attributed — its own attr map was not indexed)
                for stmt in node.body:
                    if isinstance(stmt, _FUNC_NODES):
                        dom = (
                            BACKGROUND
                            if stmt.lineno in fctx.background_lines
                            else MAIN
                        )
                        walk_fn(stmt, fctx, None, None, dom)
                continue
            for mname, meth in info.methods.items():
                args = meth.args.posonlyargs + meth.args.args
                self_name = args[0].arg if args else None
                domain = (
                    BACKGROUND if mname in info.bg_methods else MAIN
                )
                # __init__'s MAIN-domain accesses are exempt —
                # construction happens-before any thread start — but
                # a background context it CREATES (a state-touching
                # lambda handed to a spawn wrapper) runs concurrently
                # with every later access and IS recorded
                walk_fn(
                    meth, fctx, info, self_name, domain,
                    record_main=mname != "__init__",
                )
        # module-level functions (typed cross-class accesses only)
        for node in ast.iter_child_nodes(fctx.tree):
            if isinstance(node, _FUNC_NODES):
                walk_fn(node, fctx, None, None, MAIN)


# ---------------------------------------------------------------------------
# the rule: race + stale-handoff reports
# ---------------------------------------------------------------------------


@repo_rule("PTA006", "lockset-races")
def lockset_races(repo: RepoContext) -> list[Violation]:
    c = repo.contracts
    files = files_enforcing(repo, "PTA006")
    classes = _collect_classes(repo, files)
    # a class is analyzed when it has background contexts (declared or
    # inferred) or a declared ThreadContract (whose handoffs must then
    # verify)
    interesting = {
        name for name, info in classes.items()
        if info.bg_methods or name in c.thread_classes
    }
    if not interesting:
        return []
    _collect_accesses(repo, files, classes, interesting)

    out: list[Violation] = []
    for name in sorted(interesting):
        info = classes[name]
        tc = c.thread_classes.get(name)
        declared = tc is not None
        if tc is None:
            tc = ThreadContract()
        for attr, sites in sorted(info.accesses.items()):
            domains = {s.domain for s in sites}
            writes = [s for s in sites if s.write]
            cross = len(domains) >= 2 and bool(writes)
            common = frozenset.intersection(
                *(s.lockset for s in sites)
            ) if sites else frozenset()
            if not cross:
                continue
            if common:
                continue  # consistently protected by one lock
            if attr in tc.handoffs:
                continue  # documented handoff (verified live below)
            bad = next(
                (s for s in writes if not s.lockset), None
            ) or next((s for s in sites if not s.lockset), sites[0])
            extra = (
                "" if declared else
                f"; declare a ThreadContract for {name} in "
                "analysis/contracts.py"
            )
            out.append(Violation(
                code="PTA006", rule="lockset-races",
                path=bad.path, line=bad.line, col=bad.col,
                message=(
                    f"{name}.{attr} is written cross-thread with no "
                    f"common lock (accessed from "
                    f"{' and '.join(sorted(domains))} across "
                    f"{len(sites)} site(s); designated lock "
                    f"self.{tc.lock_attr}): hold the lock at every "
                    "site or declare a documented handoff in "
                    f"analysis/contracts.py{extra}"
                ),
            ))
        # handoff verification: every declared entry must still name a
        # genuinely cross-thread, not-fully-locked attribute
        if declared:
            for attr in sorted(tc.handoffs):
                sites = info.accesses.get(attr, [])
                domains = {s.domain for s in sites}
                writes = [s for s in sites if s.write]
                why = None
                if not sites:
                    why = ("the attribute is never accessed outside "
                           "__init__")
                elif len(domains) < 2:
                    why = (f"every access is on the "
                           f"{next(iter(domains))} thread")
                elif not writes:
                    why = "no thread writes it after construction"
                elif frozenset.intersection(
                    *(s.lockset for s in sites)
                ):
                    why = ("every access already holds a common lock "
                           "— the handoff is redundant")
                if why is not None:
                    out.append(Violation(
                        code="PTA006", rule="lockset-races",
                        path=info.path, line=info.lineno, col=0,
                        message=(
                            f"stale handoff: {name}.{attr} is "
                            f"allowlisted in analysis/contracts.py "
                            f"but {why}; delete the entry (a stale "
                            "allowlist silently blesses the next "
                            "real race on this attribute)"
                        ),
                    ))
    return out
