"""The compiled-program auditor: trace the production kernels, walk
their closed jaxprs, and pin what the AST layer can only approximate.

PTA001 can prove no *syntactic* host sync sits in a hot scope; it
cannot see what the compiled program actually does. This module can:
it drives one tiny scheduling round through the REAL construction path
(synthetic cluster → FlowGraphBuilder → topology padding → the
resident solver's own argument prep), traces every production kernel
with ``jax.make_jaxpr`` on those tiny shapes, and asserts the compiled
contracts directly on the jaxprs:

- **zero host callbacks** — no ``*callback*`` / ``debug_print`` /
  infeed/outfeed primitives anywhere in the program (a stray
  ``jax.debug.print`` left from a debugging session silently syncs
  every dispatch);
- **zero smuggled transfers** — no ``device_put`` primitives inside
  the fused programs, and a bounded closure-constant census: a host
  array smuggled into a kernel (``jnp.asarray(host_val)`` where
  ``host_val`` is module state) becomes a tracing CONSTANT, so it
  shows up here as an oversized const or a const-census diff;
- **no f64 leaks** — the kernels run under ``enable_x64`` for the
  int64 domain arithmetic; no float64 aval may appear anywhere (a
  float64 table would double the HBM story AND desync from the TPU's
  f32-native layout);
- **a pinned per-kernel primitive-count fingerprint**
  (``analysis/kernel_fingerprints.json``): an accidental fusion break,
  a new transfer, or a silently changed reduction shows up as a CI
  diff at review time, not as a perf regression three PRs later.
  ``--update-fingerprints`` re-traces and rewrites the file; the diff
  then documents the intentional change in the PR.

Audited kernels (the production set): ``_solve`` (the eps-ladder
auction), ``_resident_chain`` (the whole fused round),
``_express_patch`` + ``_express_chain`` (the express lane),
``_stream_chain`` (the K-window streaming scan), and
``_solve_member`` (the service lane's bucket-member solve). The
fingerprint is a property of the TRACE, not the backend: the 8-device
CI lane re-runs the audit to prove the SPMD path sees the same
program (sharding changes layout, never primitives).

Violations carry code PTA008 so they ride the same reporting/CI
surface as the AST rules.
"""

from __future__ import annotations

import json
import pathlib
from collections import Counter as _Counter

import numpy as np

from poseidon_tpu.analysis.core import Violation

FINGERPRINT_FILE = "poseidon_tpu/analysis/kernel_fingerprints.json"

# a closure constant larger than this (bytes) inside a production
# kernel is a smuggled host array, full stop: the kernels take every
# table through explicit arguments, so legitimate consts are scalars
# and tiny index vectors
_CONST_BYTES_LIMIT = 256

_BANNED_PRIMITIVE_SUBSTRINGS = ("callback", "infeed", "outfeed")
_BANNED_PRIMITIVES = {"debug_print", "device_put", "copy"}


# ---------------------------------------------------------------------------
# jaxpr walking (duck-typed: ClosedJaxpr has .jaxpr/.consts, Jaxpr has
# .eqns — isinstance against jax internals churns across versions)
# ---------------------------------------------------------------------------


def _inner_jaxprs(params: dict):
    for v in params.values():
        vs = v if isinstance(v, (tuple, list)) else (v,)
        for x in vs:
            if hasattr(x, "jaxpr") and hasattr(x, "consts"):
                yield x.jaxpr, list(x.consts)
            elif hasattr(x, "eqns"):
                yield x, []


def _walk(closed):
    """Yield (jaxpr, consts) for the closed jaxpr and every nested
    sub-jaxpr (pjit bodies, scan/while/cond branches)."""
    stack = [(closed.jaxpr, list(closed.consts))]
    while stack:
        jaxpr, consts = stack.pop()
        yield jaxpr, consts
        for eqn in jaxpr.eqns:
            stack.extend(_inner_jaxprs(eqn.params))


def primitive_counts(closed) -> dict[str, int]:
    counts: _Counter = _Counter()
    for jaxpr, _consts in _walk(closed):
        for eqn in jaxpr.eqns:
            counts[eqn.primitive.name] += 1
    return dict(sorted(counts.items()))


def const_census(closed) -> tuple[int, int]:
    """(count, total bytes) of array constants across every level."""
    count = 0
    total = 0
    for _jaxpr, consts in _walk(closed):
        for c in consts:
            count += 1
            total += int(np.asarray(c).nbytes)
    return count, total


def _all_avals(closed):
    for jaxpr, _consts in _walk(closed):
        for v in jaxpr.invars + jaxpr.constvars + jaxpr.outvars:
            aval = getattr(v, "aval", None)
            if aval is not None:
                yield aval
        for eqn in jaxpr.eqns:
            for v in eqn.outvars:
                aval = getattr(v, "aval", None)
                if aval is not None:
                    yield aval


def structural_problems(name: str, closed) -> list[str]:
    """Contract violations independent of the committed fingerprint."""
    problems: list[str] = []
    counts = primitive_counts(closed)
    for prim, n in counts.items():
        if prim in _BANNED_PRIMITIVES or any(
            s in prim for s in _BANNED_PRIMITIVE_SUBSTRINGS
        ):
            problems.append(
                f"{name}: banned primitive '{prim}' x{n} in the "
                "compiled program (host callback / smuggled transfer "
                "— the fused chain must stay device-pure)"
            )
    for _jaxpr, consts in _walk(closed):
        for c in consts:
            arr = np.asarray(c)
            if arr.nbytes > _CONST_BYTES_LIMIT:
                problems.append(
                    f"{name}: {arr.nbytes}-byte closure constant "
                    f"(shape {arr.shape}, {arr.dtype}) baked into the "
                    "trace — a smuggled host array; every table must "
                    "enter through an explicit argument"
                )
    f64 = sorted({
        str(getattr(a, "shape", "?"))
        for a in _all_avals(closed)
        if getattr(a, "dtype", None) is not None
        and np.dtype(a.dtype) == np.float64
    })
    if f64:
        problems.append(
            f"{name}: float64 avals leak into the program (shapes "
            f"{', '.join(f64[:4])}) — the kernels are integer/f32 "
            "under x64 hygiene"
        )
    return problems


def fingerprint(closed) -> dict:
    count, nbytes = const_census(closed)
    return {
        "primitives": primitive_counts(closed),
        "const_count": count,
        "const_bytes": nbytes,
    }


def diff_fingerprint(name: str, got: dict, want: dict) -> list[str]:
    problems: list[str] = []
    gp, wp = got["primitives"], want.get("primitives", {})
    for prim in sorted(set(gp) | set(wp)):
        g, w = gp.get(prim, 0), wp.get(prim, 0)
        if g != w:
            problems.append(
                f"{name}: primitive '{prim}' count {g} != pinned {w} "
                "(fusion break / new op — if intentional, re-pin with "
                "--update-fingerprints and let the diff document it)"
            )
    for key in ("const_count", "const_bytes"):
        if got[key] != want.get(key, 0):
            problems.append(
                f"{name}: {key} {got[key]} != pinned {want.get(key, 0)}"
                " (a closure constant appeared or vanished)"
            )
    return problems


# ---------------------------------------------------------------------------
# tracing the production kernels on tiny shapes
# ---------------------------------------------------------------------------


def trace_production_kernels() -> dict[str, object]:
    """Drive one tiny round through the real construction path and
    return {kernel name: closed jaxpr} for the production set.

    The tiny round EXECUTES once (CPU-cheap at 8 machines / 12 tasks)
    because the express kernels take the solver's own warm context —
    tracing against hand-rolled lookalike arrays would audit a
    different program than production dispatches.
    """
    import jax

    from poseidon_tpu.compat import enable_x64
    from poseidon_tpu.graph.builder import FlowGraphBuilder
    from poseidon_tpu.models.costs import build_cost_inputs_host
    from poseidon_tpu.ops import resident as res
    from poseidon_tpu.ops.batch import (
        MEMBER_KEYS,
        _solve_member,
        member_bucket_dims,
        stack_members,
    )
    from poseidon_tpu.ops.dense_auction import _solve, build_member_tables
    from poseidon_tpu.ops.transport import (
        extract_topology,
        instance_from_topology,
    )
    from poseidon_tpu.synth import make_synthetic_cluster

    cluster = make_synthetic_cluster(
        8, 12, seed=11, machines_per_rack=4, max_tasks_per_machine=4,
        prefs_per_task=2, tasks_per_job=4,
    )
    arrays, meta = FlowGraphBuilder().build_arrays(cluster)
    solver = res.ResidentSolver(
        oracle_fallback=False, small_to_oracle=False,
        express_lane=True, express_max_batch=4,
    )
    outcome = solver.run_round(arrays, meta, cost_model="quincy")
    if not outcome.converged:
        raise RuntimeError(
            "jaxpr audit: the tiny bootstrap round did not certify"
        )
    ctx = solver._express
    if ctx is None:
        raise RuntimeError(
            "jaxpr audit: no express context after a certified round"
        )
    warm = solver._warm
    model_fn = ctx.model_fn
    kmax = solver.express_max_batch
    pk = ctx.n_prefs
    Tp, Mp = ctx.dev.c.shape

    arrival = res.ExpressArrival(
        uid="jaxpr-audit-pod", wait_rounds=0, cpu_milli=100,
        mem_kb=1 << 16, prefs=((0, -1, 3), (-1, 0, 5)),
    )
    solver._express_finalize(ctx)
    mini_host = solver._express_mini_inputs(ctx, [arrival], kmax, pk)
    add_row = np.full(kmax, -1, np.int32)
    add_row[0] = Tp - 1
    add_pm = np.full((kmax, pk), -1, np.int32)
    add_pr = np.full((kmax, pk), -1, np.int32)

    # the stream lane's [K, ...] event buffers: the same per-window
    # slices the synced lane takes, stacked along the batch axis (K=2
    # is enough — scan length never changes the traced program)
    skw = 2
    mini_stack = jax.tree_util.tree_map(
        lambda leaf: np.stack([np.asarray(leaf)] * skw), mini_host
    )
    add_row_s = np.stack([add_row] * skw)
    add_pm_s = np.stack([add_pm] * skw)
    add_pr_s = np.stack([add_pr] * skw)
    spw = solver._stream_pw_floor
    prow_s = np.full((skw, spw), -1, np.int32)
    pcol_s = np.full((skw, spw), -1, np.int32)
    pdelta_s = np.zeros((skw, spw), np.int32)

    # the service lane's stacked member tables (2 heterogeneous
    # members through the same scale-and-pad source production uses)
    topo = extract_topology(
        meta, arrays["src"], arrays["dst"], arrays["cap"]
    )
    cost_host = np.asarray(
        jax.device_get(ctx.cost_dev), np.int64
    )[: meta.n_arcs]
    inst = instance_from_topology(topo, cost_host)
    bTp, bMp, bP = member_bucket_dims(inst)
    members = [
        build_member_tables(inst, bTp, bMp, bP) for _ in range(2)
    ]
    stacked = stack_members(members, 2)
    bsmax = max(min(int(np.max(members[0]["slots"], initial=0)), bTp), 1)

    zeros_t = np.zeros(Tp, np.int32)
    zeros_bt = np.zeros(bTp, np.int32)
    zeros_bm = np.zeros(bMp, np.int32)
    patch_w = res._EXPRESS_PATCH_CHUNK
    with enable_x64(True):
        traces = {  # noqa: PTA007 -- one-shot audit bootstrap: each kernel is traced exactly once per run on pinned tiny shapes; there is no steady state to protect
            "solve": jax.make_jaxpr(
                lambda dev, a, l, f, e: _solve(
                    dev, a, l, f, e, alpha=solver.alpha,
                    max_rounds=64, smax=ctx.smax,
                    analytic_init=False,
                )
            )(ctx.dev, warm.asg, warm.lvl, warm.floor, np.int32(1)),
            "resident_chain": jax.make_jaxpr(
                lambda dt, inp, a, l, f: res._resident_chain(
                    dt, inp, a, l, f, model_fn=model_fn,
                    n_prefs=pk, smax=ctx.smax, alpha=solver.alpha,
                    max_rounds=64, warm_start=False,
                )
            )(
                ctx.dt,
                # the round's pricing inputs, rebuilt exactly as
                # begin_round padded them (its floors are still live
                # on the solver)
                build_cost_inputs_host(
                    solver._e_floor, meta,
                    t_min=solver._ti_floor, m_min=solver._mi_floor,
                ),
                zeros_t, zeros_t, np.zeros(Mp, np.int32),
            ),
            "express_patch": jax.make_jaxpr(
                lambda u, w, tv, s, a, l, r, c, d: res._express_patch(
                    u, w, tv, s, a, l, r, c, d
                )
            )(
                ctx.dev.u, ctx.dev.w, ctx.dev.task_valid, ctx.dev.s,
                warm.asg, warm.lvl,
                np.full(patch_w, -1, np.int32),
                np.full(patch_w, -1, np.int32),
                np.zeros(patch_w, np.int32),
            ),
            "express_chain": jax.make_jaxpr(
                lambda dev, dt, cost, mini, a, l, f, ar, pm, pr:
                res._express_chain(
                    dev, dt, cost, mini, a, l, f, ar, pm, pr,
                    model_fn=model_fn, kmax=kmax, pk=pk,
                    alpha=solver.alpha, max_rounds=res.EXPRESS_FUSE,
                    smax=ctx.smax,
                    change_cap=solver.express_change_cap,
                )
            )(
                ctx.dev, ctx.dt, ctx.cost_dev, mini_host,
                warm.asg, warm.lvl, warm.floor,
                add_row, add_pm, add_pr,
            ),
            "stream_chain": jax.make_jaxpr(
                lambda dev, dt, cost, mini, a, l, f, ar, pm, pr,
                prw, pcl, pdl:
                res._stream_chain(
                    dev, dt, cost, mini, a, l, f, ar, pm, pr,
                    prw, pcl, pdl,
                    model_fn=model_fn, kmax=kmax, pk=pk,
                    alpha=solver.alpha, max_rounds=res.EXPRESS_FUSE,
                    smax=ctx.smax,
                    change_cap=solver.express_change_cap,
                )
            )(
                ctx.dev, ctx.dt, ctx.cost_dev, mini_stack,
                warm.asg, warm.lvl, warm.floor,
                add_row_s, add_pm_s, add_pr_s,
                prow_s, pcol_s, pdelta_s,
            ),
            "solve_member": jax.make_jaxpr(
                lambda *args: _solve_member(
                    *args, n_prefs=bP, smax=bsmax, alpha=solver.alpha,
                    max_rounds=64, warm_start=False,
                )
            )(
                *(stacked[k] for k in MEMBER_KEYS), np.int32(0),
                zeros_bt, zeros_bt, zeros_bm,
            ),
        }
    return traces


# ---------------------------------------------------------------------------
# the audit entry point
# ---------------------------------------------------------------------------


def run_jaxpr_audit(
    root: pathlib.Path, *, update: bool = False, traces=None
) -> tuple[list[Violation], int]:
    """Trace, check structure, and diff against the committed
    fingerprints. Returns (violations, kernels audited). ``update``
    rewrites ``kernel_fingerprints.json`` instead of diffing.
    ``traces`` reuses an already-traced kernel set (the tests trace
    once and drive every audit path from it)."""
    fp_path = root / FINGERPRINT_FILE
    if traces is None:
        traces = trace_production_kernels()
    violations: list[Violation] = []

    def flag(msg: str):
        violations.append(Violation(
            code="PTA008", rule="jaxpr-audit",
            path=FINGERPRINT_FILE, line=1, col=0, message=msg,
        ))

    got = {name: fingerprint(t) for name, t in traces.items()}
    for name, t in traces.items():
        for p in structural_problems(name, t):
            flag(p)

    if update:
        fp_path.write_text(json.dumps(
            {
                "_comment": (
                    "Pinned per-kernel primitive-count fingerprints "
                    "(python -m poseidon_tpu.analysis "
                    "--update-fingerprints). A diff here means the "
                    "compiled programs changed: say why in the PR."
                ),
                "kernels": got,
            },
            indent=2, sort_keys=True,
        ) + "\n")
        return violations, len(traces)

    if not fp_path.is_file():
        flag(
            f"{FINGERPRINT_FILE} is missing: run python -m "
            "poseidon_tpu.analysis --update-fingerprints and commit it"
        )
        return violations, len(traces)
    want = json.loads(fp_path.read_text()).get("kernels", {})
    for name in sorted(set(got) | set(want)):
        if name not in got:
            flag(
                f"{name}: pinned in {FINGERPRINT_FILE} but no longer "
                "traced — remove the stale entry with "
                "--update-fingerprints"
            )
            continue
        if name not in want:
            flag(
                f"{name}: traced but not pinned in {FINGERPRINT_FILE} "
                "— add it with --update-fingerprints"
            )
            continue
        for p in diff_fingerprint(name, got[name], want[name]):
            flag(p)
    return violations, len(traces)
