"""CLI entry: ``python -m poseidon_tpu.analysis [options] [paths]``.

Exit codes: 0 clean, 1 violations found, 2 usage error. CI runs
``python -m poseidon_tpu.analysis --format=json --audit-suppressions``
as a blocking step (after ruff, before the test suite) and the jaxpr
kernel audit (``--jaxpr``) on both the plain and 8-virtual-device
lanes.

Passes:

- the AST rules (always): PTA001-PTA005 file/repo rules plus the
  whole-program passes — PTA006 (lockset race detection over the
  thread model) and PTA007 (recompile-hazard static-arg provenance);
- ``--audit-suppressions``: additionally report DEAD ``# noqa:
  PTA0xx`` comments (rule no longer fires on that statement);
- ``--jaxpr``: additionally trace the production kernels and audit
  their closed jaxprs against ``analysis/kernel_fingerprints.json``
  (PTA008). ``--jaxpr-only`` runs just that audit (the CI audit step
  — its lint step already ran the AST rules). ``--update-fingerprints``
  re-pins the file instead of diffing (structural contract problems
  still report).

The JSON document's schema is load-bearing for CI and downstream
tooling and is locked by tests/test_analysis.py::TestJsonSchema:
``violations`` (objects with exactly code/rule/path/line/col/message,
sorted by (path, line, col, code)), ``count``, ``files_scanned``, and
— only when ``--jaxpr`` ran — ``kernels_audited``.
"""

from __future__ import annotations

import argparse
import pathlib
import sys

from poseidon_tpu.analysis.core import (
    analyze_and_audit,
    analyze_tree,
    format_human,
    format_json,
)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m poseidon_tpu.analysis",
        description=(
            "Contract linter: enforce the repo's hot-path, O(churn), "
            "jit-hygiene, thread-discipline, surface-consistency, "
            "lockset-race and recompile-hazard invariants (rules "
            "PTA001-PTA007; see analysis/rules.py, analysis/"
            "threads.py, analysis/recompile.py), plus the compiled-"
            "kernel jaxpr audit (PTA008, analysis/jaxpr_check.py)"
        ),
    )
    p.add_argument(
        "paths", nargs="*",
        help="files to scan (default: the shipped tree — "
             "poseidon_tpu/, scripts/, tests/, bench.py)",
    )
    p.add_argument(
        "--format", choices=("human", "json"), default="human",
        help="output format (json for CI)",
    )
    p.add_argument(
        "--root", default=".",
        help="repo root (scopes and doc files resolve against it)",
    )
    p.add_argument(
        "--audit-suppressions", action="store_true",
        help="also report dead '# noqa: PTA0xx' suppressions "
             "(reasoned noqas whose rule no longer fires there)",
    )
    p.add_argument(
        "--jaxpr", action="store_true",
        help="also trace the production kernels and audit their "
             "closed jaxprs (callbacks/transfers/f64/fingerprints)",
    )
    p.add_argument(
        "--jaxpr-only", action="store_true",
        help="run ONLY the kernel jaxpr audit, skipping the AST rules "
             "(the CI audit step: the lint step already ran them)",
    )
    p.add_argument(
        "--update-fingerprints", action="store_true",
        help="re-trace the kernels and rewrite analysis/"
             "kernel_fingerprints.json (implies --jaxpr)",
    )
    args = p.parse_args(argv)

    root = pathlib.Path(args.root).resolve()
    paths = None
    if args.paths:
        paths = []
        for raw in args.paths:
            path = pathlib.Path(raw).resolve()
            if not path.exists():
                print(f"no such file: {raw}", file=sys.stderr)
                return 2
            if not path.is_relative_to(root):
                print(
                    f"{raw} is outside --root {root} (scopes are "
                    "declared root-relative)", file=sys.stderr,
                )
                return 2
            if path.is_dir():
                paths.extend(sorted(path.rglob("*.py")))
            else:
                paths.append(path)
    if args.jaxpr_only:
        violations, files_scanned = [], 0
    else:
        run = (
            analyze_and_audit if args.audit_suppressions
            else analyze_tree
        )
        violations, files_scanned = run(root, paths)
    kernels_audited = None
    if args.jaxpr or args.jaxpr_only or args.update_fingerprints:
        from poseidon_tpu.analysis.jaxpr_check import run_jaxpr_audit

        jaxpr_violations, kernels_audited = run_jaxpr_audit(
            root, update=args.update_fingerprints
        )
        # the merged document keeps the locked (path, line, col, code)
        # ordering whichever passes contributed
        violations = sorted(
            violations + jaxpr_violations,
            key=lambda v: (v.path, v.line, v.col, v.code),
        )

    if args.format == "json":
        print(format_json(violations, files_scanned, kernels_audited))
    else:
        out = format_human(violations, files_scanned)
        if kernels_audited is not None:
            out += f"\n{kernels_audited} kernel jaxpr(s) audited"
        print(out)
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
