"""CLI entry: ``python -m poseidon_tpu.analysis [options] [paths]``.

Exit codes: 0 clean, 1 violations found, 2 usage error. CI runs
``python -m poseidon_tpu.analysis --format=json --audit-suppressions``
as a blocking step (after ruff, before the test suite) and the jaxpr
soundness audits (``--jaxpr``) on both the plain and 8-virtual-device
lanes.

Passes:

- the AST rules (always): PTA001-PTA005 file/repo rules plus the
  whole-program passes — PTA006 (lockset race detection over the
  thread model), PTA007 (recompile-hazard static-arg provenance) and
  PTA010 (lock-order deadlock + no-blocking-under-lock);
- ``--audit-suppressions``: additionally report DEAD ``# noqa:
  PTA0xx`` comments (rule no longer fires on that statement);
- ``--jaxpr``: additionally trace the production kernels and audit
  their closed jaxprs — the fingerprint/structure audit (PTA008,
  ``analysis/kernel_fingerprints.json``) and the padding-taint
  dataflow audit (PTA009, ``analysis/padding_taint.py``) share one
  trace. ``--jaxpr-only`` runs just those audits (the CI audit step —
  its lint step already ran the AST rules). ``--update-fingerprints``
  re-pins the fingerprint file instead of diffing (structural
  contract problems still report);
- ``--rule PTA0NN[,PTA0MM]``: run only the named rule(s) — CI lanes
  and local iteration isolate one pass without paying for the rest
  (an unknown code exits 2: a typo'd rule id must not ride a green
  stamp, exactly like a typo'd path). Selecting no jaxpr-backed rule
  skips tracing; selecting ONLY jaxpr-backed rules skips the AST
  walk.

A path argument that exists but contains no Python targets is a usage
error (exit 2), not a clean run: a typo'd CI path must fail loudly.

The JSON document's schema is load-bearing for CI and downstream
tooling and is locked by tests/test_analysis.py::TestJsonSchema:
``violations`` (objects with exactly code/rule/path/line/col/message,
sorted by (path, line, col, code)), ``count``, ``files_scanned``, and
— only when ``--jaxpr`` ran — ``kernels_audited``.
"""

from __future__ import annotations

import argparse
import pathlib
import sys

from poseidon_tpu.analysis.core import (
    FILE_RULES,
    REPO_RULES,
    _ensure_rules_loaded,
    analyze_and_audit,
    analyze_tree,
    format_human,
    format_json,
)

# codes not produced by a registered AST rule: PTA000 comes from the
# parser/suppression layer, PTA008/PTA009 from the jaxpr audits
_EXTRA_CODES = ("PTA000", "PTA008", "PTA009")
_JAXPR_CODES = frozenset(("PTA008", "PTA009"))


def _known_codes() -> set[str]:
    _ensure_rules_loaded()
    codes = {code for code, _name, _fn in FILE_RULES}
    codes.update(code for code, _name, _fn in REPO_RULES)
    codes.update(_EXTRA_CODES)
    return codes


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m poseidon_tpu.analysis",
        description=(
            "Contract linter: enforce the repo's hot-path, O(churn), "
            "jit-hygiene, thread-discipline, surface-consistency, "
            "lockset-race, recompile-hazard and lock-order invariants "
            "(rules PTA001-PTA007, PTA010; see analysis/rules.py, "
            "analysis/threads.py, analysis/recompile.py, analysis/"
            "locks.py), plus the compiled-kernel jaxpr audits "
            "(PTA008 fingerprints, analysis/jaxpr_check.py; PTA009 "
            "padding-taint dataflow, analysis/padding_taint.py)"
        ),
    )
    p.add_argument(
        "paths", nargs="*",
        help="files to scan (default: the shipped tree — "
             "poseidon_tpu/, scripts/, tests/, bench.py)",
    )
    p.add_argument(
        "--format", choices=("human", "json"), default="human",
        help="output format (json for CI)",
    )
    p.add_argument(
        "--root", default=".",
        help="repo root (scopes and doc files resolve against it)",
    )
    p.add_argument(
        "--rule", default=None, metavar="PTA0NN[,PTA0MM]",
        help="run only the named rule(s); unknown codes exit 2",
    )
    p.add_argument(
        "--audit-suppressions", action="store_true",
        help="also report dead '# noqa: PTA0xx' suppressions "
             "(reasoned noqas whose rule no longer fires there)",
    )
    p.add_argument(
        "--jaxpr", action="store_true",
        help="also trace the production kernels and audit their "
             "closed jaxprs (callbacks/transfers/f64/fingerprints "
             "via PTA008, padding-taint dataflow via PTA009)",
    )
    p.add_argument(
        "--jaxpr-only", action="store_true",
        help="run ONLY the kernel jaxpr audits, skipping the AST "
             "rules (the CI audit step: the lint step already ran "
             "them)",
    )
    p.add_argument(
        "--update-fingerprints", action="store_true",
        help="re-trace the kernels and rewrite analysis/"
             "kernel_fingerprints.json (implies --jaxpr)",
    )
    args = p.parse_args(argv)

    selection: set[str] | None = None
    if args.rule is not None:
        selection = {c.strip() for c in args.rule.split(",") if c.strip()}
        unknown = selection - _known_codes()
        if not selection or unknown:
            bad = ", ".join(sorted(unknown)) or "(empty)"
            print(
                f"unknown rule id(s): {bad} — known: "
                + ", ".join(sorted(_known_codes())),
                file=sys.stderr,
            )
            return 2

    root = pathlib.Path(args.root).resolve()
    paths = None
    if args.paths:
        paths = []
        for raw in args.paths:
            path = pathlib.Path(raw).resolve()
            if not path.exists():
                print(f"no such file: {raw}", file=sys.stderr)
                return 2
            if not path.is_relative_to(root):
                print(
                    f"{raw} is outside --root {root} (scopes are "
                    "declared root-relative)", file=sys.stderr,
                )
                return 2
            if path.is_dir():
                paths.extend(sorted(path.rglob("*.py")))
            else:
                paths.append(path)
        if not paths:
            # a target that exists but holds no Python files is a
            # typo'd CI path, not a clean tree: refuse the green stamp
            print(
                "no Python targets under: "
                + " ".join(args.paths)
                + " — pass files or directories containing .py "
                "files (usage error, exit 2)",
                file=sys.stderr,
            )
            return 2

    jaxpr_requested = (
        args.jaxpr or args.jaxpr_only or args.update_fingerprints
    )
    run_ast = not args.jaxpr_only and (
        selection is None or bool(selection - _JAXPR_CODES)
    )
    run_pta008 = args.update_fingerprints or (
        jaxpr_requested and (selection is None or "PTA008" in selection)
    )
    run_pta009 = jaxpr_requested and (
        selection is None or "PTA009" in selection
    )

    if run_ast:
        run = (
            analyze_and_audit if args.audit_suppressions
            else analyze_tree
        )
        violations, files_scanned = run(root, paths)
    else:
        violations, files_scanned = [], 0
    kernels_audited = None
    if run_pta008 or run_pta009:
        from poseidon_tpu.analysis.jaxpr_check import (
            trace_production_kernels,
        )

        # both jaxpr audits read the same traces; trace once
        traces = trace_production_kernels()
        if run_pta008:
            from poseidon_tpu.analysis.jaxpr_check import run_jaxpr_audit

            jaxpr_violations, kernels_audited = run_jaxpr_audit(
                root, update=args.update_fingerprints, traces=traces
            )
            violations = violations + jaxpr_violations
        if run_pta009:
            from poseidon_tpu.analysis.padding_taint import (
                run_padding_audit,
            )

            taint_violations, kernels_audited = run_padding_audit(
                root, traces=traces
            )
            violations = violations + taint_violations
    if selection is not None:
        violations = [v for v in violations if v.code in selection]
    # the merged document keeps the locked (path, line, col, code)
    # ordering whichever passes contributed
    violations = sorted(
        violations, key=lambda v: (v.path, v.line, v.col, v.code)
    )

    if args.format == "json":
        print(format_json(violations, files_scanned, kernels_audited))
    else:
        out = format_human(violations, files_scanned)
        if kernels_audited is not None:
            out += f"\n{kernels_audited} kernel jaxpr(s) audited"
        print(out)
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
