"""CLI entry: ``python -m poseidon_tpu.analysis [--format=...] [paths]``.

Exit codes: 0 clean, 1 violations found, 2 usage error. CI runs
``python -m poseidon_tpu.analysis --format=json`` as a blocking step
(after ruff, before the test suite).
"""

from __future__ import annotations

import argparse
import pathlib
import sys

from poseidon_tpu.analysis.core import (
    analyze_tree,
    format_human,
    format_json,
)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m poseidon_tpu.analysis",
        description=(
            "Contract linter: enforce the repo's hot-path, O(churn), "
            "jit-hygiene, thread-discipline, and surface-consistency "
            "invariants (rules PTA001-PTA005; see analysis/rules.py)"
        ),
    )
    p.add_argument(
        "paths", nargs="*",
        help="files to scan (default: the shipped tree — "
             "poseidon_tpu/, scripts/, bench.py)",
    )
    p.add_argument(
        "--format", choices=("human", "json"), default="human",
        help="output format (json for CI)",
    )
    p.add_argument(
        "--root", default=".",
        help="repo root (scopes and doc files resolve against it)",
    )
    args = p.parse_args(argv)

    root = pathlib.Path(args.root).resolve()
    paths = None
    if args.paths:
        paths = []
        for raw in args.paths:
            path = pathlib.Path(raw).resolve()
            if not path.exists():
                print(f"no such file: {raw}", file=sys.stderr)
                return 2
            if not path.is_relative_to(root):
                print(
                    f"{raw} is outside --root {root} (scopes are "
                    "declared root-relative)", file=sys.stderr,
                )
                return 2
            if path.is_dir():
                paths.extend(sorted(path.rglob("*.py")))
            else:
                paths.append(path)
    violations, files_scanned = analyze_tree(root, paths)
    formatter = format_json if args.format == "json" else format_human
    print(formatter(violations, files_scanned))
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
