"""The declared contracts the lint rules check against.

Everything repo-specific lives here, separate from the rule logic, so
(a) a reviewer can see the whole enforced surface in one file and
(b) the analyzer tests can run the same rules against synthetic
contracts pointed at snippet trees.

Paths are repo-root-relative POSIX suffixes: a file matches a scope
entry when its normalized path ENDS WITH the entry, so the same
contracts work on the real tree and on a test-built mirror of it.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class ThreadContract:
    """PTA004 per-class declaration.

    ``lock_attr`` names the designated lock (``with self.<lock_attr>:``
    satisfies the rule at a conflicting access site). ``handoffs`` maps
    attribute name -> the documented reason the cross-thread access is
    safe WITHOUT the lock (a queue, an Event happens-before pair, a
    benign-race close). Background contexts are not listed here — they
    are declared next to the code with a ``# pta: background-thread``
    marker comment on the ``def`` line, so the declaration cannot drift
    from the thread that actually runs the function.
    """

    lock_attr: str = "_lock"
    handoffs: dict[str, str] = dataclasses.field(default_factory=dict)


# the observability recording/span-assembly scopes run INSIDE the
# round's finish/actuate window and the express fast path: hot under
# BOTH PTA001 (no host sync) and PTA002 (no O(cluster) walk) from day
# one — one constant referenced from both maps so the two enforcement
# surfaces cannot drift apart
_OBS_HOT_SCOPES = {
    "poseidon_tpu/obs/flightrec.py": (
        # the flight recorder's capture helpers run inside the round's
        # begin/finish window and the express fast path: vectorized
        # np copies of already-host arrays only — never a device sync,
        # never an O(cluster) Python walk (the dump WRITER is not
        # listed: it runs on the anomaly/on-demand path, off the
        # round's critical path by design)
        "FlightRecorder.capture_begin",
        "FlightRecorder.capture_finish",
        "FlightRecorder.capture_express",
        "FlightRecorder._trim",
        "_copy_meta",
    ),
    "poseidon_tpu/obs/lifecycle.py": (
        # lifecycle stamps run inside the round window and the express
        # fast path: dict ops + clock reads only (note_unscheduled's
        # percentile runs over the age list the caller's existing
        # unscheduled walk produced — no second walk, no device)
        "LifecycleTracker.stamp_event",
        "LifecycleTracker.backdate_event",
        "LifecycleTracker.stamp",
        "LifecycleTracker.stamp_decided",
        "LifecycleTracker.event_wall_us",
        "LifecycleTracker.close_confirmed",
        "LifecycleTracker.close_replayed",
        "LifecycleTracker.drop",
        "LifecycleTracker.note_unscheduled",
        "bounded_lane",
    ),
    "poseidon_tpu/obs/metrics.py": (
        "Counter.inc",
        "Gauge.set",
        "Histogram.observe",
        "SchedulerMetrics.record_pod_e2c",
        "SchedulerMetrics.record_unsched_wait",
        "SchedulerMetrics.record_lifecycle_dropped",
        "SchedulerMetrics.record_trace_dropped",
        "SchedulerMetrics.record_predicted_bytes",
        "SchedulerMetrics.record_round",
        "SchedulerMetrics.record_degrade",
        "SchedulerMetrics.record_express_batch",
        "SchedulerMetrics.record_express_degrade",
        "SchedulerMetrics.record_resync",
        "SchedulerMetrics.record_reconnect",
        "SchedulerMetrics.record_solver_round",
        "SchedulerMetrics.record_express_fetch",
        "SchedulerMetrics.record_stream_fetch",
        "SchedulerMetrics.record_stream_flush",
        "SchedulerMetrics.record_service_round",
        "SchedulerMetrics.record_service_dispatch",
        "SchedulerMetrics.record_service_compiles",
        "SchedulerMetrics.record_checkpoint",
        "SchedulerMetrics.record_checkpoint_age",
        "SchedulerMetrics.record_journal_replay",
        "SchedulerMetrics.record_restore",
        # failure-domain recorders: guard hold/release fire inside
        # the observe path, outage/outbox/shed/watchdog inside the
        # driver tick — all host ints already in hand
        "SchedulerMetrics.record_guard_hold",
        "SchedulerMetrics.record_guard_release",
        "SchedulerMetrics.record_outage",
        "SchedulerMetrics.record_outbox",
        "SchedulerMetrics.record_express_shed",
        "SchedulerMetrics.record_deadline_miss",
        "SchedulerMetrics.record_overload_cleared",
    ),
    "poseidon_tpu/obs/spans.py": (
        "round_span_tree",
        "express_span_tree",
        "stream_span_tree",
        "emit_span",
    ),
}


@dataclasses.dataclass(frozen=True)
class Contracts:
    """The full declared surface consumed by the rules."""

    # ---- PTA001: hot-path scopes (no host syncs) ----------------------
    # whole files whose every function is hot
    hot_path_files: tuple[str, ...] = ()
    # path suffix -> qualified function names ("Class.method"); nested
    # functions inherit their enclosing scope
    hot_path_functions: dict[str, tuple[str, ...]] = dataclasses.field(
        default_factory=dict
    )
    # dotted-name prefixes / bare callables whose results are device
    # arrays (the int()/float() taint sources)
    device_producers: tuple[str, ...] = ()
    # producers excluded from taint even though they match a prefix
    # (jax.device_get RESULTS are host arrays)
    device_producer_exceptions: tuple[str, ...] = ()

    # ---- PTA002: O(churn) scopes (no cluster-sized loops) -------------
    ochurn_functions: dict[str, tuple[str, ...]] = dataclasses.field(
        default_factory=dict
    )
    # terminal attribute/variable names that hold cluster-sized
    # collections (iterating one of these in an O(churn) scope flags)
    cluster_sized_names: tuple[str, ...] = ()

    # ---- PTA004 + PTA006: thread discipline ---------------------------
    thread_classes: dict[str, ThreadContract] = dataclasses.field(
        default_factory=dict
    )
    # PTA006 spawn inference: callables that run a callable argument on
    # a background thread (the repo's thread-launching wrappers). A
    # lambda / function reference passed to one of these is a thread
    # root: its body executes concurrently with the caller.
    thread_spawn_wrappers: tuple[str, ...] = ()

    # ---- PTA007: recompile-hazard dataflow ----------------------------
    # attribute reads that are data-dependent quantities (live-state
    # maxima, per-round counts): deriving a static arg or pad floor
    # from one of these without riding a grow-only floor is the
    # recompile bug class PR 8 had to flush out at runtime
    hazard_attrs: tuple[str, ...] = ()
    # name fragments that mark a value as riding a grow-only floor
    # (matching is substring for "floor", exact for the pad-parameter
    # vocabulary): an expression referencing one of these is sanctified
    floor_markers: tuple[str, ...] = ()
    # host padding helpers whose listed keyword args are SHAPE floors:
    # a tainted, un-floored value flowing into one of these recompiles
    # the fused chain exactly like a tainted static arg
    pad_sinks: dict[str, tuple[str, ...]] = dataclasses.field(
        default_factory=dict
    )

    # ---- per-path rule scoping ----------------------------------------
    # (path prefix, codes enforced there); first match wins, files
    # matching no entry get every rule. tests/ runs the jit-hygiene +
    # vocabulary rules only (test files deliberately contain seeded
    # violations for the rest, as data)
    path_rules: tuple[tuple[str, tuple[str, ...]], ...] = ()

    # ---- PTA005: trace vocabulary + flag surface ----------------------
    trace_module: str = "poseidon_tpu/trace.py"
    trace_vocab_name: str = "EVENT_TYPES"
    flag_module: str = "poseidon_tpu/cli.py"
    flag_doc_files: tuple[str, ...] = ("README.md", "deploy/poseidon-tpu.cfg")
    # metric-name drift: every ``poseidon_*`` family registered in the
    # metrics module must appear in the doc file's observability
    # reference, and every family the doc names must still be
    # registered (a renamed family silently orphans dashboards)
    metrics_module: str = "poseidon_tpu/obs/metrics.py"
    metrics_doc_file: str = "README.md"

    # ---- PTA009: per-kernel mask contracts ----------------------------
    # kernel name -> ((primitive, function, reason), ...): reductions
    # that consume padding-tainted operands SAFELY — the padded lanes
    # are benign by construction (INF fills, zero-weight rows) rather
    # than by a visible select_n mask. Verified live both ways: an
    # unsanctioned tainted reduction is a violation, and a sanction no
    # trace exercises is reported stale (the PTA006 handoff
    # discipline).
    kernel_mask_contracts: dict[
        str, tuple[tuple[str, str, str], ...]
    ] = dataclasses.field(default_factory=dict)

    # ---- PTA010: lock-order + no-blocking-under-lock ------------------
    # terminal callable/method names that BLOCK (filesystem barriers,
    # apiserver round-trips, solver dispatch): executing one while any
    # lock is held stalls every thread contending that lock for the
    # call's full latency. ``.join()``/``queue.put(block=True)`` are
    # recognized structurally by the rule; this vocabulary covers the
    # repo's I/O surface. Plain buffered ``.write``/``.flush`` are NOT
    # blocking (page-cache writes — the journal's write-under-lock is
    # by design; only the fsync barrier must leave the region).
    blocking_call_names: tuple[str, ...] = ()


# The marker comment declaring a function runs on a background thread
# (PTA004). Lives on the ``def`` line:  def run(self):  # pta: background-thread
BACKGROUND_MARKER = "pta: background-thread"


DEFAULT_CONTRACTS = Contracts(
    hot_path_files=(
        # the whole resident round is the hot path: ONE upload, ONE
        # fused program, ONE sanctioned fetch (module docstring)
        "poseidon_tpu/ops/resident.py",
    ),
    hot_path_functions={
        # the incremental-build path: O(churn) numpy patching, never a
        # device sync
        "poseidon_tpu/graph/builder.py": (
            "IncrementalFlowGraphBuilder.build_arrays",
            "IncrementalFlowGraphBuilder._apply_deltas",
        ),
        # the begin_round -> finish_round window the pipelined driver
        # overlaps host work under, plus the express fast path (the
        # event-to-bind latency budget is single-digit ms: one
        # dispatch, one sanctioned fetch, no host syncs)
        "poseidon_tpu/bridge/bridge.py": (
            "SchedulerBridge.begin_round",
            "SchedulerBridge.finish_round",
            "SchedulerBridge.express_batch",
            "SchedulerBridge.stream_window",
            "SchedulerBridge.stream_flush",
            "SchedulerBridge.stream_finish",
            "SchedulerBridge._express_transitions",
        ),
        # the scale lane: aggregation planning/expansion runs inside
        # the resident round (hot from day one — pure vectorized host
        # numpy, no device syncs)
        "poseidon_tpu/graph/aggregate.py": (
            "plan_from_costs",
            "plan_from_signatures",
            "aggregate_topology",
            "prune_topology_prefs",
            "expand_assignment",
            "_plan_from_keys",
            "_pinned_mask",
            "_float_bits",
        ),
        # the sharded-round layout helper: explicit device_put only
        "poseidon_tpu/parallel/sharded.py": (
            "resident_round_shardings",
        ),
        # the service lane (multi-tenant batching): begin prices on the
        # CPU backend (its fetch never crosses the device link, the one
        # noqa'd site), launch does one explicit upload + per-member
        # dispatches, finish joins the chunk's ONE sanctioned batched
        # fetch — no other host sync may slip into the dispatch window
        "poseidon_tpu/service/dispatch.py": (
            "TenantSolver.begin_round",
            "TenantSolver.finish_round",
            "BatchDispatcher.register",
            "BatchDispatcher.launch",
            "BatchDispatcher._stage_chunk",
            "BatchDispatcher._dispatch_chunk",
            "BatchDispatcher.finish",
        ),
        # the front door pipeline: pure host bookkeeping (queues,
        # futures, stats) — never a device call of its own
        "poseidon_tpu/service/service.py": (
            "SchedulingService.pump",
            "SchedulingService._finish_wave",
            "SchedulingService._account",
        ),
        # the checkpoint capture path (ha/checkpoint.py) runs on the
        # driver thread right after a round: shallow dict copies +
        # host-array copies only, never a device sync (the warm seed
        # is the mirror the round's own fetch already downloaded); it
        # is deliberately NOT an O(churn) scope — the amortized-
        # cadence O(cluster) dict copy is its documented design
        "poseidon_tpu/ha/checkpoint.py": (
            "capture_snapshot",
            "CheckpointManager.capture",
        ),
        # the shadow audit's capture (obs/audit.py) runs on the
        # driver thread at the sampling cadence: list/array copies of
        # host data only, never a device sync. Like the checkpoint
        # capture it is deliberately NOT an O(churn) scope — the
        # amortized-cadence O(cluster) copy is its documented design
        # (the audit WORKER runs on its own background thread, off
        # every hot path, and is deliberately unlisted)
        "poseidon_tpu/obs/audit.py": (
            "ShadowAuditor.due",
            "ShadowAuditor.capture",
        ),
        # observability recording + span assembly (_OBS_HOT_SCOPES):
        # pure host arithmetic on values the caller already fetched,
        # never a new device sync
        **_OBS_HOT_SCOPES,
    },
    device_producers=(
        "jnp.",
        "jax.",
        # the fused resident chain + its jitted pieces
        "_resident_chain",
        "_redensify",
        "_finalize",
        "_express_chain",
        "_express_step",
        "_stream_chain",
        "_express_patch",
        "_solve",
        "_solve_member",
        "_densify",
        "cold_start",
        "model_fn",
        "_jitted_model",
    ),
    device_producer_exceptions=(
        "jax.device_get",   # result is HOST data
    ),
    ochurn_functions={
        # express_batch / _express_transitions / express_round run per
        # EVENT BATCH, between ticks: an O(cluster) walk there would
        # turn the single-digit-ms lane back into a round
        "poseidon_tpu/bridge/bridge.py": (
            "SchedulerBridge.begin_round",
            "SchedulerBridge.finish_round",
            "SchedulerBridge.express_batch",
            "SchedulerBridge.stream_window",
            "SchedulerBridge.stream_flush",
            "SchedulerBridge.stream_finish",
            "SchedulerBridge._express_transitions",
        ),
        "poseidon_tpu/graph/builder.py": (
            "IncrementalFlowGraphBuilder.build_arrays",
            "IncrementalFlowGraphBuilder._apply_deltas",
        ),
        "poseidon_tpu/ops/resident.py": (
            "ResidentSolver.begin_round",
            "ResidentSolver.finish_round",
            "ResidentSolver.express_round",
            # the stream lane runs per event WINDOW between ticks,
            # same latency budget as the express fast path
            "ResidentSolver.stream_window",
            "ResidentSolver.stream_flush",
            "ResidentSolver.stream_finish",
            "ResidentSolver._stream_apply_freeze",
            # the express context's lazy host-map build: its two
            # deliberate O(T) walks carry reasoned suppressions (the
            # suppression audit proved the previous scope omission
            # made those noqas dead — any NEW cluster walk here now
            # actually fails CI)
            "ResidentSolver._express_finalize",
        ),
        # the service dispatch/pipeline scopes run once per WAVE across
        # N tenants: an O(tenants x cluster) host walk there turns the
        # batched lane back into N serial schedulers
        "poseidon_tpu/service/dispatch.py": (
            "TenantSolver.begin_round",
            "TenantSolver.finish_round",
            "BatchDispatcher.register",
            "BatchDispatcher.launch",
            "BatchDispatcher._stage_chunk",
            "BatchDispatcher._dispatch_chunk",
            "BatchDispatcher.finish",
        ),
        "poseidon_tpu/service/service.py": (
            "SchedulingService.pump",
            "SchedulingService._finish_wave",
            "SchedulingService._account",
        ),
        # aggregation planning/expansion must stay vectorized numpy:
        # a Python walk over machines here is O(cluster) every round
        "poseidon_tpu/graph/aggregate.py": (
            "plan_from_costs",
            "plan_from_signatures",
            "aggregate_topology",
            "prune_topology_prefs",
            "expand_assignment",
            "_plan_from_keys",
            "_pinned_mask",
        ),
        # the actuation outbox (ha/outbox.py) pumps once per tick in
        # the driver loop's observe window: O(outbox-entries) only —
        # an O(cluster) walk here would bill every healthy tick for
        # the outage machinery
        "poseidon_tpu/ha/outbox.py": (
            "ActuationOutbox.enqueue",
            "ActuationOutbox.pump",
            "ActuationOutbox._pump_pass",
            "OutageDetector.note_failure",
            "OutageDetector.note_success",
        ),
        # the chaos orchestrator's injection step runs on the driver
        # thread between rounds (cli round_hook): schedule lookups
        # and bounded injections only, never a cluster walk
        "poseidon_tpu/chaos/scenarios.py": (
            "ChaosOrchestrator.on_round",
        ),
        # metric recording + span assembly (_OBS_HOT_SCOPES): an
        # O(cluster) walk there would bill every round for its own
        # observability
        **_OBS_HOT_SCOPES,
    },
    cluster_sized_names=(
        "tasks",
        "machines",
        "pods",
        "nodes",
        "pending",
        "task_uids",
        "machine_names",
        "pod_to_machine",
    ),
    thread_classes={
        # The bridge is single-threaded BY CONTRACT: no background
        # context may mutate it at all (any marker-declared background
        # function writing bridge state must hold the lock — and there
        # is deliberately no lock, so the right fix is a handoff
        # through the driver loop).
        "SchedulerBridge": ThreadContract(lock_attr="_lock", handoffs={}),
        "ResidentSolver": ThreadContract(lock_attr="_lock", handoffs={}),
        # resident.py's single-shot fetch handle: the Event set/wait
        # pair is the documented happens-before edge
        "_AsyncFetch": ThreadContract(
            lock_attr="_lock",
            handoffs={
                "_value": "written before _done.set(); read only after "
                          "_done.wait() — Event establishes happens-before",
                "_exc": "same Event happens-before as _value",
            },
        ),
        # the metrics registry: recording sites run on the driver
        # thread inside the round, render() on the metrics server's
        # handler threads — every access to the instrument maps holds
        # the one shared registry lock
        "MetricsRegistry": ThreadContract(lock_attr="_lock", handoffs={}),
        # the /readyz latch: driver-thread marks, handler-thread reads,
        # both under the lock (the booleans flip once, but reasons()
        # must not see a torn seeded/round pair)
        "HealthState": ThreadContract(lock_attr="_lock", handoffs={}),
        # the endpoint server: started/stopped from the driver thread
        # only; the serving thread touches the httpd object, never
        # ObsServer attributes (the former ``_httpd`` handoff entry was
        # PTA006-audited stale: no background context reads the
        # attribute — the serving thread holds the httpd OBJECT via
        # Thread(target=), it never dereferences ``self._httpd``).
        # ``slo`` IS read per /slo request by handler threads, via a
        # captured server reference the lockset pass cannot attribute
        # — the benign-race rationale (atomic reference assignment; a
        # stale read costs one 404 scrape) is documented at the read
        # site in obs/server.py
        "ObsServer": ThreadContract(lock_attr="_lock", handoffs={}),
        # the checkpoint manager (ha/checkpoint.py): capture on the
        # driver thread, serialization on the background writer; the
        # snapshot handoff is a queue.Queue of immutable-after-capture
        # snapshots (frozen dataclasses + copy-on-write arrays), and
        # the writer statistics are read/written under _lock on both
        # sides
        "CheckpointManager": ThreadContract(
            lock_attr="_lock", handoffs={}
        ),
        # the actuation journal (ha/journal.py): intents/terminal
        # marks from the driver thread, ``posted`` marks from the
        # bounded binding-POST pool — every file write holds _lock
        "ActuationJournal": ThreadContract(
            lock_attr="_lock", handoffs={}
        ),
        # the actuation outbox (ha/outbox.py): pump/drop on the
        # driver thread, enqueue ALSO from the bounded binding-POST
        # pool workers (cli _post_bindings) — the entry list is
        # guarded by _lock on every access; the lifetime counters
        # are pump-side (driver-thread) only
        "ActuationOutbox": ThreadContract(
            lock_attr="_lock", handoffs={}
        ),
        # the shadow auditor (obs/audit.py): capture on the driver
        # thread, the re-solve on the audit worker; the snapshot
        # handoff is a bounded queue.Queue of immutable-after-capture
        # snapshots, and results/counters are written and read under
        # _lock on both sides
        # (the snapshot handoff is a queue.Queue — construction-only
        # attribute, so no handoff entry is needed: the queue's own
        # lock is the happens-before edge)
        "ShadowAuditor": ThreadContract(lock_attr="_lock"),
        # the SLO engine: evaluate() on the driver thread, status()
        # on the obs server's handler threads — window state is read
        # and written under _lock on both sides
        "SloEngine": ThreadContract(lock_attr="_lock", handoffs={}),
        # watch.py's per-resource reader thread (the former ``rv``
        # handoff entry was PTA006-audited stale: the reconnect cursor
        # is reader-thread-private — construction aside, no main-thread
        # access exists, so there is no handoff to document)
        "_WatchStream": ThreadContract(
            lock_attr="_lock",
            handoffs={
                "_resp": "benign race with stop(): closing a stale "
                         "response object at worst forces one counted "
                         "reconnect; queue.Queue carries the real data",
                "seen_rv": "monotonic int advanced only after the event "
                           "is enqueued; torn reads impossible on a GIL "
                           "int, staleness means one extra wait loop",
                "last_activity": "monotonic float heartbeat; a stale "
                                 "read only delays the staleness resync "
                                 "by one tick",
                "coalesced_reconnects":
                    "monotonic int advanced only by the reader thread "
                    "(queue-suppressed reconnects during an outage); "
                    "the consumer folds deltas via a private cursor — "
                    "same GIL-int pattern as seen_rv, staleness costs "
                    "one tick of count lag, never a lost count",
            },
        ),
    },
    thread_spawn_wrappers=(
        # ops/resident.py's single-shot background download: the fn
        # passed to its constructor runs on the fetch daemon thread
        "_AsyncFetch",
    ),
    hazard_attrs=(
        # data-dependent shape/width sources: topology maxima and
        # builder counts change with live cluster state every round
        "max_prefs",
        "n_arcs",
        "n_tasks",
        "n_machines",
    ),
    floor_markers=(
        "floor",        # substring: _s_floor, ctx.p_floor, _b_floor...
        "t_min",
        "m_min",
        "p_min",
        "minimum",
    ),
    pad_sinks={
        "pad_topology": ("t_min", "m_min", "p_min"),
        "build_cost_inputs_host": ("t_min", "m_min"),
    },
    path_rules=(
        ("tests/", ("PTA000", "PTA003", "PTA005")),
    ),
    kernel_mask_contracts={
        # "*" = every kernel whose trace reaches the site (the solve
        # family shares these). Each entry is a reduction that folds
        # padded lanes SAFELY by construction — the identity the fold
        # needs is already baked into the table, so masking at the
        # fold would buy nothing and cost a select per inner-loop
        # call. Verified live: an entry with no matching tainted
        # reduction in the current traces is reported stale.
        "*": (
            ("reduce_min", "_task_options",
             "folds dev.c + p over the machine axis: padded machine "
             "columns are INF-filled at construction "
             "(build_dense_instance/build_member_tables), so they "
             "never win a min; padded TASK rows produce garbage rows "
             "consumed only under task_valid"),
            ("argmin", "_task_options",
             "same table as the reduce_min above: INF padded columns "
             "lose every argmin; ties resolve inside the valid "
             "machine set"),
            ("reduce_min", "_theta_clearing",
             "the analytic-init seat market folds dev.c with the "
             "same INF-filled padded columns; stage-one lambda is "
             "already zeroed on zero-slot machines via dev.s > 0"),
            ("reduce_min", "auction_round",
             "the bid window's per-task best-value fold over "
             "gathered dev.c rows: INF padded columns, and bidder "
             "positions come from the sorted carry where padded "
             "tasks ride the DUMP segment"),
            ("reduce_or", "body",
             "any(waiting): layout() computes waiting = (in-machine "
             "& unseated) | WAIT over the sorted carry — padded "
             "tasks sit in the DUMP segment, never WAIT, so they "
             "cannot hold the loop open"),
            ("reduce_or", "phase_shift",
             "any(violators(...)): violators() ANDs dev.task_valid "
             "into the mask before returning, padded rows cannot "
             "trigger a refight"),
            ("reduce_or", "tighten",
             "any(viol)/any(stranded): violator masks AND in "
             "task_valid; stranded masks AND in dev.s > 0, which "
             "excludes zero-slot padded machines by the pad "
             "contract"),
            ("reduce_sum", "_solve",
             "the dual's machine-side term sums dev.s * lambda: "
             "padded machines carry s == 0 by the pad contract, "
             "contributing exact zeros to the certificate"),
        ),
    },
    blocking_call_names=(
        # filesystem barrier: the one call whose whole point is to
        # WAIT for the platters/flash
        "fsync",
        # apiserver round-trips (apiclient/client.py surface): each is
        # an HTTP request with network latency and retry loops
        "get_pod",
        "bind_pod_to_node",
        "evict_pod",
        "bind_outcome",
        "evict_outcome",
        "list_pods",
        "list_nodes",
        "urlopen",
        "getresponse",
        "sendall",
        # solver dispatch / device sync: a round or a fetch pinned
        # under a lock serializes the daemon on kernel latency
        "run_round",
        "solve_scheduling",
        "block_until_ready",
        "device_get",
        # deliberate delay: sleeping under a lock turns an injected
        # or polled delay into a stall for every contender
        "sleep",
    ),
)
