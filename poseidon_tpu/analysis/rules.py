"""The repo-specific rule set (PTA001-PTA005).

Each rule documents itself with a minimal bad/good pair. Rules are
scoped by ``contracts.py`` — the hot-path files/functions, the
cluster-sized collection names, the thread classes — so a generic
pattern (a ``for`` loop, an ``int()`` call) is only a violation where
the repo's stated invariants forbid it.

PTA001 no-host-sync
    BAD  (inside a hot-path scope)::

        val = cost.item()              # device sync mid-round
        host = np.asarray(asg_dev)     # host materialization
    GOOD::

        # defer to the round's single sanctioned fetch, or:
        host = np.asarray(asg_np)  # noqa: PTA001 -- already host data

PTA002 no-cluster-loops
    BAD  (inside an O(churn) scope)::

        for t in cluster.tasks: ...    # O(cluster) every round
    GOOD::

        for d in dset.place: ...       # O(churn): only this round's deltas

PTA003 jit-hygiene
    BAD::

        def price(x):
            return jax.jit(model)(x)   # fresh wrapper -> retrace per call

        @partial(jax.jit, static_argnames=("opts",))
        def f(x, opts=[]): ...         # non-hashable static default
    GOOD::

        _model_jit = jax.jit(model)    # module level, traced once

PTA004 lock-discipline
    BAD::

        def run(self):  # pta: background-thread
            self.rounds += 1           # unlocked cross-thread mutation
    GOOD::

        def run(self):  # pta: background-thread
            with self._lock:
                self.rounds += 1
    (or declare the attribute as a documented handoff in contracts.py)

PTA005 surface-consistency
    BAD::

        self.trace.emit("REBALANCE")   # not in trace.EVENT_TYPES
        p.add_argument("--new_flag")   # absent from README / deploy cfg
    GOOD::

        self.trace.emit("MIGRATE")     # declared vocabulary
"""

from __future__ import annotations

import ast
import builtins
import re

from poseidon_tpu.analysis.contracts import Contracts
from poseidon_tpu.analysis.core import (
    FileContext,
    RepoContext,
    Violation,
    file_rule,
    repo_rule,
)

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)
_BUILTINS = frozenset(dir(builtins))


# ---- shared AST helpers ------------------------------------------------


def _dotted(node: ast.AST) -> str | None:
    """'jax.device_get' for Attribute chains rooted at a Name."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def iter_functions(tree: ast.AST):
    """Yield (node, qualname, depth) for every def, depth-first.
    Qualnames join class and function names with '.'; depth counts
    enclosing FUNCTIONS only (a method of a top-level class is depth 0).
    """
    def walk(node, prefix, depth):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, _FUNC_NODES):
                qual = f"{prefix}{child.name}"
                yield child, qual, depth
                yield from walk(child, qual + ".", depth + 1)
            elif isinstance(child, ast.ClassDef):
                yield from walk(child, f"{prefix}{child.name}.", depth)
            else:
                yield from walk(child, prefix, depth)
    yield from walk(tree, "", 0)


def iter_own_nodes(fn: ast.AST):
    """Walk a function's own body, NOT descending into nested defs or
    classes (they are analyzed as their own scopes)."""
    stack = list(fn.body)
    while stack:
        node = stack.pop()
        if isinstance(node, _FUNC_NODES + (ast.ClassDef,)):
            continue  # nested scope: analyzed as its own function
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _names_in(node: ast.AST) -> set[str]:
    return {
        n.id for n in ast.walk(node) if isinstance(n, ast.Name)
    }


def _bound_names(target: ast.AST) -> set[str]:
    """Names a target expression BINDS. ``obj.attr = x`` / ``d[k] = x``
    mutate an object without binding any name, so they contribute
    nothing (unlike ``_names_in``, which would claim ``obj``)."""
    if isinstance(target, ast.Name):
        return {target.id}
    if isinstance(target, (ast.Tuple, ast.List)):
        out: set[str] = set()
        for e in target.elts:
            out |= _bound_names(e)
        return out
    if isinstance(target, ast.Starred):
        return _bound_names(target.value)
    return set()


# ---- PTA001: no host syncs in hot-path scopes --------------------------

_SYNC_ATTRS = {"item", "block_until_ready"}
_HOST_MATERIALIZERS = {"np.asarray", "numpy.asarray"}


def _is_device_producer(call: ast.Call, contracts: Contracts) -> bool:
    d = _dotted(call.func)
    if d is not None:
        if d in contracts.device_producer_exceptions:
            return False
        for p in contracts.device_producers:
            if p.endswith("."):
                if d.startswith(p):
                    return True
            elif d == p or d.endswith("." + p):
                return True
    if isinstance(call.func, ast.Call):  # e.g. _jitted_model(name)(x)
        return _is_device_producer(call.func, contracts)
    return False


def _device_tainted_names(fn, contracts: Contracts) -> set[str]:
    """Names assigned (directly or transitively) from device-array
    producers within this function."""
    assigns: list[tuple[set[str], ast.AST]] = []
    for node in iter_own_nodes(fn):
        if isinstance(node, ast.Assign):
            targets: set[str] = set()
            for t in node.targets:
                targets |= _bound_names(t)
            assigns.append((targets, node.value))
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)) and \
                isinstance(node.target, ast.Name) and node.value is not None:
            assigns.append(({node.target.id}, node.value))
    def _is_host_barrier(value: ast.AST) -> bool:
        # an explicit download's RESULT is host data: int()/float() on
        # it cannot sync again, so the assignment untaints its targets
        # even though the downloaded operands were device arrays
        return (
            isinstance(value, ast.Call)
            and _dotted(value.func) in contracts.device_producer_exceptions
        )

    tainted: set[str] = set()
    for _ in range(2):  # two passes: one hop of name->name propagation
        for targets, value in assigns:
            if _is_host_barrier(value):
                continue
            if any(
                isinstance(n, ast.Call)
                and _is_device_producer(n, contracts)
                for n in ast.walk(value)
            ) or (_names_in(value) & tainted):
                tainted |= targets
    return tainted


@file_rule("PTA001", "no-host-sync")
def no_host_sync(ctx: FileContext) -> list[Violation]:
    c = ctx.contracts
    whole_file = any(ctx.path.endswith(s) for s in c.hot_path_files)
    out: list[Violation] = []

    def flag(node, msg):
        out.append(Violation(
            code="PTA001", rule="no-host-sync", path=ctx.path,
            line=node.lineno, col=node.col_offset, message=msg,
        ))

    for fn, qual, _depth in iter_functions(ctx.tree):
        if not (whole_file or ctx.in_scope(c.hot_path_functions, qual)):
            continue
        tainted = _device_tainted_names(fn, c)
        for node in iter_own_nodes(fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr in _SYNC_ATTRS:
                flag(node, f".{f.attr}() forces a device sync inside "
                           f"hot-path scope {qual}")
                continue
            d = _dotted(f)
            if d == "jax.device_get" or (
                isinstance(f, ast.Name) and f.id == "device_get"
            ):
                flag(node, "jax.device_get is a host sync; only the "
                           "round's sanctioned fetch may download "
                           f"(hot-path scope {qual})")
                continue
            if d in _HOST_MATERIALIZERS:
                flag(node, f"{d} materializes on host inside hot-path "
                           f"scope {qual} (syncs if the operand is a "
                           "device array)")
                continue
            if isinstance(f, ast.Name) and f.id in ("int", "float") \
                    and node.args:
                if _names_in(node.args[0]) & tainted:
                    flag(node, f"{f.id}() on a device array blocks on "
                               f"the device (hot-path scope {qual})")
    return out


# ---- PTA002: no cluster-sized loops in O(churn) scopes -----------------


def _cluster_sized_ref(node: ast.AST, c: Contracts) -> str | None:
    for n in ast.walk(node):
        if isinstance(n, ast.Name) and n.id in c.cluster_sized_names:
            return n.id
        if isinstance(n, ast.Attribute) and n.attr in c.cluster_sized_names:
            return n.attr
    return None


@file_rule("PTA002", "no-cluster-loops")
def no_cluster_loops(ctx: FileContext) -> list[Violation]:
    c = ctx.contracts
    out: list[Violation] = []
    for fn, qual, _depth in iter_functions(ctx.tree):
        if not ctx.in_scope(c.ochurn_functions, qual):
            continue
        for node in iter_own_nodes(fn):
            if isinstance(node, ast.For):
                iters = [node.iter]
            elif isinstance(node, (ast.ListComp, ast.SetComp,
                                   ast.DictComp, ast.GeneratorExp)):
                iters = [g.iter for g in node.generators]
            else:
                continue
            for it in iters:
                name = _cluster_sized_ref(it, c)
                if name:
                    out.append(Violation(
                        code="PTA002", rule="no-cluster-loops",
                        path=ctx.path, line=node.lineno,
                        col=node.col_offset,
                        message=(
                            f"Python loop over cluster-sized '{name}' "
                            f"in O(churn) scope {qual}: iterate this "
                            "round's deltas, or maintain a counter"
                        ),
                    ))
                    break
    return out


# ---- PTA003: jit boundary hygiene --------------------------------------


def _is_jit_expr(node: ast.AST) -> bool:
    d = _dotted(node)
    return d in ("jax.jit", "jit")


def _jit_decorator(fn) -> ast.Call | None:
    """The decorator Call if ``fn`` is jitted (plain @jax.jit returns a
    synthetic marker too — None vs Call distinction only matters for
    static_argnames extraction)."""
    for dec in fn.decorator_list:
        if _is_jit_expr(dec):
            return ast.Call(func=dec, args=[], keywords=[])
        if isinstance(dec, ast.Call):
            if _is_jit_expr(dec.func):
                return dec
            d = _dotted(dec.func)
            if d in ("partial", "functools.partial") and dec.args \
                    and _is_jit_expr(dec.args[0]):
                return dec
    return None


_MUTABLE_DEFAULTS = (ast.List, ast.Dict, ast.Set, ast.ListComp,
                     ast.DictComp, ast.SetComp)


def _module_bindings(tree: ast.Module) -> set[str]:
    names: set[str] = set()
    for node in tree.body:
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            for a in node.names:
                names.add((a.asname or a.name).split(".")[0])
        elif isinstance(node, _FUNC_NODES + (ast.ClassDef,)):
            names.add(node.name)
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                names |= _names_in(t)
        elif isinstance(node, ast.AnnAssign) and \
                isinstance(node.target, ast.Name):
            names.add(node.target.id)
        elif isinstance(node, (ast.If, ast.Try)):
            for sub in ast.walk(node):
                if isinstance(sub, (ast.Import, ast.ImportFrom)):
                    for a in sub.names:
                        names.add((a.asname or a.name).split(".")[0])
                elif isinstance(sub, ast.Assign):
                    for t in sub.targets:
                        names |= _names_in(t)
    return names


def _locally_bound(fn) -> set[str]:
    bound = {a.arg for a in fn.args.args + fn.args.kwonlyargs
             + fn.args.posonlyargs}
    if fn.args.vararg:
        bound.add(fn.args.vararg.arg)
    if fn.args.kwarg:
        bound.add(fn.args.kwarg.arg)
    for node in iter_own_nodes(fn):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                bound |= _bound_names(t)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            bound |= _bound_names(node.target)
        elif isinstance(node, ast.comprehension):
            bound |= _bound_names(node.target)
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            for a in node.names:
                bound.add((a.asname or a.name).split(".")[0])
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            bound |= _bound_names(node.target)
        elif isinstance(node, ast.withitem) and node.optional_vars:
            bound |= _names_in(node.optional_vars)
        elif isinstance(node, ast.ExceptHandler) and node.name:
            bound.add(node.name)
        elif isinstance(node, _FUNC_NODES + (ast.ClassDef,)):
            bound.add(node.name)
    return bound


def _all_import_bindings(tree: ast.AST) -> set[str]:
    """Every name bound by an import anywhere in the file. Closing over
    a locally-imported MODULE is harmless (modules don't retrace), so
    PTA003's capture check exempts them."""
    names: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            for a in node.names:
                names.add((a.asname or a.name).split(".")[0])
    return names


@file_rule("PTA003", "jit-hygiene")
def jit_hygiene(ctx: FileContext) -> list[Violation]:
    out: list[Violation] = []
    mod_names = _module_bindings(ctx.tree) | _all_import_bindings(ctx.tree)

    def flag(node, msg):
        out.append(Violation(
            code="PTA003", rule="jit-hygiene", path=ctx.path,
            line=node.lineno, col=node.col_offset, message=msg,
        ))

    for fn, qual, depth in iter_functions(ctx.tree):
        # (a) inline jax.jit(...) calls: fresh wrapper per call
        for node in iter_own_nodes(fn):
            if isinstance(node, ast.Call) and _is_jit_expr(node.func):
                flag(node, f"jax.jit(...) inside {qual} creates a fresh "
                           "traced wrapper per call (retrace + "
                           "recompile every round); hoist to module "
                           "level or cache the jitted callable")
        dec = _jit_decorator(fn)
        if dec is None:
            continue
        # (b) non-hashable defaults on a jitted function
        for default in list(fn.args.defaults) + [
            d for d in fn.args.kw_defaults if d is not None
        ]:
            if isinstance(default, _MUTABLE_DEFAULTS) or (
                isinstance(default, ast.Call)
                and isinstance(default.func, ast.Name)
                and default.func.id in ("list", "dict", "set")
            ):
                flag(default, f"mutable default on jitted {qual}: "
                              "unhashable as a static argument and a "
                              "retrace trap")
        # (d) static_argnames naming unknown parameters
        params = {a.arg for a in fn.args.args + fn.args.kwonlyargs
                  + fn.args.posonlyargs}
        for kw in dec.keywords:
            if kw.arg in ("static_argnames", "static_argnums") and \
                    isinstance(kw.value, (ast.Tuple, ast.List)):
                for elt in kw.value.elts:
                    if isinstance(elt, ast.Constant) and \
                            isinstance(elt.value, str) and \
                            elt.value not in params:
                        flag(elt, f"static_argnames entry "
                                  f"'{elt.value}' is not a parameter "
                                  f"of {qual}")
        # (c) nested jitted defs: closure capture bakes enclosing-scope
        # values into the trace (silent retrace when they change)
        if depth > 0:
            flag(fn, f"jitted function {qual} is defined inside a "
                     "function: it is re-jitted per enclosing call and "
                     "its closure is baked into the trace; hoist it")
            loads = {
                n.id for n in ast.walk(fn)
                if isinstance(n, ast.Name)
                and isinstance(n.ctx, ast.Load)
            }
            free = loads - _locally_bound(fn) - mod_names - _BUILTINS
            for name in sorted(free):
                flag(fn, f"jitted {qual} closes over '{name}' from an "
                         "enclosing scope; pass it as an argument "
                         "(static or traced) instead")
    return out


# ---- PTA004: lock discipline for cross-thread state --------------------


@file_rule("PTA004", "lock-discipline")
def lock_discipline(ctx: FileContext) -> list[Violation]:
    c = ctx.contracts
    out: list[Violation] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        tc = c.thread_classes.get(node.name)
        if tc is None:
            continue
        # (attr -> list of (line, col, is_write, domain, locked))
        accesses: dict[str, list[tuple[int, int, bool, str, bool]]] = {}

        def visit_fn(fn, self_name, domain):
            lock_expr = f"{self_name}.{tc.lock_attr}"

            def rec(n, locked):
                if isinstance(n, _FUNC_NODES):
                    nested_domain = (
                        "background"
                        if n.lineno in ctx.background_lines
                        else domain
                    )
                    # nested functions capture self from the method;
                    # a lock held at DEFINITION time is not held when
                    # the closure later runs, so locked resets
                    for stmt in n.body:
                        rec_nested(stmt, False, nested_domain)
                    return
                if isinstance(n, ast.ClassDef):
                    return
                now_locked = locked
                if isinstance(n, ast.With):
                    if any(
                        _dotted(item.context_expr) == lock_expr
                        for item in n.items
                    ):
                        now_locked = True
                if isinstance(n, ast.Attribute) and \
                        isinstance(n.value, ast.Name) and \
                        n.value.id == self_name:
                    is_write = isinstance(n.ctx, (ast.Store, ast.Del))
                    accesses.setdefault(n.attr, []).append(
                        (n.lineno, n.col_offset, is_write, domain,
                         now_locked)
                    )
                for child in ast.iter_child_nodes(n):
                    rec(child, now_locked)

            def rec_nested(n, locked, nested_domain):
                nonlocal domain
                saved, domain = domain, nested_domain
                rec(n, locked)
                domain = saved

            for stmt in fn.body:
                rec(stmt, False)

        for fn in node.body:
            if not isinstance(fn, _FUNC_NODES):
                continue
            if fn.name == "__init__":
                # construction happens-before any thread start: the
                # documented handoff for initial state
                continue
            args = fn.args.posonlyargs + fn.args.args
            if not args:
                continue
            self_name = args[0].arg
            domain = (
                "background" if fn.lineno in ctx.background_lines
                else "main"
            )
            visit_fn(fn, self_name, domain)

        for attr, sites in accesses.items():
            domains_writing = {d for (_, _, w, d, _) in sites if w}
            domains_all = {d for (_, _, _, d, _) in sites}
            if not domains_writing or len(domains_all) < 2:
                continue
            if attr in tc.handoffs:
                continue
            for line, col, is_write, domain, locked in sites:
                if locked:
                    continue
                out.append(Violation(
                    code="PTA004", rule="lock-discipline",
                    path=ctx.path, line=line, col=col,
                    message=(
                        f"{node.name}.{attr} is written cross-thread "
                        f"({'write' if is_write else 'read'} from the "
                        f"{domain} thread without holding "
                        f"self.{tc.lock_attr}); lock it or declare a "
                        "documented handoff in analysis/contracts.py"
                    ),
                ))
    return out


# ---- PTA005: trace vocabulary + flag surface consistency ---------------


def _trace_vocab(ctx: FileContext, vocab_name: str) -> set[str] | None:
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == vocab_name
            for t in node.targets
        ):
            consts = {
                n.value for n in ast.walk(node.value)
                if isinstance(n, ast.Constant)
                and isinstance(n.value, str)
            }
            if consts:
                return consts
    return None


@repo_rule("PTA005", "surface-consistency")
def surface_consistency(repo: RepoContext) -> list[Violation]:
    c = repo.contracts
    out: list[Violation] = []

    # -- trace event vocabulary --
    trace_ctx = next(
        (f for rel, f in repo.files.items()
         if rel.endswith(c.trace_module)),
        None,
    )
    vocab: set[str] | None = None
    if trace_ctx is not None:
        vocab = _trace_vocab(trace_ctx, c.trace_vocab_name)
        if vocab is None:
            out.append(Violation(
                code="PTA005", rule="surface-consistency",
                path=trace_ctx.path, line=1, col=0,
                message=(
                    f"{c.trace_vocab_name} vocabulary declaration not "
                    f"found in {c.trace_module}"
                ),
            ))
    if vocab is not None:
        for rel, fctx in repo.files.items():
            for node in ast.walk(fctx.tree):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "emit"):
                    continue
                base = node.func.value
                base_name = (
                    base.attr if isinstance(base, ast.Attribute)
                    else base.id if isinstance(base, ast.Name) else None
                )
                if base_name != "trace":
                    continue
                if not node.args:
                    continue
                arg = node.args[0]
                if isinstance(arg, ast.Constant) and \
                        isinstance(arg.value, str):
                    if arg.value not in vocab:
                        out.append(Violation(
                            code="PTA005", rule="surface-consistency",
                            path=rel, line=node.lineno,
                            col=node.col_offset,
                            message=(
                                f"trace event '{arg.value}' is not in "
                                f"the declared {c.trace_vocab_name} "
                                f"vocabulary ({c.trace_module})"
                            ),
                        ))
                else:
                    out.append(Violation(
                        code="PTA005", rule="surface-consistency",
                        path=rel, line=node.lineno, col=node.col_offset,
                        message=(
                            "dynamic trace event name: emit a literal "
                            "from the declared vocabulary (or suppress "
                            "with a reason)"
                        ),
                    ))

    # -- cli flag surface --
    cli_ctx = next(
        (f for rel, f in repo.files.items()
         if rel.endswith(c.flag_module)),
        None,
    )
    if cli_ctx is not None:
        flags: list[tuple[str, int, int]] = []
        for node in ast.walk(cli_ctx.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "add_argument"
                    and node.args):
                continue
            arg = node.args[0]
            if not (isinstance(arg, ast.Constant)
                    and isinstance(arg.value, str)
                    and arg.value.startswith("--")):
                continue
            hidden = any(
                kw.arg == "help"
                and isinstance(kw.value, ast.Attribute)
                and kw.value.attr == "SUPPRESS"
                for kw in node.keywords
            )
            if not hidden:
                flags.append((arg.value, node.lineno, node.col_offset))
        doc_texts = {
            doc: repo.read_text(doc) for doc in c.flag_doc_files
        }
        for doc, text in doc_texts.items():
            if text is None:
                out.append(Violation(
                    code="PTA005", rule="surface-consistency",
                    path=cli_ctx.path, line=1, col=0,
                    message=f"flag doc file '{doc}' not found",
                ))
        for flag, line, col in flags:
            pattern = re.compile(re.escape(flag) + r"(?![\w-])")
            for doc, text in doc_texts.items():
                if text is not None and not pattern.search(text):
                    out.append(Violation(
                        code="PTA005", rule="surface-consistency",
                        path=cli_ctx.path, line=line, col=col,
                        message=(
                            f"flag {flag} is not documented in {doc}"
                        ),
                    ))

    # -- metric family surface --
    # every poseidon_* family registered in the metrics module must
    # appear in the README's observability reference and vice versa:
    # an operator alerting on a renamed family pages on silence, and a
    # documented-but-unregistered family is a dashboard query that
    # matches nothing. Same drift-proofing shape as the trace
    # vocabulary above: the code side is the AST (literal first args
    # to .counter/.gauge/.histogram), the doc side is a token scan.
    metrics_ctx = next(
        (f for rel, f in repo.files.items()
         if rel.endswith(c.metrics_module)),
        None,
    )
    if metrics_ctx is not None:
        registered: dict[str, tuple[int, int]] = {}
        for node in ast.walk(metrics_ctx.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in (
                        "counter", "gauge", "histogram")
                    and node.args):
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and \
                    isinstance(arg.value, str) and \
                    arg.value.startswith("poseidon_"):
                registered.setdefault(
                    arg.value, (node.lineno, node.col_offset)
                )
        doc_text = repo.read_text(c.metrics_doc_file)
        if doc_text is None:
            out.append(Violation(
                code="PTA005", rule="surface-consistency",
                path=metrics_ctx.path, line=1, col=0,
                message=(
                    f"metric doc file '{c.metrics_doc_file}' not found"
                ),
            ))
        elif registered:
            documented = {
                m for m in re.findall(
                    r"\bposeidon_[a-z0-9_]+", doc_text
                )
                if not m.startswith("poseidon_tpu")
            }
            for name in sorted(registered):
                if name not in documented:
                    line, col = registered[name]
                    out.append(Violation(
                        code="PTA005", rule="surface-consistency",
                        path=metrics_ctx.path, line=line, col=col,
                        message=(
                            f"metric family '{name}' is registered "
                            f"but not documented in "
                            f"{c.metrics_doc_file}'s observability "
                            "reference"
                        ),
                    ))
            # reverse direction: histogram exports add per-series
            # _bucket/_sum/_count suffixes, so strip those before
            # deciding a documented token names a missing family; a
            # token ending in '_' is a prose prefix reference
            # ("the poseidon_outbox_* family") — fine as long as some
            # registered family matches, but it does NOT satisfy the
            # forward per-family requirement above
            def _family(tok: str) -> str:
                for suf in ("_bucket", "_sum", "_count"):
                    if tok.endswith(suf) and \
                            tok[: -len(suf)] in registered:
                        return tok[: -len(suf)]
                return tok
            for tok in sorted(documented):
                if tok.endswith("_") and any(
                    name.startswith(tok) for name in registered
                ):
                    continue
                if _family(tok) not in registered:
                    out.append(Violation(
                        code="PTA005", rule="surface-consistency",
                        path=metrics_ctx.path, line=1, col=0,
                        message=(
                            f"{c.metrics_doc_file} documents metric "
                            f"family '{tok}' that is not registered "
                            f"in {c.metrics_module} — delete the "
                            "stale reference or register the family"
                        ),
                    ))
    return out
