"""PTA007: static-arg / pad-shape provenance — the recompile-hazard pass.

PR 8's bench flushed out three recompile sources at RUNTIME, all one
bug class: a value derived from a data-dependent quantity (a ``max``
over live state, a ``len`` of the pending pool, a topology's
``max_prefs``) flowing into a position that pins a compiled shape — a
``static_argnames`` argument of a jitted kernel, or a ``t_min`` /
``m_min`` / ``p_min`` padding floor of the host padding helpers —
WITHOUT riding a grow-only floor. The steady-state symptom is brutal
and silent: a pending pool draining across a bucket boundary shrinks
the derived value, the static arg changes, and every post-drain round
pays a multi-second recompile that profiles as "the TPU got slow".

This pass catches the pattern at review time, as dataflow any reviewer
can replay:

1. **Jit registry (repo-wide).** Every ``@jax.jit`` /
   ``@partial(jax.jit, static_argnames=...)`` def in the tree is
   indexed with its static parameter names (``static_argnums`` map
   through the positional parameter list), so call sites anywhere know
   which argument positions pin compiled variants.

2. **Taint (function-local, flow-ordered).** A local is tainted when
   it derives from a hazard source — a ``max(...)`` / ``len(...)``
   call, a ``.max()`` reduction, or a declared hazard attribute
   (``.max_prefs``) — via assignments replayed in source order, so a
   later clean re-binding (``P = self._p_floor``) clears the name.

3. **Floors sanctify.** An expression that references a grow-only
   floor (``Contracts.floor_markers``: anything carrying ``floor`` in
   its name, or the ``t_min``/``m_min``/``p_min``/``minimum`` pad
   vocabulary) is clean: ``pad_bucket(max(n, 1),
   minimum=self._e_floor)`` rides the floor, ``pad_bucket(max(n, 1))``
   does not. The grow-only-ness of the floor attribute itself is the
   storing site's obligation (the same expression both reads and
   re-stores it), which the marker check covers by construction.

4. **Sinks.** A tainted, un-floored expression arriving at a static
   parameter of a registered jitted callable, or at a declared pad
   floor of the padding helpers (``Contracts.pad_sinks``), is the
   violation.

One-shot lanes (a cold ``solve_transport_dense`` call in a test or the
bench) recompile per call BY DESIGN — such sites carry a reasoned
``# noqa: PTA007`` so the design decision is written down where the
reviewer reads it.
"""

from __future__ import annotations

import ast

from poseidon_tpu.analysis.contracts import Contracts
from poseidon_tpu.analysis.core import (
    RepoContext,
    Violation,
    files_enforcing,
    repo_rule,
)
from poseidon_tpu.analysis.rules import (
    _bound_names,
    _dotted,
    _jit_decorator,
    iter_functions,
    iter_own_nodes,
)


def _static_params(fn: ast.AST, dec: ast.Call) -> tuple[list[str], set[str]]:
    """(positional param names, static param names) of a jitted def."""
    params = [
        a.arg for a in fn.args.posonlyargs + fn.args.args
    ]
    kwonly = [a.arg for a in fn.args.kwonlyargs]
    static: set[str] = set()
    for kw in dec.keywords:
        if kw.arg == "static_argnames" and \
                isinstance(kw.value, (ast.Tuple, ast.List)):
            for elt in kw.value.elts:
                if isinstance(elt, ast.Constant) and \
                        isinstance(elt.value, str):
                    static.add(elt.value)
        elif kw.arg == "static_argnames" and \
                isinstance(kw.value, ast.Constant) and \
                isinstance(kw.value.value, str):
            static.add(kw.value.value)
        elif kw.arg == "static_argnums":
            nums = []
            if isinstance(kw.value, (ast.Tuple, ast.List)):
                nums = [
                    e.value for e in kw.value.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, int)
                ]
            elif isinstance(kw.value, ast.Constant) and \
                    isinstance(kw.value.value, int):
                nums = [kw.value.value]
            for i in nums:
                if 0 <= i < len(params):
                    static.add(params[i])
    del kwonly
    return params, static


def build_jit_registry(
    files,
) -> dict[str, tuple[list[str], set[str]]]:
    """Terminal callable name -> (positional params, static param
    names) for every jitted def in the ENFORCING files (only defs with
    at least one static parameter matter to this pass; a tests/ def
    must not shadow a production kernel's signature). A name defined
    twice with DIFFERENT signatures is ambiguous and dropped — checking
    call sites against the wrong kernel's statics would both miss real
    hazards and invent false ones."""
    registry: dict[str, tuple[list[str], set[str]]] = {}
    ambiguous: set[str] = set()
    for fctx in files.values():
        for fn, _qual, _depth in iter_functions(fctx.tree):
            dec = _jit_decorator(fn)
            if dec is None:
                continue
            params, static = _static_params(fn, dec)
            if not static or fn.name in ambiguous:
                continue
            existing = registry.get(fn.name)
            if existing is not None and existing != (params, static):
                del registry[fn.name]
                ambiguous.add(fn.name)
                continue
            registry[fn.name] = (params, static)
    return registry


def _has_floor_marker(expr: ast.AST, c: Contracts) -> bool:
    exact = {m for m in c.floor_markers if m != "floor"}
    for n in ast.walk(expr):
        name = None
        if isinstance(n, ast.Name):
            name = n.id
        elif isinstance(n, ast.Attribute):
            name = n.attr
        elif isinstance(n, ast.keyword):
            name = n.arg
        if name is None:
            continue
        if "floor" in name.lower() or name in exact:
            return True
    return False


def _has_hazard_source(expr: ast.AST, c: Contracts) -> str | None:
    """The hazard in ``expr``, or None. max()/len() calls, ``.max()``
    reductions, and declared hazard attributes are data-dependent."""
    for n in ast.walk(expr):
        if isinstance(n, ast.Call):
            if isinstance(n.func, ast.Name) and \
                    n.func.id in ("max", "len") and n.args and not all(
                        isinstance(a, ast.Constant) for a in n.args
                    ):
                return f"{n.func.id}(...)"
            if isinstance(n.func, ast.Attribute) and \
                    n.func.attr in ("max", "argmax"):
                return f".{n.func.attr}() reduction"
            d = _dotted(n.func)
            if d in ("np.max", "numpy.max", "np.amax"):
                return d
        if isinstance(n, ast.Attribute) and n.attr in c.hazard_attrs:
            return f".{n.attr}"
    return None


def _ordered_assigns(
    fn: ast.AST,
) -> list[tuple[int, set[str], ast.AST, bool]]:
    """(lineno, bound names, value expr, is_augmented) in source order
    — the taint replay is flow-ORDERED: a later clean re-binding of a
    name (``P = self._p_floor`` after ``P = topo.max_prefs``) clears
    its taint, which a flow-insensitive union would keep forever."""
    items: list[tuple[int, set[str], ast.AST, bool]] = []
    for node in iter_own_nodes(fn):
        if isinstance(node, ast.Assign):
            targets: set[str] = set()
            for t in node.targets:
                targets |= _bound_names(t)
            items.append((node.lineno, targets, node.value, False))
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)) and \
                isinstance(node.target, ast.Name) and \
                node.value is not None:
            items.append((
                node.lineno, {node.target.id}, node.value,
                isinstance(node, ast.AugAssign),
            ))
    items.sort(key=lambda it: it[0])
    return items


def _taint_at(
    assigns: list[tuple[int, set[str], ast.AST, bool]],
    line: int,
    c: Contracts,
) -> dict[str, str]:
    """Name -> hazard description as of (just before) ``line``,
    replaying assignments in source order. Loops that assign below
    their use are out of scope (grow-only floors do not live in
    loops)."""
    tainted: dict[str, str] = {}
    for ln, targets, value, augmented in assigns:
        if ln >= line:
            break
        if _has_floor_marker(value, c):
            for t in targets:
                tainted.pop(t, None)  # rides a floor: sanctified
            continue
        hazard = _has_hazard_source(value, c)
        if hazard is None:
            carried = [
                n.id for n in ast.walk(value)
                if isinstance(n, ast.Name) and n.id in tainted
            ]
            if not carried:
                if not augmented:
                    for t in targets:
                        tainted.pop(t, None)  # clean re-binding
                continue
            hazard = tainted[carried[0]]
        for t in targets:
            tainted[t] = hazard
    return tainted


def _expr_hazard(
    expr: ast.AST, tainted: dict[str, str], c: Contracts
) -> str | None:
    if _has_floor_marker(expr, c):
        return None
    hazard = _has_hazard_source(expr, c)
    if hazard is not None:
        return hazard
    for n in ast.walk(expr):
        if isinstance(n, ast.Name) and n.id in tainted:
            return tainted[n.id]
    return None


@repo_rule("PTA007", "recompile-hazard")
def recompile_hazard(repo: RepoContext) -> list[Violation]:
    c = repo.contracts
    files = files_enforcing(repo, "PTA007")
    registry = build_jit_registry(files)
    out: list[Violation] = []
    for rel, fctx in files.items():
        for fn, qual, _depth in iter_functions(fctx.tree):
            assigns = _ordered_assigns(fn)
            for node in iter_own_nodes(fn):
                if not isinstance(node, ast.Call):
                    continue
                callee = None
                if isinstance(node.func, ast.Name):
                    callee = node.func.id
                elif isinstance(node.func, ast.Attribute):
                    callee = node.func.attr
                if callee is None:
                    continue
                sinks: list[tuple[str, ast.AST]] = []
                if callee in registry:
                    params, static = registry[callee]
                    for i, a in enumerate(node.args):
                        if i < len(params) and params[i] in static:
                            sinks.append((params[i], a))
                    for kw in node.keywords:
                        if kw.arg in static:
                            sinks.append((kw.arg, kw.value))
                if callee in c.pad_sinks:
                    floors = c.pad_sinks[callee]
                    for kw in node.keywords:
                        if kw.arg in floors:
                            sinks.append((kw.arg, kw.value))
                if not sinks:
                    continue
                tainted = _taint_at(assigns, node.lineno, c)
                for pname, value in sinks:
                    hazard = _expr_hazard(value, tainted, c)
                    if hazard is None:
                        continue
                    out.append(Violation(
                        code="PTA007", rule="recompile-hazard",
                        path=rel, line=node.lineno,
                        col=node.col_offset,
                        message=(
                            f"shape-pinning argument '{pname}' of "
                            f"{callee}() in {qual} derives from "
                            f"data-dependent {hazard} without riding "
                            "a grow-only floor: when the live value "
                            "shrinks across a bucket boundary this "
                            "recompiles the compiled chain mid-"
                            "steady-state (the PR 8 bug class); "
                            "route it through a grow-only *_floor / "
                            "pad_bucket(minimum=...) or suppress "
                            "with the one-shot-lane reason"
                        ),
                    ))
    return out
