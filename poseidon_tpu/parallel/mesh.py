"""Mesh construction helpers."""

from __future__ import annotations

import jax
from jax.sharding import Mesh


def make_mesh(n_devices: int | None = None, axis: str = "tasks") -> Mesh:
    """A 1-D device mesh over the first ``n_devices`` local devices.

    One axis is all the solver needs: the dense auction's state is
    task-major, and machine-side tables are small enough to replicate
    (M * S ints), so the natural layout is task-sharded / machine-
    replicated — collectives then only carry per-machine aggregates
    (price tables, seat thresholds), never the [T, M] cost table.
    """
    devs = jax.devices()
    if n_devices is not None:
        if n_devices > len(devs):
            raise ValueError(
                f"requested {n_devices} devices, have {len(devs)}"
            )
        devs = devs[:n_devices]
    if len(devs) & (len(devs) - 1):
        raise ValueError(
            f"mesh size must be a power of two to divide the padded "
            f"task axis; got {len(devs)} devices"
        )
    return Mesh(devs, (axis,))
