"""Device-mesh partitioning for the scheduling solver.

The reference's only compute-parallel seam is fork/exec of a solver
binary per round (deploy/poseidon.cfg:8-9); its cross-machine story is
HTTP to the apiserver. The TPU-native replacement spans chips instead:
the dense auction's task-axis tables shard over a ``jax.sharding.Mesh``
(GSPMD inserts the collectives the sorts/segment-reductions need over
ICI), and the exactness certificate has an explicit ``shard_map`` +
``psum`` implementation whose partial sums ride the same mesh.
"""

from poseidon_tpu.parallel.mesh import make_mesh
from poseidon_tpu.parallel.sharded import (
    collective_account,
    resident_round_shardings,
    shard_instance,
    sharded_certificate_gap,
    solve_dense_sharded,
)

__all__ = [
    "collective_account",
    "make_mesh",
    "resident_round_shardings",
    "shard_instance",
    "sharded_certificate_gap",
    "solve_dense_sharded",
]
