"""Task-axis sharding of the dense auction over a device mesh.

Two complementary mechanisms, both exact:

- ``solve_dense_sharded``: the UNCHANGED auction kernel runs under jit
  with its task-major arrays laid out via ``NamedSharding`` over the
  mesh. XLA's SPMD partitioner inserts the collectives the program
  needs (all-to-alls for the global lexicographic sort that seats
  bids, all-reduces for the convergence tests and certificate sums) —
  the "pick a mesh, annotate shardings, let the compiler insert
  collectives" recipe. Results are bit-identical to single-device
  because the partitioned program computes the same function.

- ``sharded_certificate_gap``: an explicit ``shard_map`` + ``psum``
  implementation of the primal-dual certificate: every shard reduces
  its local tasks' primal and dual contributions and one psum over the
  mesh produces the global gap. This is the hand-written collective
  path (useful as a cross-check of the in-kernel certificate and as
  the template for scaling the solve past one slice, where explicit
  communication control matters).

Machine-side state (slot table, floors, price aggregates) is
replicated: it is O(M) ints, thousands of times smaller than the
[T, M] cost table, so the ICI traffic per round is per-machine
aggregates only.

When width > 1 wins: the compiled program carries ~25 collectives per
auction round (collective_account: 9 all-reduce + 16 all-gather of
O(M) int32), ~4 KiB each at M = 1k. On real v5e ICI (~45 GB/s/link,
~1 us/hop public figures) that is ~30-60 us/round of latency-dominated
collective cost, while sharding the task axis saves (N-1)/N of the
round's dense-pass bytes. Width 8 therefore wins once the per-round
dense pass exceeds ~250 us — i.e. B x M >= ~50M int32 (B = bid window
= max(1024, T/4)) — and loses below it. PERF.md "Sharding" multiplies
this out: the 10k-task flagship fits one chip and SHOULD run width 1;
a 100k-task x 12k-machine cluster is firmly in the width-8 win region.
"""

from __future__ import annotations

import dataclasses
import re

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from poseidon_tpu.compat import enable_x64, shard_map
from poseidon_tpu.ops.dense_auction import (
    INF,
    DenseInstance,
    DenseState,
    cold_start,
    solve_dense,
    _solve,
)


def shard_instance(dev: DenseInstance, mesh: Mesh) -> DenseInstance:
    """Lay the instance out over the mesh: task axis sharded, machine
    tables replicated. Tp is a power-of-two padding bucket, so it
    divides any power-of-two mesh size."""
    axis = mesh.axis_names[0]
    task_sharded = NamedSharding(mesh, P(axis))
    task_mach = NamedSharding(mesh, P(axis, None))
    repl = NamedSharding(mesh, P())
    return DenseInstance(
        c=jax.device_put(dev.c, task_mach),
        u=jax.device_put(dev.u, task_sharded),
        w=jax.device_put(dev.w, task_sharded),
        dgen=jax.device_put(dev.dgen, repl),
        s=jax.device_put(dev.s, repl),
        task_valid=jax.device_put(dev.task_valid, task_sharded),
        scale=jax.device_put(dev.scale, repl),
        cmax=jax.device_put(dev.cmax, repl),
        smax=dev.smax,
    )


def solve_dense_sharded(
    sharded: DenseInstance,
    *,
    warm: DenseState | None = None,
    alpha: int = 1024,
    max_rounds: int | None = None,
) -> DenseState:
    """Solve an instance previously laid out by ``shard_instance``.

    Taking the sharded instance (not re-sharding internally) keeps the
    warm incremental path at zero per-round [T, M] transfers — lay the
    table out once per cluster shape, re-solve every tick.

    The kernel is identical to the single-device path; only the data
    layout differs, so converged results match bit-for-bit.
    """
    return solve_dense(
        sharded, warm=warm, alpha=alpha, max_rounds=max_rounds
    )


# The resident round's task-major topology fields (ops/resident.py
# DenseTopology): these shard over the mesh's task axis in the
# production lane; machine-side tables and the n_tasks scalar replicate
# (O(M) ints, thousands of times smaller than the [T, M] table).
RESIDENT_TASK_FIELDS = frozenset({
    "arc_unsched", "arc_cluster", "arc_u2s",
    "arc_pref", "pref_machine", "pref_rack",
})


def resident_round_shardings(mesh: Mesh, dt_host):
    """(inputs_sharding, topology_sharding_tree) for one resident round.

    This is the ``parallel/`` promotion from certificate artifact to
    production lane: the bridge's resident solver lays its ONE batched
    upload out with these shardings and the UNCHANGED fused chain
    (cost model → densify → solve → finalize) compiles as an SPMD
    program whose [T, M] table, bid windows and seat sorts are
    task-sharded — HBM and compute scale with mesh width, results
    bit-identical to single-device (the partitioned program computes
    the same function; asserted by tests/test_scale.py).

    ``dt_host`` is the host DenseTopology dataclass; pricing inputs
    (arc-major CostInputs, O(arcs) ints) replicate — the model's output
    cost vector is gathered by the task-sharded index maps, so the
    derived dense table comes out task-sharded without any resharding.
    """
    axis = mesh.axis_names[0]
    repl = NamedSharding(mesh, P())

    def spec(f):
        v = getattr(dt_host, f.name)
        if f.name in RESIDENT_TASK_FIELDS:
            nd = getattr(v, "ndim", 0)
            return NamedSharding(mesh, P(axis, *([None] * (nd - 1))))
        return repl

    dt_spec = type(dt_host)(
        **{f.name: spec(f) for f in dataclasses.fields(dt_host)}
    )
    return repl, dt_spec


_COLLECTIVE_OPS = (
    "all-reduce",
    "all-gather",
    "all-to-all",
    "collective-permute",
    "reduce-scatter",
)


def collective_account(
    sharded: DenseInstance, *, alpha: int = 1024,
    max_rounds: int | None = None,
) -> dict[str, int]:
    """Count the collectives XLA's SPMD partitioner inserted into the
    compiled sharded solve (optimized-HLO audit, SURVEY §2.4).

    The task axis is sharded and machine aggregates are replicated, so
    the expected shape is: all-reduces for per-machine price/fullness
    aggregates and convergence tests, and all-to-alls only where the
    global lexicographic seat sort crosses shards. The returned counts
    are per compiled program (the while-loop body's collectives appear
    once — they run every round at O(M) bytes, never O(T x M))."""
    from poseidon_tpu.ops.dense_auction import default_fuse

    if max_rounds is None:
        max_rounds = default_fuse()
    asg0, lvl0, floor0, eps0 = cold_start(sharded, alpha)
    with enable_x64(True):
        compiled = _solve.lower(
            sharded, asg0, lvl0, floor0, eps0, alpha,
            max_rounds, sharded.smax, analytic_init=True,
        ).compile()
        txt = compiled.as_text()
    return {
        op: len(re.findall(rf"{op}(?:-start)?\(", txt))
        for op in _COLLECTIVE_OPS
    }


def _gap_kernel(c, u, task_valid, s, asg, lvl, floor, scale, mesh_axis):
    # runs INSIDE shard_map: every array here is the per-shard block
    Mp = s.shape[0]
    UNS = Mp
    on_machine = (asg >= 0) & (asg < Mp)
    seg = jnp.where(on_machine, asg, Mp)
    # per-machine holder aggregates: local partials + mesh reduction
    local_min = jax.ops.segment_min(
        jnp.where(on_machine, lvl, INF), seg, num_segments=Mp + 1
    )[:Mp]
    local_cnt = jax.ops.segment_sum(
        on_machine.astype(jnp.int32), seg, num_segments=Mp + 1
    )[:Mp]
    glob_min = -jax.lax.pmax(-local_min, axis_name=mesh_axis)
    glob_cnt = jax.lax.psum(local_cnt, axis_name=mesh_axis)
    full = glob_cnt >= s
    lam = jnp.where(full & (s > 0), jnp.minimum(glob_min, INF), 0)
    v = jnp.minimum(c + jnp.where(s > 0, lam, INF)[None, :], INF)
    b1 = jnp.minimum(jnp.min(v, axis=1), u)
    c_asg = jnp.take_along_axis(
        c, jnp.clip(asg, 0, Mp - 1)[:, None], axis=1
    )[:, 0]
    per_task = jnp.where(
        on_machine, c_asg, jnp.where(asg == UNS, u, INF)
    )
    per_task = jnp.where(task_valid, per_task, 0)
    local_primal = jnp.sum(per_task.astype(jnp.int64))
    local_b1 = jnp.sum(jnp.where(task_valid, b1, 0).astype(jnp.int64))
    primal = jax.lax.psum(local_primal, axis_name=mesh_axis)
    b1_sum = jax.lax.psum(local_b1, axis_name=mesh_axis)
    price_mass = jnp.sum(s.astype(jnp.int64) * lam.astype(jnp.int64))
    return primal - (b1_sum - price_mass)


def sharded_certificate_gap(
    dev: DenseInstance, state: DenseState, mesh: Mesh
) -> int:
    """Primal-dual gap via explicit shard_map + psum over the mesh."""
    axis = mesh.axis_names[0]
    tm = P(axis, None)
    tv = P(axis)
    rp = P()

    def kernel(c, u, task_valid, s, asg, lvl, floor, scale):
        return _gap_kernel(
            c, u, task_valid, s, asg, lvl, floor, scale, mesh_axis=axis
        )

    fn = shard_map(
        kernel,
        mesh=mesh,
        in_specs=(tm, tv, tv, rp, tv, tv, rp, rp),
        out_specs=rp,
    )
    with enable_x64(True):
        gap = fn(
            dev.c, dev.u, dev.task_valid, dev.s,
            state.asg, state.lvl, state.floor, dev.scale,
        )
    return int(jax.device_get(gap))
