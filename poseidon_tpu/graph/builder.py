"""Host-side flow-graph builder: ClusterState -> FlowNetwork + metadata.

Reproduces the Firmament flow-network taxonomy that the reference drives
through ``FlowScheduler`` (reference src/firmament/scheduler_bridge.cc:
37-42,61-127): task nodes with unit supply, one unscheduled aggregator per
job, a cluster aggregator, optional rack aggregators, machine nodes (the
reference registers one RESOURCE_PU per k8s node under a coordinator root,
scheduler_bridge.cc:94-127), and a sink absorbing all flow. Costs are NOT
assigned here — the builder emits per-arc metadata (kind + endpoint
indices) and a cost model (poseidon_tpu/models/) computes the int32 cost
vector on device, so cost recompute per round is a pure vectorized op.

Node order (deterministic): [sink, cluster_agg, racks..., machines...,
unsched_aggs..., tasks...].

The build is split in two stages so the per-round cost can scale with
*churn* instead of cluster size:

- ``FlowGraphBuilder.extract_columns`` walks the Python task/machine
  objects once and compacts them into ``BuilderColumns`` (numpy columns
  in canonical pending order) — the only O(tasks·prefs) Python work;
- ``FlowGraphBuilder.assemble`` turns columns into the arc families +
  ``GraphMeta`` with pure vectorized numpy.

``IncrementalFlowGraphBuilder`` keeps a live ``BuilderColumns`` and
patches it from O(K) churn events (task add/remove/update/age, slot
deltas) fed by the scheduler bridge, falling back to a full re-extract
on anything it cannot patch (machine-set changes, mid-order pending
re-inserts). Because both paths share ``assemble``, a delta build is
bit-identical to a from-scratch build by construction; the differential
suite in tests/test_incremental.py asserts it anyway.

Rebalancing mode (``preemption=True``, the Firmament semantics behind
``SchedulingDelta::MIGRATE``/``PREEMPT``): RUNNING tasks enter the
graph as schedulable task nodes instead of merely discounting machine
slots. Each running task gets (a) a *continuation* arc to its current
machine — structurally an ordinary ``TASK_TO_MACHINE`` preference arc
(so the transportation form and the dense kernel apply unchanged)
carrying a ``migration_hysteresis`` discount the cost layer subtracts,
(b) the usual wildcard/preference arcs (the migration destinations),
and (c) a priced unscheduled arc whose selection means PREEMPT (the
cost layer overlays the preemption penalty). The running block is kept
in uid-sorted order, separate from the pending block, so O(churn)
patches never shift pending positions; running tasks route their
unsched arcs through per-job aggregators of their own (``run:<job>``)
— aggregator→sink arcs cost 0 under every registry model, so the split
is cost-neutral while keeping the two blocks independently patchable.
"""

from __future__ import annotations

import collections
import dataclasses
import logging
from enum import IntEnum

import numpy as np

from poseidon_tpu.cluster import ClusterState, Task, TaskPhase
from poseidon_tpu.graph.network import FlowNetwork

log = logging.getLogger(__name__)


class NodeRole(IntEnum):
    SINK = 0
    CLUSTER_AGG = 1
    RACK = 2
    MACHINE = 3
    UNSCHED = 4
    TASK = 5


class ArcKind(IntEnum):
    TASK_TO_UNSCHED = 0    # always present: leaving a task unscheduled
    TASK_TO_CLUSTER = 1    # wildcard arc through the cluster aggregator
    TASK_TO_MACHINE = 2    # preference arc (data locality)
    TASK_TO_RACK = 3       # preference arc to a rack aggregator
    CLUSTER_TO_MACHINE = 4
    RACK_TO_MACHINE = 5
    MACHINE_TO_SINK = 6
    UNSCHED_TO_SINK = 7


@dataclasses.dataclass(frozen=True)
class GraphMeta:
    """Host-side metadata parallel to the padded arc/node tables.

    Arrays are over REAL arcs/nodes (unpadded); index -1 means
    not-applicable. This is what cost models and the delta extractor
    consume.
    """

    node_role: np.ndarray     # int8[n_nodes]
    arc_kind: np.ndarray      # int8[n_arcs]
    arc_task: np.ndarray      # int32[n_arcs]  task index or -1
    arc_machine: np.ndarray   # int32[n_arcs]  machine index or -1
    arc_rack: np.ndarray      # int32[n_arcs]  rack index or -1
    arc_weight: np.ndarray    # int32[n_arcs]  data-locality weight (pref
                              # arcs; 0 elsewhere) — Quincy's input
    arc_discount: np.ndarray  # int32[n_arcs]  hysteresis discount
                              # (continuation arcs; 0 elsewhere)
    task_wait: np.ndarray     # int32[n_tasks] rounds each task has waited
    task_current: np.ndarray  # int32[n_tasks] current machine of a
                              # RUNNING task, -1 for pending — what the
                              # delta extractor diffs assignments against
    task_node: np.ndarray     # int32[n_tasks] node id of each task
    machine_node: np.ndarray  # int32[n_machines]
    node_machine: np.ndarray  # int32[n_nodes] machine index or -1
    task_uids: list[str]
    machine_names: list[str]
    rack_names: list[str]
    job_ids: list[str]        # per unsched-aggregator job id
    n_nodes: int
    n_arcs: int


@dataclasses.dataclass
class BuilderColumns:
    """Numpy-columnar snapshot of one round's scheduling input.

    Everything ``assemble`` needs, in canonical order (machines in
    cluster order; pending tasks in ``ClusterState.pending()`` order;
    jobs by first occurrence among pending tasks; a task's preference
    rows task-major in ``data_prefs`` iteration order). ``cpu_milli`` /
    ``mem_kb`` ride along for the bridge's pricing inputs so a delta
    round does not re-walk the task objects for them either.
    """

    machine_names: list[str]
    midx: dict[str, int]      # machine name -> index
    m_rack: np.ndarray        # int32[M] rack index or -1
    m_max: np.ndarray         # int64[M] max_tasks per machine
    used_slots: np.ndarray    # int64[M] RUNNING tasks bound per machine
    racks: list[str]
    uids: np.ndarray          # object[T] pending task uids
    jobs: np.ndarray          # object[J] job ids, first-occurrence order
    job_idx: np.ndarray       # int32[T]
    job_counts: np.ndarray    # int64[J] pending tasks per job
    wait: np.ndarray          # int32[T]
    pref_counts: np.ndarray   # int64[T] preference rows per task
    pref_m: np.ndarray        # int32[Ep] machine index or -1
    pref_r: np.ndarray        # int32[Ep] rack index or -1
    pref_w: np.ndarray        # int32[Ep] locality weight
    cpu_milli: np.ndarray     # int64[T] requested milli-cores
    mem_kb: np.ndarray        # int64[T] requested memory
    # Rebalancing block (preemption mode): RUNNING tasks in uid-sorted
    # order, kept separate from the pending block so O(churn) patches
    # on either block never shift the other's positions. Empty in
    # place-only mode. ``merge_columns`` flattens this block into the
    # canonical task sequence (pending first, then running) before
    # assembly / topology derivation.
    run_uids: np.ndarray = dataclasses.field(
        default_factory=lambda: np.zeros(0, object))   # object[Rt]
    run_job: np.ndarray = dataclasses.field(
        default_factory=lambda: np.zeros(0, object))   # object[Rt]
    run_machine: np.ndarray = dataclasses.field(
        default_factory=lambda: np.zeros(0, np.int32))  # int32[Rt]
    run_wait: np.ndarray = dataclasses.field(
        default_factory=lambda: np.zeros(0, np.int32))  # int32[Rt]
    run_cpu: np.ndarray = dataclasses.field(
        default_factory=lambda: np.zeros(0, np.int64))  # int64[Rt]
    run_mem: np.ndarray = dataclasses.field(
        default_factory=lambda: np.zeros(0, np.int64))  # int64[Rt]
    run_pref_counts: np.ndarray = dataclasses.field(
        default_factory=lambda: np.zeros(0, np.int64))  # int64[Rt]
    run_pref_m: np.ndarray = dataclasses.field(
        default_factory=lambda: np.zeros(0, np.int32))  # int32[Erp]
    run_pref_r: np.ndarray = dataclasses.field(
        default_factory=lambda: np.zeros(0, np.int32))  # int32[Erp]
    run_pref_w: np.ndarray = dataclasses.field(
        default_factory=lambda: np.zeros(0, np.int32))  # int32[Erp]
    # Merged-view extras, set by ``merge_columns`` only (None on the
    # patchable form): current machine per task (-1 = pending) and the
    # per-pref-row hysteresis discount.
    current_m: np.ndarray | None = None   # int32[T]
    pref_d: np.ndarray | None = None      # int32[Ep]


class FlowGraphBuilder:
    """Builds the MCMF instance for one scheduling round.

    ``pref_arcs`` controls whether task data-preference arcs (Quincy-style)
    are emitted; the trivial cost model routes everything through the
    cluster aggregator like Firmament's TrivialCostModel does.

    ``preemption`` turns on rebalancing mode: RUNNING tasks become
    schedulable nodes with a continuation arc to their current machine
    (discounted by ``migration_hysteresis``) and a priced unscheduled
    arc, so the solver may keep, migrate, or preempt them. Machine
    slots are then NOT discounted for running tasks — they hold their
    seats through their own unit of flow.
    """

    def __init__(
        self,
        *,
        pref_arcs: bool = True,
        rack_aggs: bool = True,
        preemption: bool = False,
        migration_hysteresis: int = 20,
    ):
        self.pref_arcs = pref_arcs
        self.rack_aggs = rack_aggs
        self.preemption = preemption
        self.migration_hysteresis = int(migration_hysteresis)

    def build(self, cluster: ClusterState) -> tuple[FlowNetwork, GraphMeta]:
        """Build and upload the padded device FlowNetwork + metadata."""
        arrays, meta = self.build_arrays(cluster)
        net = FlowNetwork.from_arrays(
            arrays["src"], arrays["dst"], arrays["cap"],
            np.zeros(meta.n_arcs, dtype=np.int32),  # costs: the model's job
            arrays["supply"],
        )
        return net, meta

    def build_arrays(
        self, cluster: ClusterState
    ) -> tuple[dict[str, np.ndarray], GraphMeta]:
        """Build the graph as HOST arrays only (no device upload).

        The device-resident round (ops/resident.py) consumes these
        directly: topology index maps are derived host-side and the only
        per-round device traffic is one batched upload of pricing inputs
        — the builder must not force its own src/dst/cap transfer.
        """
        return self.assemble(self.extract_columns(cluster))

    # ---- stage 1: Python-object walk -> numpy columns -----------------

    def _task_prefs(
        self, task: Task, midx: dict[str, int], rack_idx: dict[str, int]
    ) -> list[tuple[int, int, int]]:
        """One task's resolved (machine_idx, rack_idx, weight) pref rows,
        in ``data_prefs`` iteration order (unknown names dropped)."""
        if not self.pref_arcs:
            return []
        return [
            (midx.get(name, -1), rack_idx.get(name, -1), int(weight))
            for name, weight in task.data_prefs.items()
            if name in midx or name in rack_idx
        ]

    def task_arc_rows(
        self, task: Task, midx: dict[str, int], rack_idx: dict[str, int]
    ) -> list[tuple[int, int, int]]:
        """Public single-event column patch: ONE task's resolved pref
        rows, exactly as a full extract or an incremental delta build
        would produce them. The express lane (bridge ``express_batch``
        -> ``ops/resident.py`` arrival rows) prices arrivals from this
        same resolution, so the periodic correction round — whose
        incremental build applies the identical patch — sees an
        identical graph for the pod."""
        return self._task_prefs(task, midx, rack_idx)

    def extract_columns(self, cluster: ClusterState) -> BuilderColumns:
        """The O(tasks·prefs) Python walk, done once per full rebuild."""
        machines = cluster.machines
        tasks = cluster.pending()
        racks = cluster.racks() if self.rack_aggs else []
        rack_idx = {r: i for i, r in enumerate(racks)}
        midx = cluster.machine_index()

        jobs: list[str] = []
        job_lookup: dict[str, int] = {}
        for t in tasks:
            if t.job_id not in job_lookup:
                job_lookup[t.job_id] = len(jobs)
                jobs.append(t.job_id)
        J = len(jobs)
        T = len(tasks)
        job_idx = np.array(
            [job_lookup[t.job_id] for t in tasks], dtype=np.int32
        )
        job_counts = (
            np.bincount(job_idx, minlength=J).astype(np.int64)
            if T else np.zeros(J, np.int64)
        )

        # Slots already consumed by RUNNING tasks: the reference tracks
        # running tasks against --max_tasks_per_pu inside Firmament; we
        # discount machine capacity here so re-offered slots are real.
        # In rebalancing mode running tasks are schedulable nodes and
        # hold their seats through their own unit of flow, so slots
        # stay undiscounted.
        used_slots = np.zeros(len(machines), dtype=np.int64)
        run_block: dict = {}
        if self.preemption:
            running_tasks = sorted(
                (t for t in cluster.tasks
                 if t.phase == TaskPhase.RUNNING and t.machine in midx),
                key=lambda t: t.uid,
            )
            per_run = [
                self._task_prefs(t, midx, rack_idx) for t in running_tasks
            ]
            run_trip = [row for rows in per_run for row in rows]
            run_block = dict(
                run_uids=np.array(
                    [t.uid for t in running_tasks], dtype=object
                ),
                run_job=np.array(
                    [t.job_id for t in running_tasks], dtype=object
                ),
                run_machine=np.array(
                    [midx[t.machine] for t in running_tasks], np.int32
                ),
                run_wait=np.array(
                    [t.wait_rounds for t in running_tasks], np.int32
                ),
                run_cpu=np.array(
                    [int(t.cpu_request * 1000) for t in running_tasks],
                    np.int64,
                ),
                run_mem=np.array(
                    [t.memory_request_kb for t in running_tasks],
                    np.int64,
                ),
                run_pref_counts=np.array(
                    [len(rows) for rows in per_run], np.int64
                ),
                run_pref_m=np.array([x[0] for x in run_trip], np.int32),
                run_pref_r=np.array([x[1] for x in run_trip], np.int32),
                run_pref_w=np.array([x[2] for x in run_trip], np.int32),
            )
        else:
            running = [
                midx[t.machine] for t in cluster.tasks
                if t.phase == TaskPhase.RUNNING and t.machine in midx
            ]
            if running:
                np.add.at(used_slots, running, 1)

        per_task = [self._task_prefs(t, midx, rack_idx) for t in tasks]
        trip = [row for rows in per_task for row in rows]
        pref_counts = np.array(
            [len(rows) for rows in per_task], dtype=np.int64
        ) if T else np.zeros(0, np.int64)

        return BuilderColumns(
            machine_names=[m.name for m in machines],
            midx=midx,
            m_rack=np.array(
                [rack_idx.get(m.rack, -1) if m.rack else -1
                 for m in machines],
                dtype=np.int32,
            ),
            m_max=np.array(
                [int(m.max_tasks) for m in machines], np.int64
            ),
            used_slots=used_slots,
            racks=racks,
            uids=np.array([t.uid for t in tasks], dtype=object),
            jobs=np.array(jobs, dtype=object),
            job_idx=job_idx,
            job_counts=job_counts,
            wait=np.array([t.wait_rounds for t in tasks], dtype=np.int32),
            pref_counts=pref_counts,
            pref_m=np.array([x[0] for x in trip], dtype=np.int32),
            pref_r=np.array([x[1] for x in trip], dtype=np.int32),
            pref_w=np.array([x[2] for x in trip], dtype=np.int32),
            cpu_milli=np.array(
                [int(t.cpu_request * 1000) for t in tasks], np.int64
            ),
            mem_kb=np.array(
                [t.memory_request_kb for t in tasks], np.int64
            ),
            **run_block,
        )

    # ---- stage 1.5: flatten the running block (pure numpy) ------------

    def merge_columns(self, cols: BuilderColumns) -> BuilderColumns:
        """Flatten the rebalancing block into the canonical task order.

        Returns ``cols`` unchanged when there is no running block (or it
        is already merged), so place-only mode pays nothing. Running
        tasks follow the pending block; each contributes its
        continuation row (current machine, weight 0, hysteresis
        discount) as its FIRST preference row, then its data prefs;
        their unsched aggregators are per-job but namespaced
        (``run:<job>``) so the two blocks stay independently patchable
        — aggregator→sink arcs cost 0 under every registry model, so
        the split is cost-neutral.
        """
        Rt = len(cols.run_uids)
        if cols.current_m is not None or Rt == 0:
            return cols
        T, J = len(cols.uids), len(cols.jobs)
        # running-block jobs: first occurrence among uid-sorted tasks
        rj, first, inv = np.unique(
            cols.run_job, return_index=True, return_inverse=True
        )
        order = np.argsort(first, kind="stable")
        rank = np.empty(len(order), np.int32)
        rank[order] = np.arange(len(order), dtype=np.int32)
        run_job_idx = rank[inv].astype(np.int32)
        run_jobs = rj[order]
        run_job_counts = np.bincount(
            run_job_idx, minlength=len(run_jobs)
        ).astype(np.int64)
        # continuation rows, inserted as each task's first pref row
        starts = np.zeros(Rt, np.int64)
        if Rt > 1:
            starts[1:] = np.cumsum(cols.run_pref_counts)[:-1]
        h = np.int32(self.migration_hysteresis)
        n_rp = len(cols.run_pref_m)
        pref_m2 = np.insert(cols.run_pref_m, starts, cols.run_machine)
        pref_r2 = np.insert(
            cols.run_pref_r, starts, np.full(Rt, -1, np.int32)
        )
        pref_w2 = np.insert(
            cols.run_pref_w, starts, np.zeros(Rt, np.int32)
        )
        pref_d2 = np.insert(
            np.zeros(n_rp, np.int32), starts, np.full(Rt, h, np.int32)
        )
        return dataclasses.replace(
            cols,
            uids=np.concatenate([cols.uids, cols.run_uids]),
            jobs=np.concatenate([
                cols.jobs,
                np.array([f"run:{j}" for j in run_jobs], dtype=object),
            ]),
            job_idx=np.concatenate([cols.job_idx, run_job_idx + J]),
            job_counts=np.concatenate([cols.job_counts, run_job_counts]),
            wait=np.concatenate([cols.wait, cols.run_wait]),
            pref_counts=np.concatenate(
                [cols.pref_counts, cols.run_pref_counts + 1]
            ),
            pref_m=np.concatenate([cols.pref_m, pref_m2]),
            pref_r=np.concatenate([cols.pref_r, pref_r2]),
            pref_w=np.concatenate([cols.pref_w, pref_w2]),
            cpu_milli=np.concatenate([cols.cpu_milli, cols.run_cpu]),
            mem_kb=np.concatenate([cols.mem_kb, cols.run_mem]),
            current_m=np.concatenate([
                np.full(T, -1, np.int32), cols.run_machine,
            ]),
            pref_d=np.concatenate([
                np.zeros(len(cols.pref_m), np.int32), pref_d2,
            ]),
            run_uids=np.zeros(0, object),
            run_job=np.zeros(0, object),
            run_machine=np.zeros(0, np.int32),
            run_wait=np.zeros(0, np.int32),
            run_cpu=np.zeros(0, np.int64),
            run_mem=np.zeros(0, np.int64),
            run_pref_counts=np.zeros(0, np.int64),
            run_pref_m=np.zeros(0, np.int32),
            run_pref_r=np.zeros(0, np.int32),
            run_pref_w=np.zeros(0, np.int32),
        )

    # ---- stage 2: columns -> arc families + meta (pure numpy) ---------

    def assemble(
        self, cols: BuilderColumns
    ) -> tuple[dict[str, np.ndarray], GraphMeta]:
        cols = self.merge_columns(cols)
        M, T = len(cols.machine_names), len(cols.uids)
        R, J = len(cols.racks), len(cols.jobs)
        # node layout
        SINK = 0
        CLUSTER = 1
        rack_base = 2
        machine_base = rack_base + R
        unsched_base = machine_base + M
        task_base = unsched_base + J
        n_nodes = task_base + T

        node_role = np.empty(n_nodes, dtype=np.int8)
        node_role[SINK] = NodeRole.SINK
        node_role[CLUSTER] = NodeRole.CLUSTER_AGG
        node_role[rack_base:machine_base] = NodeRole.RACK
        node_role[machine_base:unsched_base] = NodeRole.MACHINE
        node_role[unsched_base:task_base] = NodeRole.UNSCHED
        node_role[task_base:] = NodeRole.TASK

        node_machine = np.full(n_nodes, -1, dtype=np.int32)
        node_machine[machine_base:unsched_base] = np.arange(
            M, dtype=np.int32
        )

        # Everything below is vectorized per arc FAMILY (a per-arc
        # Python append loop costs ~300 ms at the 10k-pod flagship and
        # runs every scheduling round). Family order:
        # [task->unsched, task->cluster, prefs..., cluster->machine,
        #  rack->machine, machine->sink, unsched->sink]; nothing
        # downstream depends on arc order, only on kind labels.
        job_of = cols.job_idx
        job_task_count = cols.job_counts

        t_ids = np.arange(T, dtype=np.int32)
        t_nodes = task_base + t_ids

        p_t = np.repeat(t_ids, cols.pref_counts)
        p_m, p_r, p_w = cols.pref_m, cols.pref_r, cols.pref_w
        p_d = (
            cols.pref_d if cols.pref_d is not None
            else np.zeros(len(p_m), np.int32)
        )
        current_m = (
            cols.current_m if cols.current_m is not None
            else np.full(T, -1, np.int32)
        )
        is_mp = p_m >= 0

        m_ids = np.arange(M, dtype=np.int32)
        m_nodes = machine_base + m_ids
        slots = np.maximum(cols.m_max - cols.used_slots, 0).astype(
            np.int32
        )
        m_rack = cols.m_rack
        has_rack = m_rack >= 0

        def fam(n, s, d, c, k, ti=None, mi=None, ri=None, wt=None,
                dc=None):
            neg1 = np.full(n, -1, np.int32)
            return (
                np.broadcast_to(np.asarray(s, np.int32), (n,)),
                np.broadcast_to(np.asarray(d, np.int32), (n,)),
                np.broadcast_to(np.asarray(c, np.int32), (n,)),
                np.full(n, int(k), np.int8),
                neg1 if ti is None else np.asarray(ti, np.int32),
                neg1 if mi is None else np.asarray(mi, np.int32),
                neg1 if ri is None else np.asarray(ri, np.int32),
                np.zeros(n, np.int32) if wt is None
                else np.asarray(wt, np.int32),
                np.zeros(n, np.int32) if dc is None
                else np.asarray(dc, np.int32),
            )

        families = [
            fam(T, t_nodes, unsched_base + job_of, 1,
                ArcKind.TASK_TO_UNSCHED, ti=t_ids),
            fam(T, t_nodes, CLUSTER, 1, ArcKind.TASK_TO_CLUSTER,
                ti=t_ids),
            fam(int(is_mp.sum()), task_base + p_t[is_mp],
                machine_base + p_m[is_mp], 1, ArcKind.TASK_TO_MACHINE,
                ti=p_t[is_mp], mi=p_m[is_mp], wt=p_w[is_mp],
                dc=p_d[is_mp]),
            fam(int((~is_mp).sum()), task_base + p_t[~is_mp],
                rack_base + p_r[~is_mp], 1, ArcKind.TASK_TO_RACK,
                ti=p_t[~is_mp], ri=p_r[~is_mp], wt=p_w[~is_mp],
                dc=p_d[~is_mp]),
            fam(M, CLUSTER, m_nodes, slots, ArcKind.CLUSTER_TO_MACHINE,
                mi=m_ids),
            fam(int(has_rack.sum()), rack_base + m_rack[has_rack],
                m_nodes[has_rack], slots[has_rack],
                ArcKind.RACK_TO_MACHINE, mi=m_ids[has_rack],
                ri=m_rack[has_rack]),
            fam(M, m_nodes, SINK, slots, ArcKind.MACHINE_TO_SINK,
                mi=m_ids),
            fam(J, unsched_base + np.arange(J, dtype=np.int32), SINK,
                job_task_count.astype(np.int32),
                ArcKind.UNSCHED_TO_SINK),
        ]
        (src, dst, cap, kind, a_task, a_machine, a_rack, a_weight,
         a_discount) = (
            np.concatenate(cols_) for cols_ in zip(*families)
        )

        supply = np.zeros(n_nodes, dtype=np.int64)
        supply[task_base:] = 1
        supply[SINK] = -T

        n_arcs = len(src)
        arrays = {"src": src, "dst": dst, "cap": cap, "supply": supply}
        meta = GraphMeta(
            node_role=node_role,
            arc_kind=kind,
            arc_task=a_task,
            arc_machine=a_machine,
            arc_rack=a_rack,
            arc_weight=a_weight,
            arc_discount=a_discount,
            task_wait=cols.wait,
            task_current=current_m,
            task_node=np.arange(task_base, task_base + T, dtype=np.int32),
            machine_node=np.arange(machine_base, machine_base + M,
                                   dtype=np.int32),
            node_machine=node_machine,
            task_uids=cols.uids.tolist(),
            machine_names=list(cols.machine_names),
            rack_names=list(cols.racks),
            job_ids=cols.jobs.tolist(),
            n_nodes=n_nodes,
            n_arcs=n_arcs,
        )
        return arrays, meta


class _DeltaUnsupported(Exception):
    """A buffered churn event the delta path cannot patch exactly."""


class IncrementalFlowGraphBuilder:
    """O(churn) graph maintenance across scheduling rounds.

    The owner (SchedulerBridge) feeds ``note_*`` events as cluster state
    mutates; ``build_arrays`` patches the cached ``BuilderColumns`` and
    re-assembles — O(K) Python work for K churned pods plus vectorized
    numpy over the arrays, instead of the full O(tasks·prefs) object
    walk. Any event outside the patchable set (machine add/remove/
    attribute change, a pod re-entering the pending order mid-sequence,
    pref/job content changes) flips ``note_full_rebuild`` and the next
    build re-extracts from the cluster.

    Copy-on-write discipline: columns are replaced, never mutated in
    place, so arrays referenced by a previous round's ``GraphMeta`` (or
    already shipped to an in-flight solve) stay frozen.

    Self-healing: every delta build verifies the cached pending-uid
    sequence and machine-name list against the live cluster; a mismatch
    (a missed event path) logs a warning and falls back to a full
    rebuild, so a bookkeeping bug degrades to the old cost, never to a
    wrong graph.
    """

    def __init__(
        self,
        *,
        pref_arcs: bool = True,
        rack_aggs: bool = True,
        preemption: bool = False,
        migration_hysteresis: int = 20,
    ):
        self.builder = FlowGraphBuilder(
            pref_arcs=pref_arcs, rack_aggs=rack_aggs,
            preemption=preemption,
            migration_hysteresis=migration_hysteresis,
        )
        self._cols: BuilderColumns | None = None
        self._merged: BuilderColumns | None = None
        self._uid_pos: dict[str, int] = {}
        self._added: dict[str, Task] = {}
        self._removed: set[str] = set()
        self._updated: dict[str, Task] = {}
        self._aged: collections.Counter[str] = collections.Counter()
        self._slot_delta: collections.Counter[str] = collections.Counter()
        # running-block buffers (rebalancing mode)
        self._run_pos: dict[str, int] = {}
        self._run_added: dict[str, Task] = {}
        self._run_removed: set[str] = set()
        self._run_moved: dict[str, str] = {}
        self._run_updated: dict[str, Task] = {}
        self._rebuild: str | None = "cold"
        self.last_build_mode = ""
        self.builds_full = 0
        self.builds_delta = 0

    @property
    def preemption(self) -> bool:
        return self.builder.preemption

    # ---- churn notifications (all O(1)) -------------------------------

    def note_full_rebuild(self, why: str) -> None:
        if self._rebuild is None:
            self._rebuild = why
            self._added.clear()
            self._removed.clear()
            self._updated.clear()
            self._aged.clear()
            self._slot_delta.clear()
            self._run_added.clear()
            self._run_removed.clear()
            self._run_moved.clear()
            self._run_updated.clear()

    def note_task_added(self, task: Task) -> None:
        """A NEW pending pod appended at the end of the pending order."""
        if self._rebuild is not None:
            return
        if task.uid in self._removed or task.uid in self._uid_pos \
                or task.uid in self._added:
            # re-adds / duplicates cannot preserve the canonical order
            self.note_full_rebuild("pending re-insert")
            return
        self._added[task.uid] = task

    def note_task_removed(self, uid: str) -> None:
        """A pod left the pending set (placed, retired, disappeared)."""
        if self._rebuild is not None:
            return
        if uid in self._added:
            del self._added[uid]
            self._aged.pop(uid, None)
            self._updated.pop(uid, None)
            return
        if uid in self._uid_pos:
            self._removed.add(uid)
            self._updated.pop(uid, None)
            return
        self.note_full_rebuild("unknown pending removal")

    def note_task_updated(self, task: Task) -> None:
        """An existing pending pod's cpu/mem request changed in place
        (same uid, same position, same job + prefs)."""
        if self._rebuild is not None:
            return
        if task.uid in self._added:
            self._added[task.uid] = task
        elif task.uid in self._uid_pos:
            self._updated[task.uid] = task
        else:
            self.note_full_rebuild("unknown pending update")

    def note_task_aged(self, uid: str, rounds: int = 1) -> None:
        """A pending pod's wait_rounds grew by ``rounds``."""
        if self._rebuild is not None:
            return
        self._aged[uid] += rounds

    def note_slots_changed(self, machine: str, delta: int) -> None:
        """A machine's RUNNING-task count changed by ``delta``.

        Rebalancing mode ignores slot deltas: running tasks hold their
        seats through their own unit of flow, so capacity stays full.
        """
        if self._rebuild is not None or self.preemption:
            return
        self._slot_delta[machine] += delta

    # ---- running-block notifications (rebalancing mode, all O(1)) -----

    def note_running_added(self, task: Task) -> None:
        """A task entered the RUNNING set (confirm/adoption)."""
        if self._rebuild is not None:
            return
        uid = task.uid
        if uid in self._run_pos or uid in self._run_added \
                or uid in self._run_removed:
            # duplicates / re-adds inside one window would need a
            # remove+insert ordering the sorted merge cannot replay
            self.note_full_rebuild("running re-add")
            return
        if not task.machine:
            self.note_full_rebuild("running add without machine")
            return
        self._run_added[uid] = task

    def note_running_removed(self, uid: str) -> None:
        """A task left the RUNNING set (retired, preempted, evicted)."""
        if self._rebuild is not None:
            return
        if uid in self._run_added:
            del self._run_added[uid]
            self._run_moved.pop(uid, None)
            self._run_updated.pop(uid, None)
            return
        if uid in self._run_pos:
            self._run_removed.add(uid)
            self._run_moved.pop(uid, None)
            self._run_updated.pop(uid, None)
            return
        self.note_full_rebuild("unknown running removal")

    def note_running_moved(self, uid: str, machine: str) -> None:
        """A RUNNING task's machine changed (migration applied)."""
        if self._rebuild is not None:
            return
        if uid in self._run_added:
            self._run_added[uid] = dataclasses.replace(
                self._run_added[uid], machine=machine
            )
        elif uid in self._run_pos and uid not in self._run_removed:
            self._run_moved[uid] = machine
        else:
            self.note_full_rebuild("unknown running move")

    def note_running_updated(self, task: Task) -> None:
        """A RUNNING task's cpu/mem request changed in place (same
        uid, machine, job + prefs)."""
        if self._rebuild is not None:
            return
        uid = task.uid
        if uid in self._run_added:
            self._run_added[uid] = task
        elif uid in self._run_pos and uid not in self._run_removed:
            self._run_updated[uid] = task
        else:
            self.note_full_rebuild("unknown running update")

    # ---- build --------------------------------------------------------

    @property
    def columns(self) -> BuilderColumns | None:
        """The last build's MERGED columns (identical to the patchable
        columns in place-only mode, where the merge is the identity)."""
        return self._merged if self._merged is not None else self._cols

    def cost_columns(self) -> tuple[np.ndarray, np.ndarray]:
        """(task_cpu_milli, task_mem_kb) for the current task order
        (pending, then the running block in rebalancing mode)."""
        cols = self.columns
        assert cols is not None
        return cols.cpu_milli, cols.mem_kb

    def checkpoint_columns(self) -> BuilderColumns | None:
        """The patchable column set, checkpoint-clean (ha/checkpoint
        .py): buffered churn notes are folded in first (the exact
        O(churn) patch the next build would apply — the state machine
        is idempotent, so the next build simply finds nothing pending),
        because a snapshot of half-applied state would prime a restored
        builder with columns the notes never reached. None while a
        full rebuild is pending — there is nothing patchable to save.
        """
        if self._rebuild is not None or self._cols is None:
            return None
        try:
            self._apply_deltas()
        except _DeltaUnsupported as e:
            self.note_full_rebuild(str(e))
            return None
        return self._cols

    def restore_columns(self, cols: BuilderColumns) -> None:
        """Warm-restore priming (ha/checkpoint.py): adopt a
        checkpointed patchable column set as the cached state, so the
        first post-restore build patches O(churn) instead of
        re-extracting the whole cluster. Safe by construction: the
        next ``build_arrays`` runs the same self-heal verify every
        delta build runs — a snapshot that does not match the restored
        cluster degrades to a full rebuild loudly, never to a wrong
        graph."""
        self._cols = cols
        self._merged = None
        self._uid_pos = {
            u: i for i, u in enumerate(cols.uids.tolist())
        }
        self._run_pos = {
            u: i for i, u in enumerate(cols.run_uids.tolist())
        }
        self._added.clear()
        self._removed.clear()
        self._updated.clear()
        self._aged.clear()
        self._slot_delta.clear()
        self._run_added.clear()
        self._run_removed.clear()
        self._run_moved.clear()
        self._run_updated.clear()
        self._rebuild = None

    def build_arrays(
        self,
        cluster: ClusterState,
        pending: list[Task] | None = None,
    ) -> tuple[dict[str, np.ndarray], GraphMeta]:
        if pending is None:
            pending = cluster.pending()
        if self._rebuild is None and self._cols is not None:
            try:
                self._apply_deltas()
            except _DeltaUnsupported as e:
                self.note_full_rebuild(str(e))
        if self._rebuild is None and self._cols is not None:
            cols = self._cols
            # self-healing guard: the pending-uid sequence is the one
            # invariant every patch depends on — verify it in full each
            # build (O(T) C-level compare). Machines change only
            # through observe_nodes, which always notes; the length
            # check catches a bypassing mutation without paying an
            # O(M) name walk per round at 12k machines.
            ok = (
                len(pending) == len(cols.uids)
                and len(cluster.machines) == len(cols.machine_names)
                and [t.uid for t in pending] == cols.uids.tolist()  # noqa: PTA002 -- deliberate O(T) self-heal verify: a missed churn event must degrade to a full rebuild, never a wrong graph (class docstring)
            )
            if ok and self.preemption:
                # the running block is equally load-bearing in
                # rebalancing mode: verify (uid, machine) pairs against
                # the live cluster in canonical (uid-sorted) order
                live = sorted(  # noqa: PTA002 -- deliberate O(T) self-heal verify of the rebalancing running block (same contract as the pending check above)
                    (t.uid, t.machine) for t in cluster.tasks
                    if t.phase == TaskPhase.RUNNING
                    and t.machine in cols.midx
                )
                names = np.array(cols.machine_names, dtype=object)
                ok = len(live) == len(cols.run_uids) and live == list(
                    zip(cols.run_uids.tolist(),
                        names[cols.run_machine].tolist())
                )
            if not ok:
                log.warning(
                    "incremental graph state diverged from the cluster "
                    "(missed churn event?); falling back to full rebuild"
                )
                self.note_full_rebuild("verify-mismatch")
        if self._rebuild is not None:
            self._cols = self.builder.extract_columns(cluster)
            self._uid_pos = {
                u: i for i, u in enumerate(self._cols.uids.tolist())
            }
            self._run_pos = {
                u: i for i, u in enumerate(self._cols.run_uids.tolist())
            }
            self._rebuild = None
            self._added.clear()
            self._removed.clear()
            self._updated.clear()
            self._aged.clear()
            self._slot_delta.clear()
            self._run_added.clear()
            self._run_removed.clear()
            self._run_moved.clear()
            self._run_updated.clear()
            self.last_build_mode = "full"
            self.builds_full += 1
        else:
            self.last_build_mode = "delta"
            self.builds_delta += 1
        self._merged = self.builder.merge_columns(self._cols)
        return self.builder.assemble(self._merged)

    # ---- the O(K) patch ----------------------------------------------

    def _apply_deltas(self) -> None:
        cols = self._cols
        assert cols is not None
        if not (self._added or self._removed or self._updated
                or self._aged or self._slot_delta or self._run_added
                or self._run_removed or self._run_moved
                or self._run_updated):
            return
        uids = cols.uids
        jobs = cols.jobs
        job_idx = cols.job_idx
        job_counts = cols.job_counts
        wait = cols.wait
        pref_counts = cols.pref_counts
        pref_m, pref_r, pref_w = cols.pref_m, cols.pref_r, cols.pref_w
        cpu, mem = cols.cpu_milli, cols.mem_kb
        used_slots = cols.used_slots
        T, J = len(uids), len(jobs)

        if self._updated:
            cpu = cpu.copy()
            mem = mem.copy()
            for uid, t in self._updated.items():
                p = self._uid_pos[uid]
                cpu[p] = int(t.cpu_request * 1000)
                mem[p] = t.memory_request_kb

        if self._aged:
            wait = wait.copy()
            for uid, n in self._aged.items():
                p = self._uid_pos.get(uid)
                if p is None:
                    raise _DeltaUnsupported("aging of unknown task")
                wait[p] += n

        if self._removed:
            pos = np.fromiter(
                (self._uid_pos[u] for u in self._removed),
                np.int64, len(self._removed),
            )
            keep = np.ones(T, bool)
            keep[pos] = False
            pref_keep = np.repeat(keep, pref_counts)
            job_counts = job_counts - np.bincount(
                job_idx[pos], minlength=J
            )
            uids = uids[keep]
            job_idx = job_idx[keep]
            wait = wait[keep]
            cpu = cpu[keep]
            mem = mem[keep]
            pref_counts = pref_counts[keep]
            pref_m = pref_m[pref_keep]
            pref_r = pref_r[pref_keep]
            pref_w = pref_w[pref_keep]
            if (job_counts == 0).any():
                jkeep = job_counts > 0
                remap = (np.cumsum(jkeep) - 1).astype(np.int32)
                job_idx = remap[job_idx]
                jobs = jobs[jkeep]
                job_counts = job_counts[jkeep]
            # canonical job order is first occurrence among pending
            # tasks; removals can promote a later block's job past an
            # earlier one — re-permute to match what a fresh extract
            # would produce
            if len(job_idx):
                _, first = np.unique(job_idx, return_index=True)
                perm = np.argsort(first, kind="stable")
                if not np.array_equal(perm, np.arange(len(perm))):
                    inv = np.empty(len(perm), np.int32)
                    inv[perm] = np.arange(len(perm), dtype=np.int32)
                    job_idx = inv[job_idx]
                    jobs = jobs[perm]
                    job_counts = job_counts[perm]

        if self._added:
            midx = cols.midx
            rack_idx = {r: i for i, r in enumerate(cols.racks)}
            job_lookup = {j: i for i, j in enumerate(jobs.tolist())}
            new_jobs: list[str] = []
            a_job, a_wait, a_cpu, a_mem, a_cnt = [], [], [], [], []
            a_pm, a_pr, a_pw = [], [], []
            for t in self._added.values():
                jid = t.job_id
                ji = job_lookup.get(jid)
                if ji is None:
                    ji = len(job_lookup)
                    job_lookup[jid] = ji
                    new_jobs.append(jid)
                a_job.append(ji)
                a_wait.append(t.wait_rounds)
                a_cpu.append(int(t.cpu_request * 1000))
                a_mem.append(t.memory_request_kb)
                rows = self.builder._task_prefs(t, midx, rack_idx)
                a_cnt.append(len(rows))
                for m, r, w in rows:
                    a_pm.append(m)
                    a_pr.append(r)
                    a_pw.append(w)
            a_job_arr = np.array(a_job, np.int32)
            uids = np.concatenate([
                uids, np.array(list(self._added), dtype=object),
            ])
            job_idx = np.concatenate([job_idx, a_job_arr])
            wait = np.concatenate([wait, np.array(a_wait, np.int32)])
            cpu = np.concatenate([cpu, np.array(a_cpu, np.int64)])
            mem = np.concatenate([mem, np.array(a_mem, np.int64)])
            pref_counts = np.concatenate(
                [pref_counts, np.array(a_cnt, np.int64)]
            )
            pref_m = np.concatenate([pref_m, np.array(a_pm, np.int32)])
            pref_r = np.concatenate([pref_r, np.array(a_pr, np.int32)])
            pref_w = np.concatenate([pref_w, np.array(a_pw, np.int32)])
            if new_jobs:
                jobs = np.concatenate(
                    [jobs, np.array(new_jobs, dtype=object)]
                )
            job_counts = np.bincount(
                a_job_arr, minlength=len(jobs)
            ).astype(np.int64) + np.concatenate([
                job_counts,
                np.zeros(len(jobs) - len(job_counts), np.int64),
            ])

        if self._slot_delta:
            used_slots = used_slots.copy()
            for name, d in self._slot_delta.items():
                i = cols.midx.get(name)
                if i is None:
                    raise _DeltaUnsupported("slot delta on unknown machine")
                used_slots[i] += d
            if (used_slots < 0).any():
                raise _DeltaUnsupported("negative running-slot count")

        # ---- running block (rebalancing mode) -------------------------
        run_uids = cols.run_uids
        run_job = cols.run_job
        run_machine = cols.run_machine
        run_wait = cols.run_wait
        run_cpu = cols.run_cpu
        run_mem = cols.run_mem
        run_pc = cols.run_pref_counts
        run_pm, run_pr, run_pw = (
            cols.run_pref_m, cols.run_pref_r, cols.run_pref_w
        )

        if self._run_moved:
            run_machine = run_machine.copy()
            for uid, name in self._run_moved.items():
                i = cols.midx.get(name)
                if i is None:
                    raise _DeltaUnsupported("move to unknown machine")
                run_machine[self._run_pos[uid]] = i

        if self._run_updated:
            run_cpu = run_cpu.copy()
            run_mem = run_mem.copy()
            for uid, t in self._run_updated.items():
                p = self._run_pos[uid]
                run_cpu[p] = int(t.cpu_request * 1000)
                run_mem[p] = t.memory_request_kb

        if self._run_removed:
            pos = np.fromiter(
                (self._run_pos[u] for u in self._run_removed),
                np.int64, len(self._run_removed),
            )
            keep = np.ones(len(run_uids), bool)
            keep[pos] = False
            pkeep = np.repeat(keep, run_pc)
            run_uids = run_uids[keep]
            run_job = run_job[keep]
            run_machine = run_machine[keep]
            run_wait = run_wait[keep]
            run_cpu = run_cpu[keep]
            run_mem = run_mem[keep]
            run_pc = run_pc[keep]
            run_pm = run_pm[pkeep]
            run_pr = run_pr[pkeep]
            run_pw = run_pw[pkeep]

        if self._run_added:
            midx = cols.midx
            rack_idx = {r: i for i, r in enumerate(cols.racks)}
            add = sorted(self._run_added.values(), key=lambda t: t.uid)
            if any(t.machine not in midx for t in add):
                raise _DeltaUnsupported("running add on unknown machine")
            per = [
                self.builder._task_prefs(t, midx, rack_idx) for t in add
            ]
            trip = [row for rows in per for row in rows]
            a_pc = np.array([len(rows) for rows in per], np.int64)
            # merge-sort the sorted additions into the uid-sorted block
            all_uids = np.concatenate([
                run_uids, np.array([t.uid for t in add], dtype=object),
            ])
            order = np.argsort(all_uids, kind="stable")
            counts_all = np.concatenate([run_pc, a_pc])
            new_counts = counts_all[order]
            tot = int(counts_all.sum())
            pm_all = np.concatenate(
                [run_pm, np.array([x[0] for x in trip], np.int32)]
            )
            pr_all = np.concatenate(
                [run_pr, np.array([x[1] for x in trip], np.int32)]
            )
            pw_all = np.concatenate(
                [run_pw, np.array([x[2] for x in trip], np.int32)]
            )
            if tot:
                starts = np.zeros(len(counts_all), np.int64)
                starts[1:] = np.cumsum(counts_all)[:-1]
                new_starts = np.zeros(len(new_counts), np.int64)
                new_starts[1:] = np.cumsum(new_counts)[:-1]
                gather = np.repeat(
                    starts[order] - new_starts, new_counts
                ) + np.arange(tot)
                pm_all = pm_all[gather]
                pr_all = pr_all[gather]
                pw_all = pw_all[gather]
            run_uids = all_uids[order]
            run_job = np.concatenate([
                run_job, np.array([t.job_id for t in add], dtype=object),
            ])[order]
            run_machine = np.concatenate([
                run_machine,
                np.array([midx[t.machine] for t in add], np.int32),
            ])[order]
            run_wait = np.concatenate([
                run_wait,
                np.array([t.wait_rounds for t in add], np.int32),
            ])[order]
            run_cpu = np.concatenate([
                run_cpu,
                np.array(
                    [int(t.cpu_request * 1000) for t in add], np.int64
                ),
            ])[order]
            run_mem = np.concatenate([
                run_mem,
                np.array(
                    [t.memory_request_kb for t in add], np.int64
                ),
            ])[order]
            run_pc = new_counts
            run_pm, run_pr, run_pw = pm_all, pr_all, pw_all

        self._cols = dataclasses.replace(
            cols, uids=uids, jobs=jobs, job_idx=job_idx,
            job_counts=job_counts, wait=wait, pref_counts=pref_counts,
            pref_m=pref_m, pref_r=pref_r, pref_w=pref_w,
            cpu_milli=cpu, mem_kb=mem, used_slots=used_slots,
            run_uids=run_uids, run_job=run_job, run_machine=run_machine,
            run_wait=run_wait, run_cpu=run_cpu, run_mem=run_mem,
            run_pref_counts=run_pc, run_pref_m=run_pm,
            run_pref_r=run_pr, run_pref_w=run_pw,
        )
        if self._removed:
            self._uid_pos = {
                u: i for i, u in enumerate(uids.tolist())
            }
        elif self._added:
            base = len(self._uid_pos)
            for k, uid in enumerate(self._added):
                self._uid_pos[uid] = base + k
        if self._run_removed or self._run_added:
            self._run_pos = {
                u: i for i, u in enumerate(run_uids.tolist())
            }
        self._added.clear()
        self._removed.clear()
        self._updated.clear()
        self._aged.clear()
        self._slot_delta.clear()
        self._run_added.clear()
        self._run_removed.clear()
        self._run_moved.clear()
        self._run_updated.clear()
