"""Host-side flow-graph builder: ClusterState -> FlowNetwork + metadata.

Reproduces the Firmament flow-network taxonomy that the reference drives
through ``FlowScheduler`` (reference src/firmament/scheduler_bridge.cc:
37-42,61-127): task nodes with unit supply, one unscheduled aggregator per
job, a cluster aggregator, optional rack aggregators, machine nodes (the
reference registers one RESOURCE_PU per k8s node under a coordinator root,
scheduler_bridge.cc:94-127), and a sink absorbing all flow. Costs are NOT
assigned here — the builder emits per-arc metadata (kind + endpoint
indices) and a cost model (poseidon_tpu/models/) computes the int32 cost
vector on device, so cost recompute per round is a pure vectorized op.

Node order (deterministic): [sink, cluster_agg, racks..., machines...,
unsched_aggs..., tasks...].
"""

from __future__ import annotations

import dataclasses
from enum import IntEnum

import numpy as np

from poseidon_tpu.cluster import ClusterState, TaskPhase
from poseidon_tpu.graph.network import FlowNetwork


class NodeRole(IntEnum):
    SINK = 0
    CLUSTER_AGG = 1
    RACK = 2
    MACHINE = 3
    UNSCHED = 4
    TASK = 5


class ArcKind(IntEnum):
    TASK_TO_UNSCHED = 0    # always present: leaving a task unscheduled
    TASK_TO_CLUSTER = 1    # wildcard arc through the cluster aggregator
    TASK_TO_MACHINE = 2    # preference arc (data locality)
    TASK_TO_RACK = 3       # preference arc to a rack aggregator
    CLUSTER_TO_MACHINE = 4
    RACK_TO_MACHINE = 5
    MACHINE_TO_SINK = 6
    UNSCHED_TO_SINK = 7


@dataclasses.dataclass(frozen=True)
class GraphMeta:
    """Host-side metadata parallel to the padded arc/node tables.

    Arrays are over REAL arcs/nodes (unpadded); index -1 means
    not-applicable. This is what cost models and the delta extractor
    consume.
    """

    node_role: np.ndarray     # int8[n_nodes]
    arc_kind: np.ndarray      # int8[n_arcs]
    arc_task: np.ndarray      # int32[n_arcs]  task index or -1
    arc_machine: np.ndarray   # int32[n_arcs]  machine index or -1
    arc_rack: np.ndarray      # int32[n_arcs]  rack index or -1
    arc_weight: np.ndarray    # int32[n_arcs]  data-locality weight (pref
                              # arcs; 0 elsewhere) — Quincy's input
    task_wait: np.ndarray     # int32[n_tasks] rounds each task has waited
    task_node: np.ndarray     # int32[n_tasks] node id of each task
    machine_node: np.ndarray  # int32[n_machines]
    node_machine: np.ndarray  # int32[n_nodes] machine index or -1
    task_uids: list[str]
    machine_names: list[str]
    rack_names: list[str]
    job_ids: list[str]        # per unsched-aggregator job id
    n_nodes: int
    n_arcs: int


class FlowGraphBuilder:
    """Builds the MCMF instance for one scheduling round.

    ``pref_arcs`` controls whether task data-preference arcs (Quincy-style)
    are emitted; the trivial cost model routes everything through the
    cluster aggregator like Firmament's TrivialCostModel does.
    """

    def __init__(self, *, pref_arcs: bool = True, rack_aggs: bool = True):
        self.pref_arcs = pref_arcs
        self.rack_aggs = rack_aggs

    def build(self, cluster: ClusterState) -> tuple[FlowNetwork, GraphMeta]:
        machines = cluster.machines
        tasks = cluster.pending()
        racks = cluster.racks() if self.rack_aggs else []
        rack_idx = {r: i for i, r in enumerate(racks)}
        midx = cluster.machine_index()

        jobs: list[str] = []
        job_idx: dict[str, int] = {}
        for t in tasks:
            if t.job_id not in job_idx:
                job_idx[t.job_id] = len(jobs)
                jobs.append(t.job_id)

        M, T, R, J = len(machines), len(tasks), len(racks), len(jobs)
        # node layout
        SINK = 0
        CLUSTER = 1
        rack_base = 2
        machine_base = rack_base + R
        unsched_base = machine_base + M
        task_base = unsched_base + J
        n_nodes = task_base + T

        node_role = np.empty(n_nodes, dtype=np.int8)
        node_role[SINK] = NodeRole.SINK
        node_role[CLUSTER] = NodeRole.CLUSTER_AGG
        node_role[rack_base:machine_base] = NodeRole.RACK
        node_role[machine_base:unsched_base] = NodeRole.MACHINE
        node_role[unsched_base:task_base] = NodeRole.UNSCHED
        node_role[task_base:] = NodeRole.TASK

        node_machine = np.full(n_nodes, -1, dtype=np.int32)
        for i in range(M):
            node_machine[machine_base + i] = i

        src: list[int] = []
        dst: list[int] = []
        cap: list[int] = []
        kind: list[int] = []
        a_task: list[int] = []
        a_machine: list[int] = []
        a_rack: list[int] = []

        a_weight: list[int] = []

        def arc(s: int, d: int, c: int, k: ArcKind,
                ti: int = -1, mi: int = -1, ri: int = -1, wt: int = 0) -> None:
            src.append(s)
            dst.append(d)
            cap.append(c)
            kind.append(int(k))
            a_task.append(ti)
            a_machine.append(mi)
            a_rack.append(ri)
            a_weight.append(wt)

        job_task_count = np.zeros(J, dtype=np.int64)
        for ti, t in enumerate(tasks):
            job_task_count[job_idx[t.job_id]] += 1

        # Slots already consumed by RUNNING tasks: the reference tracks
        # running tasks against --max_tasks_per_pu inside Firmament; we
        # discount machine capacity here so re-offered slots are real.
        used_slots = np.zeros(M, dtype=np.int64)
        for t in cluster.tasks:
            if t.phase == TaskPhase.RUNNING and t.machine in midx:
                used_slots[midx[t.machine]] += 1

        # task arcs
        for ti, t in enumerate(tasks):
            tnode = task_base + ti
            ji = job_idx[t.job_id]
            arc(tnode, unsched_base + ji, 1, ArcKind.TASK_TO_UNSCHED, ti=ti)
            arc(tnode, CLUSTER, 1, ArcKind.TASK_TO_CLUSTER, ti=ti)
            if self.pref_arcs:
                for name, weight in t.data_prefs.items():
                    if name in midx:
                        arc(tnode, machine_base + midx[name], 1,
                            ArcKind.TASK_TO_MACHINE, ti=ti, mi=midx[name],
                            wt=int(weight))
                    elif name in rack_idx:
                        arc(tnode, rack_base + rack_idx[name], 1,
                            ArcKind.TASK_TO_RACK, ti=ti, ri=rack_idx[name],
                            wt=int(weight))

        # aggregator -> machine arcs
        for mi, m in enumerate(machines):
            slots = max(int(m.max_tasks) - int(used_slots[mi]), 0)
            mnode = machine_base + mi
            arc(CLUSTER, mnode, slots, ArcKind.CLUSTER_TO_MACHINE, mi=mi)
            if m.rack and m.rack in rack_idx:
                arc(rack_base + rack_idx[m.rack], mnode, slots,
                    ArcKind.RACK_TO_MACHINE, mi=mi, ri=rack_idx[m.rack])
            arc(mnode, SINK, slots, ArcKind.MACHINE_TO_SINK, mi=mi)

        # unscheduled aggregators drain to sink
        for ji in range(J):
            arc(unsched_base + ji, SINK, int(job_task_count[ji]),
                ArcKind.UNSCHED_TO_SINK)

        supply = np.zeros(n_nodes, dtype=np.int64)
        supply[task_base:] = 1
        supply[SINK] = -T

        n_arcs = len(src)
        net = FlowNetwork.from_arrays(
            np.array(src, dtype=np.int32),
            np.array(dst, dtype=np.int32),
            np.array(cap, dtype=np.int32),
            np.zeros(n_arcs, dtype=np.int32),  # costs come from the model
            supply,
        )
        meta = GraphMeta(
            node_role=node_role,
            arc_kind=np.array(kind, dtype=np.int8),
            arc_task=np.array(a_task, dtype=np.int32),
            arc_machine=np.array(a_machine, dtype=np.int32),
            arc_rack=np.array(a_rack, dtype=np.int32),
            arc_weight=np.array(a_weight, dtype=np.int32),
            task_wait=np.array([t.wait_rounds for t in tasks],
                               dtype=np.int32),
            task_node=np.arange(task_base, task_base + T, dtype=np.int32),
            machine_node=np.arange(machine_base, machine_base + M,
                                   dtype=np.int32),
            node_machine=node_machine,
            task_uids=[t.uid for t in tasks],
            machine_names=[m.name for m in machines],
            rack_names=racks,
            job_ids=jobs,
            n_nodes=n_nodes,
            n_arcs=n_arcs,
        )
        return net, meta
