"""Host-side flow-graph builder: ClusterState -> FlowNetwork + metadata.

Reproduces the Firmament flow-network taxonomy that the reference drives
through ``FlowScheduler`` (reference src/firmament/scheduler_bridge.cc:
37-42,61-127): task nodes with unit supply, one unscheduled aggregator per
job, a cluster aggregator, optional rack aggregators, machine nodes (the
reference registers one RESOURCE_PU per k8s node under a coordinator root,
scheduler_bridge.cc:94-127), and a sink absorbing all flow. Costs are NOT
assigned here — the builder emits per-arc metadata (kind + endpoint
indices) and a cost model (poseidon_tpu/models/) computes the int32 cost
vector on device, so cost recompute per round is a pure vectorized op.

Node order (deterministic): [sink, cluster_agg, racks..., machines...,
unsched_aggs..., tasks...].
"""

from __future__ import annotations

import dataclasses
from enum import IntEnum

import numpy as np

from poseidon_tpu.cluster import ClusterState, TaskPhase
from poseidon_tpu.graph.network import FlowNetwork


class NodeRole(IntEnum):
    SINK = 0
    CLUSTER_AGG = 1
    RACK = 2
    MACHINE = 3
    UNSCHED = 4
    TASK = 5


class ArcKind(IntEnum):
    TASK_TO_UNSCHED = 0    # always present: leaving a task unscheduled
    TASK_TO_CLUSTER = 1    # wildcard arc through the cluster aggregator
    TASK_TO_MACHINE = 2    # preference arc (data locality)
    TASK_TO_RACK = 3       # preference arc to a rack aggregator
    CLUSTER_TO_MACHINE = 4
    RACK_TO_MACHINE = 5
    MACHINE_TO_SINK = 6
    UNSCHED_TO_SINK = 7


@dataclasses.dataclass(frozen=True)
class GraphMeta:
    """Host-side metadata parallel to the padded arc/node tables.

    Arrays are over REAL arcs/nodes (unpadded); index -1 means
    not-applicable. This is what cost models and the delta extractor
    consume.
    """

    node_role: np.ndarray     # int8[n_nodes]
    arc_kind: np.ndarray      # int8[n_arcs]
    arc_task: np.ndarray      # int32[n_arcs]  task index or -1
    arc_machine: np.ndarray   # int32[n_arcs]  machine index or -1
    arc_rack: np.ndarray      # int32[n_arcs]  rack index or -1
    arc_weight: np.ndarray    # int32[n_arcs]  data-locality weight (pref
                              # arcs; 0 elsewhere) — Quincy's input
    task_wait: np.ndarray     # int32[n_tasks] rounds each task has waited
    task_node: np.ndarray     # int32[n_tasks] node id of each task
    machine_node: np.ndarray  # int32[n_machines]
    node_machine: np.ndarray  # int32[n_nodes] machine index or -1
    task_uids: list[str]
    machine_names: list[str]
    rack_names: list[str]
    job_ids: list[str]        # per unsched-aggregator job id
    n_nodes: int
    n_arcs: int


class FlowGraphBuilder:
    """Builds the MCMF instance for one scheduling round.

    ``pref_arcs`` controls whether task data-preference arcs (Quincy-style)
    are emitted; the trivial cost model routes everything through the
    cluster aggregator like Firmament's TrivialCostModel does.
    """

    def __init__(self, *, pref_arcs: bool = True, rack_aggs: bool = True):
        self.pref_arcs = pref_arcs
        self.rack_aggs = rack_aggs

    def build(self, cluster: ClusterState) -> tuple[FlowNetwork, GraphMeta]:
        """Build and upload the padded device FlowNetwork + metadata."""
        arrays, meta = self.build_arrays(cluster)
        net = FlowNetwork.from_arrays(
            arrays["src"], arrays["dst"], arrays["cap"],
            np.zeros(meta.n_arcs, dtype=np.int32),  # costs: the model's job
            arrays["supply"],
        )
        return net, meta

    def build_arrays(
        self, cluster: ClusterState
    ) -> tuple[dict[str, np.ndarray], GraphMeta]:
        """Build the graph as HOST arrays only (no device upload).

        The device-resident round (ops/resident.py) consumes these
        directly: topology index maps are derived host-side and the only
        per-round device traffic is one batched upload of pricing inputs
        — the builder must not force its own src/dst/cap transfer.
        """
        machines = cluster.machines
        tasks = cluster.pending()
        racks = cluster.racks() if self.rack_aggs else []
        rack_idx = {r: i for i, r in enumerate(racks)}
        midx = cluster.machine_index()

        jobs: list[str] = []
        job_idx: dict[str, int] = {}
        for t in tasks:
            if t.job_id not in job_idx:
                job_idx[t.job_id] = len(jobs)
                jobs.append(t.job_id)

        M, T, R, J = len(machines), len(tasks), len(racks), len(jobs)
        # node layout
        SINK = 0
        CLUSTER = 1
        rack_base = 2
        machine_base = rack_base + R
        unsched_base = machine_base + M
        task_base = unsched_base + J
        n_nodes = task_base + T

        node_role = np.empty(n_nodes, dtype=np.int8)
        node_role[SINK] = NodeRole.SINK
        node_role[CLUSTER] = NodeRole.CLUSTER_AGG
        node_role[rack_base:machine_base] = NodeRole.RACK
        node_role[machine_base:unsched_base] = NodeRole.MACHINE
        node_role[unsched_base:task_base] = NodeRole.UNSCHED
        node_role[task_base:] = NodeRole.TASK

        node_machine = np.full(n_nodes, -1, dtype=np.int32)
        node_machine[machine_base:unsched_base] = np.arange(
            M, dtype=np.int32
        )

        # Everything below is vectorized per arc FAMILY (a per-arc
        # Python append loop costs ~300 ms at the 10k-pod flagship and
        # runs every scheduling round). Family order:
        # [task->unsched, task->cluster, prefs..., cluster->machine,
        #  rack->machine, machine->sink, unsched->sink]; nothing
        # downstream depends on arc order, only on kind labels.
        job_of = np.array(
            [job_idx[t.job_id] for t in tasks], dtype=np.int32
        )
        job_task_count = np.bincount(
            job_of, minlength=J
        ).astype(np.int64) if T else np.zeros(J, np.int64)

        # Slots already consumed by RUNNING tasks: the reference tracks
        # running tasks against --max_tasks_per_pu inside Firmament; we
        # discount machine capacity here so re-offered slots are real.
        used_slots = np.zeros(M, dtype=np.int64)
        running = [
            midx[t.machine] for t in cluster.tasks
            if t.phase == TaskPhase.RUNNING and t.machine in midx
        ]
        if running:
            np.add.at(used_slots, running, 1)

        t_ids = np.arange(T, dtype=np.int32)
        t_nodes = task_base + t_ids

        # ragged preference triples, one pass over the (small) dicts
        if self.pref_arcs:
            trip = [
                (ti, midx.get(name, -1), rack_idx.get(name, -1),
                 int(weight))
                for ti, t in enumerate(tasks)
                for name, weight in t.data_prefs.items()
                if name in midx or name in rack_idx
            ]
        else:
            trip = []
        p_t = np.array([x[0] for x in trip], dtype=np.int32)
        p_m = np.array([x[1] for x in trip], dtype=np.int32)
        p_r = np.array([x[2] for x in trip], dtype=np.int32)
        p_w = np.array([x[3] for x in trip], dtype=np.int32)
        is_mp = p_m >= 0

        m_ids = np.arange(M, dtype=np.int32)
        m_nodes = machine_base + m_ids
        slots = np.maximum(
            np.array([int(m.max_tasks) for m in machines], np.int64)
            - used_slots, 0,
        ).astype(np.int32)
        m_rack = np.array(
            [rack_idx.get(m.rack, -1) if m.rack else -1 for m in machines],
            dtype=np.int32,
        )
        has_rack = m_rack >= 0

        def fam(n, s, d, c, k, ti=None, mi=None, ri=None, wt=None):
            neg1 = np.full(n, -1, np.int32)
            return (
                np.broadcast_to(np.asarray(s, np.int32), (n,)),
                np.broadcast_to(np.asarray(d, np.int32), (n,)),
                np.broadcast_to(np.asarray(c, np.int32), (n,)),
                np.full(n, int(k), np.int8),
                neg1 if ti is None else np.asarray(ti, np.int32),
                neg1 if mi is None else np.asarray(mi, np.int32),
                neg1 if ri is None else np.asarray(ri, np.int32),
                np.zeros(n, np.int32) if wt is None
                else np.asarray(wt, np.int32),
            )

        families = [
            fam(T, t_nodes, unsched_base + job_of, 1,
                ArcKind.TASK_TO_UNSCHED, ti=t_ids),
            fam(T, t_nodes, CLUSTER, 1, ArcKind.TASK_TO_CLUSTER,
                ti=t_ids),
            fam(int(is_mp.sum()), task_base + p_t[is_mp],
                machine_base + p_m[is_mp], 1, ArcKind.TASK_TO_MACHINE,
                ti=p_t[is_mp], mi=p_m[is_mp], wt=p_w[is_mp]),
            fam(int((~is_mp).sum()), task_base + p_t[~is_mp],
                rack_base + p_r[~is_mp], 1, ArcKind.TASK_TO_RACK,
                ti=p_t[~is_mp], ri=p_r[~is_mp], wt=p_w[~is_mp]),
            fam(M, CLUSTER, m_nodes, slots, ArcKind.CLUSTER_TO_MACHINE,
                mi=m_ids),
            fam(int(has_rack.sum()), rack_base + m_rack[has_rack],
                m_nodes[has_rack], slots[has_rack],
                ArcKind.RACK_TO_MACHINE, mi=m_ids[has_rack],
                ri=m_rack[has_rack]),
            fam(M, m_nodes, SINK, slots, ArcKind.MACHINE_TO_SINK,
                mi=m_ids),
            fam(J, unsched_base + np.arange(J, dtype=np.int32), SINK,
                job_task_count.astype(np.int32),
                ArcKind.UNSCHED_TO_SINK),
        ]
        src, dst, cap, kind, a_task, a_machine, a_rack, a_weight = (
            np.concatenate(cols) for cols in zip(*families)
        )

        supply = np.zeros(n_nodes, dtype=np.int64)
        supply[task_base:] = 1
        supply[SINK] = -T

        n_arcs = len(src)
        arrays = {"src": src, "dst": dst, "cap": cap, "supply": supply}
        meta = GraphMeta(
            node_role=node_role,
            arc_kind=kind,
            arc_task=a_task,
            arc_machine=a_machine,
            arc_rack=a_rack,
            arc_weight=a_weight,
            task_wait=np.array([t.wait_rounds for t in tasks],
                               dtype=np.int32),
            task_node=np.arange(task_base, task_base + T, dtype=np.int32),
            machine_node=np.arange(machine_base, machine_base + M,
                                   dtype=np.int32),
            node_machine=node_machine,
            task_uids=[t.uid for t in tasks],
            machine_names=[m.name for m in machines],
            rack_names=racks,
            job_ids=jobs,
            n_nodes=n_nodes,
            n_arcs=n_arcs,
        )
        return arrays, meta
