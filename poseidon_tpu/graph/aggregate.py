"""Equivalence-class aggregation: shrink the machine axis before densify.

The dense auction's HBM footprint is the [T, M] cost table, so the scale
ceiling is the MACHINE axis: a 512k-task x 64k-machine round is ~131 GB
of int32 and the budget guard (ops/dense_auction.py::check_table_budget)
degrades it to the CPU oracle — at exactly the scale where the TPU
should win most. The reference never builds all-pairs arcs either:
Firmament's cost models route tasks through machine-class *equivalence
aggregators* (PAPER.md §7.1 taxonomy; CoCo/Whare-Map), the same
arc-compression trick that made Quincy's flow formulation tractable at
cluster scale. This module is that trick for the dense lane.

Machines are partitioned into **equivalence classes**: two machines
share a class when every channel cost any task could pay at them is
identical — the generic route (cluster->m + m->sink), the rack route
(rack(m)->m + m->sink) and the rack id itself. Members of a class are
then interchangeable goods, so the dense table only needs ONE column
per class, with capacity = the summed member slots, and the aggregated
optimum equals the all-pairs optimum *exactly* (any class assignment
expands to a member assignment of identical cost, and vice versa; the
differential fuzz in tests/test_aggregate.py proves it instance by
instance). Machines named by a task's machine-preference arc (including
rebalancing continuation arcs) are **pinned** into singleton classes —
a preference prices one specific machine, so that machine must stay
individually addressable for the class-level pref hit to stay exact.

Two plan builders, one per lane:

- ``plan_from_costs`` keys the signature on the PRICED arc table
  (d, g, ra, rack) — exact for any cost model, used where host costs
  exist (the differential tests, host tooling);
- ``plan_from_signatures`` keys on the cost model's per-machine INPUTS
  (rack, load, mem-free, used slots — the capacity bucket / label /
  knowledge-base utilization band of the Firmament taxonomy), so the
  production resident round (ops/resident.py) can plan BEFORE pricing
  without a host sync. Equal inputs imply equal prices for every
  registry model that prices machines by their signature (all of them
  except ``random``, which hashes the machine index and is rejected by
  the resident lane's guard).

``expand_assignment`` maps the winning class assignment back to real
machines, keeping every task already running on a member of its
assigned class in place (so rebalancing deltas reflect real moves, not
expansion noise), then filling remaining seats in canonical machine
order. ``prune_topology_prefs`` is the companion top-k preference
pruning: arcs grow O(tasks * k) instead of O(tasks * max_prefs), exact
whenever k covers every task's prefs, a stated approximation below
that; continuation arcs are never pruned (dropping one would force a
spurious migration).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from poseidon_tpu.ops.transport import TransportTopology


@dataclasses.dataclass(frozen=True)
class AggregatePlan:
    """A machine -> equivalence-class partition (host-side, O(M) ints).

    Columns are numbered by first member in machine order, so the plan
    is deterministic for a given signature table. ``rep_machine`` names
    the member whose arcs price the whole column (members are
    cost-identical by construction, so any member works; the first is
    canonical). Pinned columns (preference targets) are singletons.
    """

    col_of_machine: np.ndarray  # int32[M] column of each machine
    rep_machine: np.ndarray     # int32[C] representative member
    col_slots: np.ndarray       # int32[C] summed member slot capacity
    n_machines: int
    n_pinned: int               # singleton columns forced by pref arcs

    @property
    def n_cols(self) -> int:
        return len(self.rep_machine)


def _pinned_mask(topo: TransportTopology) -> np.ndarray:
    """Machines named by any task's machine-preference arc."""
    pin = np.zeros(topo.n_machines, bool)
    pm = topo.pref_machine
    hit = pm[pm >= 0]
    if len(hit):
        pin[hit] = True
    return pin


def _plan_from_keys(
    key: np.ndarray, slots: np.ndarray, n_pinned: int
) -> AggregatePlan:
    """Group machines by identical key rows; column order follows the
    first member's machine index (deterministic, machine-order stable).
    """
    M = len(slots)
    _, inverse = np.unique(key, axis=0, return_inverse=True)
    inverse = inverse.reshape(M)
    C = int(inverse.max(initial=-1)) + 1
    rep = np.full(C, M, np.int64)
    np.minimum.at(rep, inverse, np.arange(M, dtype=np.int64))
    order = np.argsort(rep, kind="stable")
    renum = np.empty(C, np.int64)
    renum[order] = np.arange(C, dtype=np.int64)
    col = renum[inverse]
    col_slots = np.zeros(C, np.int64)
    np.add.at(col_slots, col, slots.astype(np.int64))
    return AggregatePlan(
        col_of_machine=col.astype(np.int32),
        rep_machine=rep[order].astype(np.int32),
        col_slots=np.minimum(col_slots, np.int64(2**31 - 1)).astype(
            np.int32
        ),
        n_machines=M,
        n_pinned=n_pinned,
    )


def plan_from_costs(
    topo: TransportTopology, cost: np.ndarray
) -> AggregatePlan:
    """Partition machines by their PRICED signature (exact for any
    model): (pinned, cluster->m cost, m->sink cost, rack->m cost,
    rack id). ``cost`` is the host int cost vector over the real arcs.
    """
    M = topo.n_machines
    cost = cost.astype(np.int64, copy=False)
    g = cost[topo.arc_m2s]
    c2m = cost[topo.arc_c2m]
    r2m = np.where(
        topo.arc_r2m >= 0, cost[np.maximum(topo.arc_r2m, 0)],
        np.int64(-1),
    )
    pin = _pinned_mask(topo)
    key = np.column_stack([
        np.where(pin, np.arange(M, dtype=np.int64) + 1, 0),
        c2m, g, r2m, topo.rack_of.astype(np.int64),
    ])
    return _plan_from_keys(key, topo.slots, int(pin.sum()))


def _float_bits(arr, n: int) -> np.ndarray:
    """Exact-equality int64 key for a per-machine float column (None =
    the build_cost_inputs_host default: an unsampled cluster)."""
    if arr is None:
        return np.zeros(n, np.int64)
    a = arr.astype(np.float32, copy=False)
    return np.ascontiguousarray(a).view(np.int32).astype(np.int64)


def plan_from_signatures(
    topo: TransportTopology,
    *,
    machine_load: np.ndarray | None = None,
    machine_mem_free: np.ndarray | None = None,
    machine_used_slots: np.ndarray | None = None,
) -> AggregatePlan:
    """Partition machines by their COST-MODEL-INPUT signature, before
    any pricing happens: (pinned, rack id, load band, free-mem band,
    used slots). Exact whenever the model prices a machine purely from
    these inputs — true for every registry model except ``random``
    (which hashes the machine index; the resident lane rejects it).
    Float bands use exact bit equality: identical knowledge-base
    aggregates, identical class. The arguments mirror
    ``build_cost_inputs_host``'s machine-side kwargs (None = the same
    unsampled defaults).
    """
    M = topo.n_machines
    pin = _pinned_mask(topo)
    used = (
        machine_used_slots.astype(np.int64, copy=False)
        if machine_used_slots is not None else np.zeros(M, np.int64)
    )
    key = np.column_stack([
        np.where(pin, np.arange(M, dtype=np.int64) + 1, 0),
        topo.rack_of.astype(np.int64),
        _float_bits(machine_load, M)[:M],
        _float_bits(machine_mem_free, M)[:M],
        used[:M],
    ])
    return _plan_from_keys(key, topo.slots, int(pin.sum()))


def aggregate_topology(
    topo: TransportTopology, plan: AggregatePlan
) -> TransportTopology:
    """The class-level transport skeleton: machine axis = plan columns.

    Arc indices still point into the ORIGINAL arc table (each column
    prices through its representative member's arcs), so the aggregated
    topology composes with ``instance_from_topology`` and the resident
    chain's on-device cost gathers unchanged. Task-side and job-side
    structure is untouched; machine preferences remap to their target's
    (pinned, singleton) column.
    """
    rep = plan.rep_machine
    pm = topo.pref_machine
    col_pm = np.where(
        pm >= 0, plan.col_of_machine[np.maximum(pm, 0)], -1
    ).astype(np.int32)
    return TransportTopology(
        job_of=topo.job_of,
        arc_unsched=topo.arc_unsched,
        arc_cluster=topo.arc_cluster,
        arc_u2s=topo.arc_u2s,
        arc_pref=topo.arc_pref,
        pref_machine=col_pm,
        pref_rack=topo.pref_rack,
        arc_c2m=topo.arc_c2m[rep],
        arc_r2m=topo.arc_r2m[rep],
        arc_m2s=topo.arc_m2s[rep],
        rack_of=topo.rack_of[rep],
        slots=plan.col_slots,
        arc_job_sink=topo.arc_job_sink,
        job_sink_cap=topo.job_sink_cap,
        n_racks=topo.n_racks,
    )


def prune_topology_prefs(
    topo: TransportTopology,
    arc_weight: np.ndarray,
    arc_discount: np.ndarray,
    k: int,
) -> TransportTopology:
    """Keep each task's k heaviest preference rows (Quincy's locality
    weight = how much input data the pref makes local, so the heaviest
    prefs are the ones the optimum plausibly uses). Identity when k
    already covers ``max_prefs``; a bounded approximation below that
    (the dropped prefs' tasks still route via the generic channel).
    Rebalancing continuation arcs (``arc_discount > 0``) are never
    pruned — dropping one would turn "stay put" into a forced
    migration/preemption.
    """
    P = topo.max_prefs
    if k <= 0 or P <= k:
        return topo
    ap = topo.arc_pref
    w = np.where(
        ap >= 0,
        arc_weight[np.maximum(ap, 0)].astype(np.int64),
        np.int64(-1),
    )
    protected = (ap >= 0) & (arc_discount[np.maximum(ap, 0)] > 0)
    eff = np.where(protected, np.int64(2**62), w)
    order = np.argsort(-eff, axis=1, kind="stable")[:, :k]
    return dataclasses.replace(
        topo,
        arc_pref=np.take_along_axis(ap, order, axis=1),
        pref_machine=np.take_along_axis(topo.pref_machine, order, axis=1),
        pref_rack=np.take_along_axis(topo.pref_rack, order, axis=1),
    )


def expand_assignment(
    plan: AggregatePlan,
    machine_slots: np.ndarray,
    current: np.ndarray,
    assignment: np.ndarray,
) -> np.ndarray:
    """Expand a per-task COLUMN assignment to real machine indices.

    Churn-minimizing and exact: a task whose ``current`` machine is a
    member of its assigned column keeps that machine (capped at the
    member's slots), so NOOP stays NOOP and rebalancing deltas reflect
    genuine moves; remaining tasks fill free member seats in canonical
    (column, machine-index) order. Members of a column are
    cost-identical by construction, so every expansion choice prices
    the same — the objective is preserved exactly. Raises ValueError if
    the assignment overfills a column (a solver-contract violation, not
    a degradable condition).
    """
    T = len(assignment)
    out = np.full(T, -1, np.int32)
    on = assignment >= 0
    if not on.any():
        return out
    col = plan.col_of_machine
    C = plan.n_cols
    M = plan.n_machines
    if (assignment[on] >= C).any():
        raise ValueError("assignment references a column past the plan")
    counts = np.bincount(assignment[on], minlength=C)
    if (counts > plan.col_slots).any():
        bad = int(np.flatnonzero(counts > plan.col_slots)[0])
        raise ValueError(
            f"aggregated assignment overfills column {bad}: "
            f"{int(counts[bad])} tasks > {int(plan.col_slots[bad])} slots"
        )
    slots = machine_slots.astype(np.int64, copy=False)

    # keep pass: tasks already on a member of their assigned column
    keep = np.flatnonzero(on & (current >= 0) & (current < M))
    if len(keep):
        keep = keep[col[current[keep]] == assignment[keep]]
    if len(keep):
        m = current[keep]
        order = np.argsort(m, kind="stable")
        ms = m[order]
        starts = np.searchsorted(ms, np.arange(M, dtype=np.int64))
        rank = np.arange(len(ms), dtype=np.int64) - starts[ms]
        kept = keep[order[rank < slots[ms]]]
        out[kept] = current[kept]

    used = np.bincount(out[out >= 0], minlength=M)
    rem = slots - used.astype(np.int64)

    # fill pass: remaining tasks take free seats in (column, machine)
    # order; feasibility follows from the column-capacity check above
    # (kept tasks occupy seats of their own column, so free seats per
    # column >= remaining tasks per column)
    need = np.flatnonzero(on & (out < 0))
    if len(need):
        m_order = np.argsort(col, kind="stable")
        seat_machine = np.repeat(m_order, rem[m_order])
        seat_col = col[seat_machine]
        col_start = np.searchsorted(
            seat_col, np.arange(C, dtype=np.int64)
        )
        cols_n = assignment[need]
        order = np.argsort(cols_n, kind="stable")
        sc = cols_n[order]
        nstart = np.searchsorted(sc, np.arange(C, dtype=np.int64))
        rank = np.arange(len(sc), dtype=np.int64) - nstart[sc]
        out[need[order]] = seat_machine[
            col_start[sc] + rank
        ].astype(np.int32)
    return out
