"""L0 flow-network data model: structure-of-arrays, padded, device-resident.

The reference keeps its flow network inside Firmament's C++
``FlowGraphManager`` (surface visible at reference
src/firmament/scheduler_bridge.cc:37-42 and deploy/poseidon.cfg:12-19); the
solver then re-serializes it to DIMACS text for a child process. Here the
network *is* the device representation: int32 arc/node tables padded to
power-of-two buckets so jit recompilation is rare as the cluster churns.

Conventions
-----------
* Arcs are directed ``src -> dst`` with integer capacity ``cap >= 0`` and
  integer unit cost ``cost``. Lower bounds are always 0 (the reference's
  DIMACS usage never needs nonzero lower bounds).
* ``supply[v] > 0`` means v is a source of that many flow units, ``< 0`` a
  demand. Supplies sum to 0 over real nodes.
* Padding: arc slots with index >= n_arcs have cap == 0, cost == 0 and
  src == dst == 0, so every vectorized sweep treats them as harmless no-ops.
  Node slots >= n_nodes have supply == 0.
* All solver arithmetic is int32 and exact — optimality is checked against
  the C++ oracle, not approximated. Cost magnitudes must satisfy
  ``max|cost| * n_nodes * ALPHA < 2**31`` (checked in the solvers).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import numpy as np
import jax.numpy as jnp


def pad_bucket(n: int, minimum: int = 16) -> int:
    """Next padding bucket >= max(n, minimum).

    Powers of two up to 1024, then multiples of 1024 (= 8 x 128, so
    every bucket stays (8, 128)-tile aligned for the TPU layout). Pure
    doubling wasted up to ~60% of every [T, M] sweep at the flagship
    scale (10k tasks -> 16384 slots; now 10240); the finer ladder keeps
    the compiled-shape count bounded (O(log n + n / 1024), grow-only,
    SURVEY.md section 5.7) while padding overhead stays under 10%.
    """
    b = minimum
    while b < n and b < 1024:
        b *= 2
    if n <= b:
        return b
    return ((n + 1023) // 1024) * 1024


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class FlowNetwork:
    """A padded min-cost-flow instance as device arrays (a JAX pytree).

    Shapes: arcs padded to E slots, nodes padded to N slots. ``n_nodes`` /
    ``n_arcs`` are traced int32 scalars carrying the real counts, so one
    compiled solver serves every instance within a (N, E) bucket.
    """

    src: jax.Array      # int32[E] arc tail
    dst: jax.Array      # int32[E] arc head
    cap: jax.Array      # int32[E] capacity (0 on padding)
    cost: jax.Array     # int32[E] unit cost (0 on padding)
    supply: jax.Array   # int32[N] node supply (+source / -demand, 0 padding)
    n_nodes: jax.Array  # int32 scalar, real node count
    n_arcs: jax.Array   # int32 scalar, real arc count

    @property
    def num_node_slots(self) -> int:
        return self.supply.shape[-1]

    @property
    def num_arc_slots(self) -> int:
        return self.src.shape[-1]

    @staticmethod
    def from_arrays(
        src: Any,
        dst: Any,
        cap: Any,
        cost: Any,
        supply: Any,
        *,
        node_slots: int | None = None,
        arc_slots: int | None = None,
        validate: bool = True,
    ) -> "FlowNetwork":
        """Build a padded instance from host arrays (any integer dtype)."""
        src = np.asarray(src, dtype=np.int32)
        dst = np.asarray(dst, dtype=np.int32)
        cap = np.asarray(cap, dtype=np.int32)
        cost = np.asarray(cost, dtype=np.int32)
        supply = np.asarray(supply, dtype=np.int32)
        n_arcs = src.shape[0]
        n_nodes = supply.shape[0]
        if validate:
            if not (dst.shape[0] == cap.shape[0] == cost.shape[0] == n_arcs):
                raise ValueError("arc arrays disagree on length")
            if n_arcs and (src.min() < 0 or src.max() >= n_nodes):
                raise ValueError("arc src out of range")
            if n_arcs and (dst.min() < 0 or dst.max() >= n_nodes):
                raise ValueError("arc dst out of range")
            if n_arcs and cap.min() < 0:
                raise ValueError("negative capacity")
            if int(supply.sum()) != 0:
                raise ValueError(f"supplies must sum to 0, got {supply.sum()}")
        N = node_slots or pad_bucket(n_nodes)
        E = arc_slots or pad_bucket(n_arcs)
        if N < n_nodes or E < n_arcs:
            raise ValueError("padding slots smaller than real counts")

        def pad(a: np.ndarray, size: int) -> np.ndarray:
            out = np.zeros(size, dtype=np.int32)
            out[: a.shape[0]] = a
            return out

        return FlowNetwork(
            src=jnp.asarray(pad(src, E)),
            dst=jnp.asarray(pad(dst, E)),
            cap=jnp.asarray(pad(cap, E)),
            cost=jnp.asarray(pad(cost, E)),
            supply=jnp.asarray(pad(supply, N)),
            n_nodes=jnp.int32(n_nodes),
            n_arcs=jnp.int32(n_arcs),
        )

    def with_costs(self, cost: jax.Array) -> "FlowNetwork":
        """Same topology, new arc costs (cost-model recompute path)."""
        return dataclasses.replace(self, cost=cost.astype(jnp.int32))

    # ---- host-side conveniences (not for use inside jit) ----

    def to_host(self) -> dict[str, np.ndarray]:
        na = int(self.n_arcs)
        nn = int(self.n_nodes)
        return {
            "src": np.asarray(self.src)[:na],
            "dst": np.asarray(self.dst)[:na],
            "cap": np.asarray(self.cap)[:na],
            "cost": np.asarray(self.cost)[:na],
            "supply": np.asarray(self.supply)[:nn],
        }


def total_supply(net: FlowNetwork) -> int:
    """Total positive supply (the flow value a feasible solution must route)."""
    s = np.asarray(net.supply)
    return int(s[s > 0].sum())
