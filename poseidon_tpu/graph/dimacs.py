"""DIMACS min-cost-flow text format read/write.

DIMACS is the lingua franca of the reference's solver seam: Firmament
serializes the flow network to its external solver binary (cs2/Flowlessly,
reference deploy/poseidon.cfg:8-10, README.md:21) in this format. We keep
it as the interchange with our C++ CPU oracle (poseidon_tpu/oracle/) and
for golden-instance fixtures.

Format (1-indexed nodes):
    c <comment>
    p min <n_nodes> <n_arcs>
    n <node_id> <supply>          (only nonzero supplies listed)
    a <src> <dst> <low> <cap> <cost>
"""

from __future__ import annotations

import io

import numpy as np

from poseidon_tpu.graph.network import FlowNetwork


def write_dimacs(net: FlowNetwork) -> str:
    h = net.to_host()
    return write_dimacs_host(
        h["src"], h["dst"], h["cap"], h["cost"], h["supply"],
        int(net.n_nodes), int(net.n_arcs),
    )


def write_dimacs_host(
    src, dst, cap, cost, supply, n_nodes: int, n_arcs: int
) -> str:
    """Render a DIMACS min-cost instance from HOST arrays directly.

    The device-free twin of ``write_dimacs``: callers that never built
    a ``FlowNetwork`` (the shadow audit's background thread prices on
    host numpy and solves on the subprocess oracle) render from the
    builder's raw arrays — no jax import, no device traffic.
    """
    out = io.StringIO()
    out.write(f"p min {n_nodes} {n_arcs}\n")
    supply = np.asarray(supply)
    for v in np.flatnonzero(supply):
        out.write(f"n {v + 1} {int(supply[v])}\n")
    for a in range(n_arcs):
        out.write(
            f"a {int(src[a]) + 1} {int(dst[a]) + 1} 0 "
            f"{int(cap[a])} {int(cost[a])}\n"
        )
    return out.getvalue()


def read_dimacs(text: str) -> FlowNetwork:
    n_nodes = n_arcs = -1
    supply: np.ndarray | None = None
    src: list[int] = []
    dst: list[int] = []
    cap: list[int] = []
    cost: list[int] = []
    for raw in text.splitlines():
        line = raw.strip()
        if not line or line.startswith("c"):
            continue
        parts = line.split()
        if parts[0] == "p":
            if parts[1] != "min":
                raise ValueError(f"not a min-cost problem line: {line!r}")
            n_nodes, n_arcs = int(parts[2]), int(parts[3])
            supply = np.zeros(n_nodes, dtype=np.int64)
        elif parts[0] == "n":
            if supply is None:
                raise ValueError("n line before p line")
            v = int(parts[1])
            if not 1 <= v <= n_nodes:
                # without this, node id 0 would alias supply[-1] silently
                raise ValueError(f"node id {v} out of range 1..{n_nodes}")
            supply[v - 1] = int(parts[2])
        elif parts[0] == "a":
            if int(parts[3]) != 0:
                raise ValueError("nonzero lower bounds unsupported")
            src.append(int(parts[1]) - 1)
            dst.append(int(parts[2]) - 1)
            cap.append(int(parts[4]))
            cost.append(int(parts[5]))
    if supply is None:
        raise ValueError("missing p line")
    if len(src) != n_arcs:
        raise ValueError(f"expected {n_arcs} arcs, got {len(src)}")
    return FlowNetwork.from_arrays(src, dst, cap, cost, supply)


def parse_flow_output(text: str, n_arcs: int) -> tuple[int, np.ndarray]:
    """Parse DIMACS solution lines: ``s <cost>`` + ``f <src> <dst> <flow>``.

    Our C++ oracle prints exactly one ``f`` line per input arc, in input
    order (including zero flows), so the k-th ``f`` line is the flow on
    arc k. Returns (total_cost, int64[n_arcs] flows).
    """
    total: int | None = None
    flows = np.zeros(n_arcs, dtype=np.int64)
    k = 0
    for raw in text.splitlines():
        parts = raw.split()
        if not parts:
            continue
        if parts[0] == "s":
            total = int(parts[1])
        elif parts[0] == "f":
            if k >= n_arcs:
                raise ValueError("more f lines than arcs")
            flows[k] = int(parts[3])
            k += 1
    if total is None:
        raise ValueError("no 's' (solution cost) line in solver output")
    if k not in (0, n_arcs):
        raise ValueError(f"expected 0 or {n_arcs} f lines, got {k}")
    return total, flows
