from poseidon_tpu.graph.network import FlowNetwork, pad_bucket
from poseidon_tpu.graph.builder import FlowGraphBuilder, NodeRole, ArcKind

__all__ = ["FlowNetwork", "pad_bucket", "FlowGraphBuilder", "NodeRole", "ArcKind"]
