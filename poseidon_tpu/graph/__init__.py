from poseidon_tpu.graph.network import FlowNetwork, pad_bucket
from poseidon_tpu.graph.builder import FlowGraphBuilder, NodeRole, ArcKind
from poseidon_tpu.graph.deltas import (
    DeltaKind,
    DeltaSet,
    SchedulingDelta,
    extract_deltas,
)

__all__ = [
    "FlowNetwork", "pad_bucket", "FlowGraphBuilder", "NodeRole",
    "ArcKind", "DeltaKind", "DeltaSet", "SchedulingDelta",
    "extract_deltas",
]
