"""Flow decomposition: per-arc flows -> per-task placements.

The solver returns arc flows; tasks routing through aggregators (cluster /
rack) lose their identity inside the aggregate, so the flow must be
decomposed into task->...->machine paths. Firmament does the same
internally before emitting ``SchedulingDelta::PLACE`` records (surface at
reference src/firmament/scheduler_bridge.cc:170-190). Greedy path peeling
is exact here because every task carries exactly one unit of flow.
"""

from __future__ import annotations

import numpy as np

from poseidon_tpu.graph.builder import GraphMeta, NodeRole


def extract_placements(
    flows: np.ndarray, meta: GraphMeta, src: np.ndarray, dst: np.ndarray
) -> dict[str, str | None]:
    """Map each task uid to a machine name, or None if left unscheduled.

    ``flows`` must be non-negative per-arc flows over the REAL arcs (length
    meta.n_arcs); ``src``/``dst`` the real arc endpoints.
    """
    n = meta.n_nodes
    res = np.asarray(flows[: meta.n_arcs]).astype(np.int64).copy()
    src = np.asarray(src[: meta.n_arcs])
    dst = np.asarray(dst[: meta.n_arcs])

    # out-adjacency over arcs with positive flow, rebuilt lazily
    out_arcs: list[list[int]] = [[] for _ in range(n)]
    for a in np.flatnonzero(res > 0):
        out_arcs[src[a]].append(int(a))

    role = meta.node_role
    placements: dict[str, str | None] = {}
    for ti, uid in enumerate(meta.task_uids):
        v = int(meta.task_node[ti])
        path: list[int] = []
        dead = False
        while role[v] not in (NodeRole.MACHINE, NodeRole.UNSCHED, NodeRole.SINK):
            adv = None
            while out_arcs[v]:
                a = out_arcs[v][-1]
                if res[a] > 0:
                    adv = a
                    break
                out_arcs[v].pop()
            if adv is None:
                dead = True
                break
            path.append(adv)
            v = int(dst[adv])
        if dead:
            raise ValueError(
                f"flow decomposition stuck at node {v} for task {uid}; "
                "flows are not a feasible routing of all task supplies"
            )
        for a in path:
            res[a] -= 1
        if role[v] == NodeRole.MACHINE:
            placements[uid] = meta.machine_names[meta.node_machine[v]]
        else:
            placements[uid] = None  # unscheduled (or degenerate direct sink)
    return placements
