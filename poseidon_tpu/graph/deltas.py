"""SchedulingDelta vocabulary: solved assignment -> typed decisions.

Firmament turns each solve into ``SchedulingDelta`` records of four
kinds — PLACE a pending task, MIGRATE a running task to a better
machine, PREEMPT a running task back to unscheduled, or NOOP (keep it
where it is). The reference only ever actuates PLACE (its
``scheduler_bridge.cc:176-190`` loop binds new placements and nothing
else); this module closes the vocabulary: ``extract_deltas`` diffs the
solver's per-task assignment against the current placements recorded in
``GraphMeta.task_current`` and emits typed records, with a per-round
migration budget so one solve cannot churn the whole cluster at once.

Budget semantics: MIGRATE and PREEMPT are both disruptive (each tears a
running pod off its machine), so they share the ``max_migrations``
budget, granted in task order (stable across rounds). Deltas beyond the
budget are returned as ``deferred`` — nothing is actuated for them, the
tasks stay where they are, and the next round's solve re-proposes
whatever still improves the objective, so dropped migrations re-enter
naturally.

Pending tasks the solver left unassigned are not deltas (there is
nothing to do); they are returned as ``unscheduled`` uids so the bridge
can age them.
"""

from __future__ import annotations

import dataclasses
from enum import IntEnum

import numpy as np

from poseidon_tpu.graph.builder import GraphMeta


class DeltaKind(IntEnum):
    PLACE = 0     # pending task -> machine (a new binding)
    MIGRATE = 1   # running task -> different machine (unbind + rebind)
    PREEMPT = 2   # running task -> unscheduled (evict + park, aged)
    NOOP = 3      # running task keeps its machine


# Sentinel for "runner-up margin not computed / no finite alternative"
# (int64-safe; a real margin can be negative when capacity forces a
# worse-than-runner-up choice, so 0/-1 cannot be the sentinel).
MARGIN_UNKNOWN = np.int64(2) ** 62


@dataclasses.dataclass(frozen=True)
class SchedulingDelta:
    """One typed scheduling decision for one task.

    ``cost`` is the decision's exact int64 route cost under the round's
    instance (the solver's per-task objective contribution: the chosen
    machine route for PLACE/MIGRATE/NOOP, the priced unsched route for
    PREEMPT); ``margin`` is runner-up-minus-chosen — how much worse the
    next-best alternative was (negative when capacity forced this task
    off its cheapest machine). Both default to "unknown" when the
    caller has no per-task cost vector (legacy/flow-only backends)."""

    kind: DeltaKind
    task: str            # task uid
    machine: str = ""    # target machine (PLACE/MIGRATE; "" otherwise)
    from_machine: str = ""  # current machine (MIGRATE/PREEMPT/NOOP)
    cost: int | None = None
    margin: int | None = None


@dataclasses.dataclass
class DeltaSet:
    """One round's typed decisions, budget already applied."""

    place: list[SchedulingDelta]
    migrate: list[SchedulingDelta]
    preempt: list[SchedulingDelta]
    noop: list[SchedulingDelta]
    # disruptive deltas dropped by the migration budget (typed as what
    # they would have been); nothing is actuated for these
    deferred: list[SchedulingDelta]
    unscheduled: list[str]   # pending uids the solver left unassigned

    @property
    def counts(self) -> dict[str, int]:
        return {
            "place": len(self.place),
            "migrate": len(self.migrate),
            "preempt": len(self.preempt),
            "noop": len(self.noop),
            "deferred": len(self.deferred),
        }


def extract_deltas(
    meta: GraphMeta,
    assignment: np.ndarray,
    *,
    max_migrations: int = 0,
    task_cost: np.ndarray | None = None,
    task_margin: np.ndarray | None = None,
) -> DeltaSet:
    """Diff a solved assignment against current placements.

    ``assignment`` is the solver's per-task machine index (or -1 =
    unscheduled) over ``meta.task_uids`` order; ``meta.task_current``
    names where each task runs today (-1 = pending). ``max_migrations``
    bounds MIGRATE+PREEMPT per round (0 = unlimited); excess disruptive
    deltas land in ``deferred`` in task order.

    ``task_cost`` / ``task_margin`` (optional, int64 over task order)
    stamp each typed delta with its exact route cost and runner-up
    margin (``ResidentOutcome.task_cost``/``task_margin``); a
    ``MARGIN_UNKNOWN`` margin entry maps to None.
    """
    asg = np.asarray(assignment, np.int64)
    cur = np.asarray(meta.task_current, np.int64)
    if asg.shape != cur.shape:
        raise ValueError(
            f"assignment length {asg.shape} does not match the "
            f"metadata task count {cur.shape}"
        )
    names = meta.machine_names
    uids = meta.task_uids
    is_run = cur >= 0

    def _cost(i) -> int | None:
        return int(task_cost[i]) if task_cost is not None else None

    def _margin(i) -> int | None:
        if task_margin is None:
            return None
        m = int(task_margin[i])
        return None if m == MARGIN_UNKNOWN else m

    place = [
        SchedulingDelta(DeltaKind.PLACE, uids[i], machine=names[asg[i]],
                        cost=_cost(i), margin=_margin(i))
        for i in np.flatnonzero(~is_run & (asg >= 0))
    ]
    unscheduled = [
        uids[i] for i in np.flatnonzero(~is_run & (asg < 0))
    ]
    noop = [
        SchedulingDelta(DeltaKind.NOOP, uids[i],
                        machine=names[cur[i]],
                        from_machine=names[cur[i]],
                        cost=_cost(i), margin=_margin(i))
        for i in np.flatnonzero(is_run & (asg == cur))
    ]

    disruptive: list[SchedulingDelta] = []
    for i in np.flatnonzero(is_run & (asg != cur)):
        if asg[i] >= 0:
            disruptive.append(SchedulingDelta(
                DeltaKind.MIGRATE, uids[i], machine=names[asg[i]],
                from_machine=names[cur[i]],
                cost=_cost(i), margin=_margin(i),
            ))
        else:
            disruptive.append(SchedulingDelta(
                DeltaKind.PREEMPT, uids[i], from_machine=names[cur[i]],
                cost=_cost(i), margin=_margin(i),
            ))
    budget = max_migrations if max_migrations > 0 else len(disruptive)
    granted, deferred = disruptive[:budget], disruptive[budget:]
    return DeltaSet(
        place=place,
        migrate=[d for d in granted if d.kind == DeltaKind.MIGRATE],
        preempt=[d for d in granted if d.kind == DeltaKind.PREEMPT],
        noop=noop,
        deferred=deferred,
        unscheduled=unscheduled,
    )
