"""Scheduler event trace: the TraceGenerator analog.

The reference wires a ``TraceGenerator`` + injected ``WallTime`` into its
scheduler so Firmament can emit Google-cluster-trace-style event logs
(reference src/firmament/scheduler_bridge.{h,cc}:29,31,36,42; SURVEY
§5.1). Here the trace is a first-class JSONL stream: one object per
scheduler event, with an injectable clock so tests are deterministic.

Event types mirror the cluster-trace vocabulary: SUBMIT (pod observed),
SCHEDULE (placement decision), MIGRATE (rebalancing move, ``detail.
from`` names the old machine), PREEMPT (rebalancing park), EVICT (node
loss), FINISH (pod retired), WATCH_RESYNC (the watch subsystem degraded
to a full LIST resync — ``detail.reason`` names why: 410 Gone, decode
error, or staleness), WATCH_RECONNECT (an error-path watch-stream
reconnect, ``detail.resource``/``detail.reason``), FETCH_TIMEOUT
(the pipelined round's background placement fetch missed its
``--max_solver_runtime`` deadline; the round is abandoned loudly) and
DEGRADE (the dense lane fell back to the CPU oracle this round —
``detail.why`` names the guard: memory-envelope, cost-domain, or
uncertified; counted in ``SchedulerStats.degrades_total``). The
express lane (``--express_lane``) adds EXPRESS_PLACE (a pod bound
between round ticks by the on-HBM incremental re-solve),
EXPRESS_CORRECTED (the periodic correction round moved an express
placement — the differential-verify outcome), and EXPRESS_DEGRADE (an
express batch fell back to the round path, ``detail.why`` names the
guard that fired),
plus ROUND records carrying the per-phase timing/stat payload
(``SchedulerStats`` as a dict — including the round-pipeline timers:
``build_mode`` delta/full/legacy, ``dispatch_ms``, ``fetch_wait_ms``,
``overlap_ms``, ``wall_ms``; ``total_ms`` is the host critical path,
excluding the overlap window where the loop worked on other rounds).

Pipelined rounds (bridge ``begin_round``/``finish_round``) emit their
ROUND record at finish time, so a round's SCHEDULE/ROUND events may
interleave with the NEXT round's SUBMIT events in the stream;
``read_trace`` does the ``round_num`` ordering for consumers.
"""

from __future__ import annotations

import collections
import dataclasses
import json
import time
from typing import Callable, IO

# The DECLARED event vocabulary. Consumers key on these names, so an
# emit outside the set is a silent contract break for every downstream
# trace reader: the static pass (analysis/rules.py PTA005) checks every
# ``*.emit("NAME")`` call site against this set, and ``emit`` enforces
# it at runtime. Extending the vocabulary = adding the name here (and
# documenting it in the module docstring above).
EVENT_TYPES = frozenset({
    "SUBMIT",           # pod observed
    "SCHEDULE",         # placement decision
    "MIGRATE",          # rebalancing move
    "PREEMPT",          # rebalancing park
    "EVICT",            # node loss
    "FINISH",           # pod retired
    "ROUND",            # per-round stats payload
    "WATCH_RESYNC",     # watch degraded to a full LIST resync
    "WATCH_RECONNECT",  # error-path watch-stream reconnect
    "FETCH_TIMEOUT",    # pipelined placement fetch missed its deadline
    "DEGRADE",          # dense lane degraded this round to the oracle
    "EXPRESS_PLACE",    # express-lane placement between round ticks
    "EXPRESS_CORRECTED",  # correction round moved an express placement
    "EXPRESS_DEGRADE",  # express batch fell back to the round path
})


@dataclasses.dataclass
class TraceEvent:
    timestamp_us: int
    event: str              # one of EVENT_TYPES
    task: str = ""
    machine: str = ""
    round_num: int = 0
    detail: dict | None = None


class TraceGenerator:
    """Appends one JSON object per line to ``sink`` (file-like)."""

    def __init__(
        self,
        sink: IO[str] | None = None,
        clock_us: Callable[[], int] | None = None,
        buffer_events: int = 10_000,
    ):
        self.sink = sink
        self.clock_us = clock_us or (lambda: int(time.time() * 1e6))
        # with no sink, keep a bounded ring (a daemon running forever
        # must not accumulate events without bound)
        self.events: collections.deque[TraceEvent] = collections.deque(
            maxlen=buffer_events
        )

    def emit(
        self,
        event: str,
        *,
        task: str = "",
        machine: str = "",
        round_num: int = 0,
        detail: dict | None = None,
    ) -> None:
        if event not in EVENT_TYPES:
            raise ValueError(
                f"undeclared trace event {event!r}; the vocabulary is "
                f"trace.EVENT_TYPES (PTA005)"
            )
        ev = TraceEvent(
            timestamp_us=self.clock_us(),
            event=event,
            task=task,
            machine=machine,
            round_num=round_num,
            detail=detail,
        )
        if self.sink is not None:
            self.sink.write(json.dumps(dataclasses.asdict(ev)) + "\n")
        else:
            self.events.append(ev)

    def flush(self) -> None:
        if self.sink is not None:
            self.sink.flush()


def read_trace(path: str):
    """Yield a trace file's events ordered by ``round_num``.

    Pipelined rounds interleave a round's SCHEDULE/ROUND records with
    the next round's SUBMIT records in file order; this reader restores
    round order (stable within a round, so per-round event order is
    file order) so consumers do not have to re-implement the sort the
    module docstring used to prescribe. Blank lines are skipped; a
    malformed line raises ``json.JSONDecodeError`` like any other
    corrupt input.
    """
    with open(path) as fh:
        events = [
            TraceEvent(**json.loads(line))
            for line in fh if line.strip()
        ]
    events.sort(key=lambda e: e.round_num)  # stable: file order within
    yield from events
