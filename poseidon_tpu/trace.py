"""Scheduler event trace: the TraceGenerator analog.

The reference wires a ``TraceGenerator`` + injected ``WallTime`` into its
scheduler so Firmament can emit Google-cluster-trace-style event logs
(reference src/firmament/scheduler_bridge.{h,cc}:29,31,36,42; SURVEY
§5.1). Here the trace is a first-class JSONL stream: one object per
scheduler event, with an injectable clock so tests are deterministic.

Event types mirror the cluster-trace vocabulary: SUBMIT (pod observed),
SCHEDULE (placement decision), MIGRATE (rebalancing move, ``detail.
from`` names the old machine), PREEMPT (rebalancing park), EVICT (node
loss), FINISH (pod retired), WATCH_RESYNC (the watch subsystem degraded
to a full LIST resync — ``detail.reason`` names why: 410 Gone, decode
error, or staleness), WATCH_RECONNECT (an error-path watch-stream
reconnect, ``detail.resource``/``detail.reason``), FETCH_TIMEOUT
(the pipelined round's background placement fetch missed its
``--max_solver_runtime`` deadline; the round is abandoned loudly) and
DEGRADE (the dense lane fell back to the CPU oracle this round —
``detail.why`` names the guard: memory-envelope, cost-domain, or
uncertified; counted in ``SchedulerStats.degrades_total``). The
express lane (``--express_lane``) adds EXPRESS_PLACE (a pod bound
between round ticks by the on-HBM incremental re-solve),
EXPRESS_CORRECTED (the periodic correction round moved an express
placement — the differential-verify outcome), and EXPRESS_DEGRADE (an
express batch fell back to the round path, ``detail.why`` names the
guard that fired). The crash-safety layer (``--checkpoint_dir``,
poseidon_tpu/ha/) adds CHECKPOINT (a warm-state snapshot captured),
RESTORE (the daemon rehydrated from a checkpoint at startup —
``detail.warm`` says whether the solve seed survived) and
JOURNAL_REPLAY (an incomplete journaled actuation replayed
idempotently on restart, ``detail.op``/``detail.outcome``),
The failure-domain layer (ISSUE 15) adds EVICTION_GUARD_HOLD /
EVICTION_GUARD_RELEASE (the mass-eviction guard holding or releasing
an implausible snapshot shrink — release ``detail.outcome`` is
"accepted" true-death or "recovered"), OUTAGE (the apiserver-outage
degradation ladder flipping, ``detail.phase`` begin/end),
OUTBOX_DEAD_LETTER (an outboxed actuation exhausted its retry budget)
and ROUND_DEADLINE_MISS (the overload watchdog: a round's wall span
exceeded ``--round_deadline_ms``),
plus ROUND records carrying the per-phase timing/stat payload
(``SchedulerStats`` as a dict — including the round-pipeline timers:
``build_mode`` delta/full/legacy, ``dispatch_ms``, ``fetch_wait_ms``,
``overlap_ms``, ``wall_ms``; ``total_ms`` is the host critical path,
excluding the overlap window where the loop worked on other rounds)
and SPAN records carrying a structured per-phase span tree for a round
or express batch (``--trace_profile``; the tree schema lives in
``poseidon_tpu/obs/spans.py``, the consumers are the Chrome-trace
exporter and ``python -m poseidon_tpu.trace report``).

**Clock contract.** ``timestamp_us`` is WALL-clock microseconds
(``time.time()`` by default): it exists to correlate events across
hosts and with apiserver/audit logs, and it is NOT safe to difference —
NTP steps/slews make wall-clock intervals lie. Every DURATION in the
stream (the ROUND record's ``*_ms`` timers, SPAN ``dur_ms``/``off_ms``
values, EXPRESS_PLACE ``e2b_ms``) is therefore measured by the
producers on the monotonic clock family (``time.monotonic`` /
``time.perf_counter``) and shipped as an already-computed value.
Consumers: read durations from the payloads, never from timestamp
deltas. An injected ``clock_us`` (tests) replaces only the timestamp
source.

Pipelined rounds (bridge ``begin_round``/``finish_round``) emit their
ROUND record at finish time, so a round's SCHEDULE/ROUND events may
interleave with the NEXT round's SUBMIT events in the stream;
``read_trace`` does the ``round_num`` ordering for consumers.

Command line: ``python -m poseidon_tpu.trace report <file>`` renders
the operator's one-pager (round-latency percentiles by lane/build
mode, express event-to-bind percentiles, degrade/resync/timeout
tallies with reasons, placement-churn summary; ``--json`` for the raw
data model), and ``python -m poseidon_tpu.trace chrome <file>`` writes
a Chrome-trace/Perfetto JSON of the SPAN events.
"""

from __future__ import annotations

import collections
import dataclasses
import json
import logging
import time
from typing import Callable, IO

log = logging.getLogger(__name__)

# The DECLARED event vocabulary. Consumers key on these names, so an
# emit outside the set is a silent contract break for every downstream
# trace reader: the static pass (analysis/rules.py PTA005) checks every
# ``*.emit("NAME")`` call site against this set, and ``emit`` enforces
# it at runtime. Extending the vocabulary = adding the name here (and
# documenting it in the module docstring above).
EVENT_TYPES = frozenset({
    "SUBMIT",           # pod observed
    "SCHEDULE",         # placement decision
    "MIGRATE",          # rebalancing move
    "PREEMPT",          # rebalancing park
    "EVICT",            # node loss
    "FINISH",           # pod retired
    "ROUND",            # per-round stats payload
    "WATCH_RESYNC",     # watch degraded to a full LIST resync
    "WATCH_RECONNECT",  # error-path watch-stream reconnect
    "FETCH_TIMEOUT",    # pipelined placement fetch missed its deadline
    "DEGRADE",          # dense lane degraded this round to the oracle
    "EXPRESS_PLACE",    # express-lane placement between round ticks
    "EXPRESS_CORRECTED",  # correction round moved an express placement
    "EXPRESS_DEGRADE",  # express batch fell back to the round path
    "STREAM_FLUSH",     # one stream-lane flush: K accumulated windows
                        # scanned as one device program with ONE fetch
                        # (detail.windows/placements/fetches/
                        # failed_window; ops/resident.py stream lane)
    "SPAN",             # per-round/per-batch phase span tree
                        # (--trace_profile; obs/spans.py schema)
    "FLIGHTREC_DUMP",   # the anomaly flight recorder wrote a dump
                        # (detail.reason names the trigger, detail.path
                        # the manifest; obs/flightrec.py)
    "CHECKPOINT",       # a warm-state checkpoint was captured
                        # (ha/checkpoint.py; detail.round/cadence)
    "RESTORE",          # the daemon rehydrated from a checkpoint at
                        # startup (detail.round/warm/rv)
    "JOURNAL_REPLAY",   # an incomplete journaled actuation was
                        # replayed idempotently on restart
                        # (ha/journal.py; detail.op/outcome)
    "SLO_BREACH",       # an SLO burn-rate alert latched (obs/slo.py;
                        # detail.slo names the objective spec,
                        # detail.burn_short/burn_long the rates —
                        # emitted exactly once per breach window)
    "EVICTION_GUARD_HOLD",     # the mass-eviction guard held an
                               # implausible snapshot shrink
                               # (detail.kind node|pod, detail.gone/
                               # known/strike)
    "EVICTION_GUARD_RELEASE",  # the guard released: detail.outcome is
                               # "accepted" (the shrink persisted past
                               # the strike/grace bound and was honored
                               # as true death; the displaced-pod
                               # staging shows up as EVICT events and
                               # SchedulerStats.requeue_admitted/
                               # displaced_parked) or "recovered" (the
                               # snapshot healed); detail carries kind/
                               # gone/known/strikes/held_s
    "OUTAGE",           # the apiserver-outage ladder flipped: detail.
                        # phase is "begin" (consecutive transport
                        # failures crossed --outage_threshold; rounds
                        # keep solving from last-known state, POSTs
                        # park in the actuation outbox) or "end"
                        # (first success; the outbox replays)
    "OUTBOX_DEAD_LETTER",  # an outboxed actuation exhausted its retry
                           # budget (detail.op/uid/attempts); the pod
                           # is re-queued through binding_failed
    "ROUND_DEADLINE_MISS",  # a round's wall span exceeded
                            # --round_deadline_ms (detail.wall_ms);
                            # consecutive misses declare
                            # degraded=overload and shed the express
                            # window to the tick path
})


@dataclasses.dataclass
class TraceEvent:
    timestamp_us: int
    event: str              # one of EVENT_TYPES
    task: str = ""
    machine: str = ""
    round_num: int = 0
    detail: dict | None = None
    # which tenant's session emitted this (the service lane writes all
    # tenants' streams into ONE file; "" = single-tenant daemon)
    tenant: str = ""


class TraceGenerator:
    """Appends one JSON object per line to ``sink`` (file-like).

    ``tenant`` stamps every emitted event — the service lane
    (poseidon_tpu/service/) gives each tenant session its own generator
    over one shared sink, and ``python -m poseidon_tpu.trace report
    --tenant <id>`` filters on the stamp."""

    def __init__(
        self,
        sink: IO[str] | None = None,
        clock_us: Callable[[], int] | None = None,
        buffer_events: int = 10_000,
        tenant: str = "",
    ):
        self.sink = sink
        self.clock_us = clock_us or (lambda: int(time.time() * 1e6))
        self.tenant = tenant
        # with no sink, keep a bounded ring (a daemon running forever
        # must not accumulate events without bound)
        self.events: collections.deque[TraceEvent] = collections.deque(
            maxlen=buffer_events
        )
        # ring-overwrite visibility: events the bounded ring dropped
        # before anyone read them. A post-mortem against the in-memory
        # ring must KNOW it is partial — the bridge mirrors the count
        # into poseidon_trace_dropped_total per round, and the first
        # overwrite warns once.
        self.dropped_total = 0

    def emit(
        self,
        event: str,
        *,
        task: str = "",
        machine: str = "",
        round_num: int = 0,
        detail: dict | None = None,
    ) -> None:
        if event not in EVENT_TYPES:
            raise ValueError(
                f"undeclared trace event {event!r}; the vocabulary is "
                f"trace.EVENT_TYPES (PTA005)"
            )
        ev = TraceEvent(
            timestamp_us=self.clock_us(),
            event=event,
            task=task,
            machine=machine,
            round_num=round_num,
            detail=detail,
            tenant=self.tenant,
        )
        if self.sink is not None:
            self.sink.write(json.dumps(dataclasses.asdict(ev)) + "\n")
        else:
            if (
                self.events.maxlen is not None
                and len(self.events) == self.events.maxlen
            ):
                if not self.dropped_total:
                    log.warning(
                        "trace ring full (%d events, no sink): "
                        "overwriting oldest — this in-memory trace is "
                        "now PARTIAL (counted in dropped_total / "
                        "poseidon_trace_dropped_total)",
                        self.events.maxlen,
                    )
                self.dropped_total += 1
            self.events.append(ev)

    def flush(self) -> None:
        if self.sink is not None:
            self.sink.flush()


# the reader's known schema: any other key in a line is a field some
# NEWER version writes — forward compatibility means dropping it with a
# warning, not TypeError-ing on the whole file
_EVENT_FIELDS = frozenset(
    f.name for f in dataclasses.fields(TraceEvent)
)


def read_trace(path: str):
    """Yield a trace file's events ordered by ``round_num``.

    Pipelined rounds interleave a round's SCHEDULE/ROUND records with
    the next round's SUBMIT records in file order; this reader restores
    round order (stable within a round, so per-round event order is
    file order) so consumers do not have to re-implement the sort the
    module docstring used to prescribe. Blank lines are skipped; a
    malformed line raises ``json.JSONDecodeError`` like any other
    corrupt input.

    Forward compatibility: a trace written by a NEWER version may carry
    fields this reader does not know. Unknown keys are dropped (one
    warning per file naming them) instead of raising ``TypeError`` —
    an old analysis binary must still read a new daemon's trace.

    Torn tails: a process killed mid-``write`` (crash, OOM-kill — the
    flight recorder exists for exactly these) leaves a truncated FINAL
    line. That is a normal post-mortem artifact, not corruption: the
    reader drops it with one warning and yields everything before it.
    A malformed line anywhere ELSE still raises
    ``json.JSONDecodeError`` — mid-file corruption is real corruption.
    """
    dropped: set[str] = set()
    events: list[TraceEvent] = []
    # torn-tail tolerance is one-line deferral, streaming: hold a
    # parse failure and forgive it only if no later non-blank line
    # follows (loading the whole file just to find the last line would
    # double the report's peak memory on multi-hundred-MB daemon
    # traces)
    pending_error: json.JSONDecodeError | None = None
    with open(path) as fh:
        for line in fh:
            if not line.strip():
                continue
            if pending_error is not None:
                raise pending_error  # garbage mid-file: real corruption
            try:
                doc = json.loads(line)
            except json.JSONDecodeError as e:
                pending_error = e
                continue
            unknown = doc.keys() - _EVENT_FIELDS
            if unknown:
                dropped |= unknown
                doc = {
                    k: v for k, v in doc.items() if k in _EVENT_FIELDS
                }
            events.append(TraceEvent(**doc))
    if pending_error is not None:
        log.warning(
            "read_trace(%s): dropping truncated final line "
            "(crash mid-write?)", path,
        )
    if dropped:
        log.warning(
            "read_trace(%s): dropped unknown field(s) %s — trace "
            "written by a newer version?", path, sorted(dropped),
        )
    events.sort(key=lambda e: e.round_num)  # stable: file order within
    yield from events


# ---------------------------------------------------------------------------
# the analysis CLI: python -m poseidon_tpu.trace report|chrome <file>
# ---------------------------------------------------------------------------


def main(argv: list[str] | None = None) -> int:
    import argparse
    import sys as _sys

    p = argparse.ArgumentParser(
        prog="python -m poseidon_tpu.trace",
        description="Analyze a scheduler trace JSONL file",
    )
    sub = p.add_subparsers(dest="cmd", required=True)
    rep = sub.add_parser(
        "report",
        help="the operator's one-pager: round-latency percentiles by "
             "lane/build mode, express event-to-bind percentiles, "
             "degrade/resync/timeout tallies, placement churn",
    )
    rep.add_argument("file")
    rep.add_argument("--json", action="store_true",
                     help="emit the raw data model as JSON")
    rep.add_argument("--tenant", default="",
                     help="report only one tenant's events (the "
                          "service lane stamps each session's tenant "
                          "id onto its trace events)")
    chrome = sub.add_parser(
        "chrome",
        help="export SPAN events (--trace_profile) as Chrome-trace/"
             "Perfetto JSON for chrome://tracing / ui.perfetto.dev",
    )
    chrome.add_argument("file")
    chrome.add_argument("-o", "--out", default="",
                        help="output path (default: <file>.chrome.json)")
    args = p.parse_args(argv)
    # local imports: obs.report/spans import back into this module
    from poseidon_tpu.obs import report as _report
    from poseidon_tpu.obs import spans as _spans

    if args.cmd == "report":
        data = _report.analyze_trace(args.file, tenant=args.tenant)
        if args.json:
            print(json.dumps(data, indent=2))
        else:
            print(_report.render_report(data))
    else:
        out = args.out or (args.file + ".chrome.json")
        _spans.write_chrome_trace(read_trace(args.file), out)
        print(out, file=_sys.stdout)
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
