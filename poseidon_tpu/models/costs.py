"""L3' cost models: pure vectorized arc-pricing functions + registry.

The reference selects a pluggable Firmament cost policy by integer flag —
``--flow_scheduling_cost_model=6`` with the comment "Load-balancing
policy" (reference deploy/poseidon.cfg:6-7, README.md:85-87); the policies
themselves live in the absent Firmament tree (SURVEY.md section 2.2), so
these are re-designs of the documented intent, not ports: Trivial,
Random, Quincy (data locality), Whare-Map (interference from samples),
CoCo (multi-dimensional co-location), Octopus (load balancing — the
selector the shipped config uses).

Each model is a pure function ``(CostInputs) -> int32[E]`` over the padded
arc table, safe under ``jax.jit`` and ``jax.vmap`` (the what-if batching
path, SURVEY.md section 2.4): recomputing costs per round is one fused
device op, not a graph rebuild. Costs are bounded to [0, COST_CAP] so the
solvers' scaled integer domains stay inside int32.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from poseidon_tpu.graph.builder import ArcKind, GraphMeta
from poseidon_tpu.graph.network import FlowNetwork, pad_bucket

# Bound on any single arc cost. With the solvers' n-scaling this keeps the
# price domain well inside int32 for clusters up to ~100k node slots.
COST_CAP = 10_000
_SCALE = 10

# Flagship-domain ceiling. The dense auction requires 2*cmax*(T+1) <
# MAX_SCALED_COST (ops/dense_auction.py overflow analysis), which at the
# flagship envelope T = 10k admits per-arc costs up to ~6.7k. Every
# structurally-unbounded input a registry model prices must therefore be
# clamped under DOMAIN_SAFE_COST, or rounds at flagship scale silently
# demote to the CPU oracle (round-3 advisor finding):
# - wait-rounds aging grows every starved round -> capped at WAIT_CAP
#   (beyond it a parked task already exerts maximum pressure); worst
#   cases quincy 5*_SCALE*(WAIT_CAP+1) = 3.05k, coco COST_CAP//4 +
#   5*_SCALE*WAIT_CAP = 5.5k;
# - quincy's task_input (summed locality weights, data-dependent) ->
#   clamped so TASK_TO_CLUSTER = total + _SCALE stays at 6k;
# - the rebalancing preemption overlay (a RUNNING task's unsched arc =
#   model unsched price + PREEMPTION_PENALTY) -> min-clamped at
#   DOMAIN_SAFE_COST inside ``_finish``.
# Genuinely pathological data (e.g. octopus with >600 running tasks on
# one machine) can still exceed the ceiling; those rounds fall back to
# the oracle loudly, which is the intended envelope behavior.
DOMAIN_SAFE_COST = 6_000
WAIT_CAP = 60

# Rebalancing overlay: what preempting (parking) a RUNNING task adds on
# top of the model's unscheduled price. High enough that preemption only
# wins when the packing is badly wrong (or capacity shrank), low enough
# to stay inside the flagship cost domain after the DOMAIN_SAFE_COST
# clamp in ``_finish``.
PREEMPTION_PENALTY = 100 * _SCALE


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class CostInputs:
    """Device-resident pricing inputs, padded to static buckets.

    Per-arc arrays are aligned to the FlowNetwork's arc slots; ``task`` /
    ``machine`` / ``rack`` are clipped to 0 where not applicable so they
    are always safe gather indices — ``valid``/kind masks decide whether
    the gathered value is used.
    """

    kind: jax.Array          # int32[E] ArcKind (padding: -1)
    task: jax.Array          # int32[E] gather-safe task index
    machine: jax.Array       # int32[E] gather-safe machine index
    weight: jax.Array        # int32[E] data-locality weight
    discount: jax.Array      # int32[E] hysteresis discount
                             # (rebalancing continuation arcs; else 0)
    valid: jax.Array         # bool[E]  real (non-padding) arcs
    task_wait: jax.Array     # int32[Tp] rounds waited per task
    task_running: jax.Array  # bool[Tp] RUNNING (rebalancing) tasks —
                             # their unsched arc is the preemption price
    task_input: jax.Array    # int32[Tp] total input data units per task
    task_cpu: jax.Array      # int32[Tp] requested milli-cores
    task_mem_kb: jax.Array   # int32[Tp] requested memory
    task_usage: jax.Array    # f32[Tp] sampled cpu usage (cores)
    machine_load: jax.Array  # f32[Mp] 1 - mean idle, in [0, 1]
    machine_mem_free: jax.Array  # f32[Mp] mean free-mem fraction [0, 1]
    machine_used_slots: jax.Array  # int32[Mp] running tasks per machine


def build_cost_inputs(
    net: FlowNetwork,
    meta: GraphMeta,
    **kwargs,
) -> CostInputs:
    """Assemble padded pricing inputs and upload them to device.

    See ``build_cost_inputs_host`` for the fields; this variant is the
    convenience path for tests and one-shot solves (each ``jnp.asarray``
    is its own transfer). The production round batches the host variant
    into one ``jax.device_put`` (ops/resident.py).
    """
    host = build_cost_inputs_host(net.num_arc_slots, meta, **kwargs)
    return jax.tree_util.tree_map(jnp.asarray, host)


def build_cost_inputs_host(
    arc_slots: int,
    meta: GraphMeta,
    *,
    task_cpu_milli: np.ndarray | None = None,
    task_mem_kb: np.ndarray | None = None,
    task_usage: np.ndarray | None = None,
    machine_load: np.ndarray | None = None,
    machine_mem_free: np.ndarray | None = None,
    machine_used_slots: np.ndarray | None = None,
    t_min: int = 1,
    m_min: int = 1,
) -> CostInputs:
    """Assemble padded pricing inputs from builder metadata + KB aggregates,
    as HOST numpy arrays (no device traffic).

    The sample-derived arrays (``machine_load`` etc.) come from
    ``KnowledgeBase`` aggregates; they default to an idle, unsampled
    cluster. Shapes: per-task arrays length n_tasks, per-machine length
    n_machines (padded here).

    ``t_min``/``m_min`` are grow-only padding-bucket floors from the
    owning solver (the same anti-recompile hysteresis ``pad_topology``
    applies): without them, a pending pool draining across a bucket
    boundary shrinks the per-task input shapes and recompiles the
    whole fused chain on a round whose topology padding stayed put —
    bench config 10 (``observability_overhead``) caught exactly that
    as a multi-second dispatch on every post-drain round.
    """
    E = arc_slots
    T = len(meta.task_uids)
    M = len(meta.machine_names)
    Tp = pad_bucket(max(T, 1), minimum=t_min)
    Mp = pad_bucket(max(M, 1), minimum=m_min)

    def pad_arc(a: np.ndarray, fill: int) -> np.ndarray:
        out = np.full(E, fill, np.int32)
        out[: meta.n_arcs] = a
        return out

    def padv(a, n, dtype):
        out = np.zeros(n, dtype)
        if a is not None:
            a = np.asarray(a)
            out[: a.shape[0]] = a
        return out

    # Total input data per task = sum of its pref-arc weights (Quincy's
    # "how much data could be local" denominator).
    tin = np.zeros(Tp, np.int64)
    np.add.at(tin, np.maximum(meta.arc_task, 0),
              np.where(meta.arc_task >= 0, meta.arc_weight, 0))
    tin = np.minimum(tin, DOMAIN_SAFE_COST - _SCALE)
    return CostInputs(
        kind=pad_arc(meta.arc_kind.astype(np.int32), -1),
        task=pad_arc(np.maximum(meta.arc_task, 0), 0),
        machine=pad_arc(np.maximum(meta.arc_machine, 0), 0),
        weight=pad_arc(meta.arc_weight, 0),
        discount=pad_arc(meta.arc_discount, 0),
        valid=np.arange(E) < meta.n_arcs,
        task_wait=padv(meta.task_wait, Tp, np.int32),
        task_running=padv(meta.task_current >= 0, Tp, bool),
        task_input=tin.astype(np.int32),
        task_cpu=padv(task_cpu_milli, Tp, np.int32),
        task_mem_kb=padv(task_mem_kb, Tp, np.int32),
        task_usage=padv(task_usage, Tp, np.float32),
        machine_load=padv(machine_load, Mp, np.float32),
        machine_mem_free=(
            padv(machine_mem_free, Mp, np.float32)
            if machine_mem_free is not None else np.ones(Mp, np.float32)
        ),
        machine_used_slots=padv(machine_used_slots, Mp, np.int32),
    )


def _finish(inputs: CostInputs, cost: jax.Array) -> jax.Array:
    """Clamp to the documented domain and zero the padding slots.

    Also applies the rebalancing overlays shared by every model — the
    identity when the graph carries no running tasks / discounts, so
    place-only pricing is unchanged:

    - a RUNNING task's unscheduled arc is its preemption price (model
      unsched price + PREEMPTION_PENALTY, clamped at DOMAIN_SAFE_COST
      so the flagship dense domain holds);
    - continuation arcs subtract their hysteresis discount, so staying
      put beats migrating unless the solver finds at least that much
      improvement elsewhere.
    """
    cost = jnp.clip(cost, 0, COST_CAP).astype(jnp.int32)
    running = inputs.task_running[inputs.task]
    preempt = running & (
        inputs.kind == jnp.int32(int(ArcKind.TASK_TO_UNSCHED))
    )
    cost = jnp.where(
        preempt,
        jnp.minimum(cost + PREEMPTION_PENALTY, DOMAIN_SAFE_COST),
        cost,
    )
    cost = jnp.maximum(cost - inputs.discount, 0)
    return jnp.where(inputs.valid, cost, 0)


def _kind(inputs: CostInputs, k: ArcKind) -> jax.Array:
    return inputs.kind == jnp.int32(int(k))


# ---- the models ----

def trivial_cost(inputs: CostInputs) -> jax.Array:
    """Fixed-fee policy: schedule anywhere, mildly prefer scheduling.

    Wildcard (cluster) routing costs a small constant, leaving a task
    unscheduled a larger one; every other arc is free.
    """
    c = jnp.zeros_like(inputs.kind)
    c = jnp.where(_kind(inputs, ArcKind.TASK_TO_UNSCHED), 5 * _SCALE, c)
    c = jnp.where(_kind(inputs, ArcKind.TASK_TO_CLUSTER), 2 * _SCALE, c)
    return _finish(inputs, c)


def random_cost(inputs: CostInputs, seed: int = 42) -> jax.Array:
    """Deterministic pseudo-random arc costs (debug / fuzz policy)."""
    x = (inputs.kind.astype(jnp.uint32) * jnp.uint32(0x9E3779B9)
         + inputs.task.astype(jnp.uint32) * jnp.uint32(0x85EBCA6B)
         + inputs.machine.astype(jnp.uint32) * jnp.uint32(0xC2B2AE35)
         + jnp.uint32(seed))
    x = x ^ (x >> 15)
    x = x * jnp.uint32(0x2C1B3C6D)
    x = x ^ (x >> 12)
    c = (x % jnp.uint32(100)).astype(jnp.int32)
    # keep unsched clearly the worst option so random still schedules
    c = jnp.where(_kind(inputs, ArcKind.TASK_TO_UNSCHED), COST_CAP // 2, c)
    return _finish(inputs, c)


def quincy_cost(inputs: CostInputs) -> jax.Array:
    """Data-locality policy (Quincy-style).

    A preference arc's cost is the data the task would have to fetch
    remotely if placed there (total input minus what is local at the
    target); the wildcard path assumes nothing is local; the unscheduled
    arc grows with how long the task has waited, so starvation pressure
    eventually overrides locality.
    """
    total = inputs.task_input[inputs.task]
    remote = jnp.maximum(total - inputs.weight, 0)
    c = jnp.zeros_like(inputs.kind)
    pref = (_kind(inputs, ArcKind.TASK_TO_MACHINE)
            | _kind(inputs, ArcKind.TASK_TO_RACK))
    c = jnp.where(pref, remote, c)
    c = jnp.where(_kind(inputs, ArcKind.TASK_TO_CLUSTER), total + _SCALE, c)
    wait = jnp.minimum(inputs.task_wait[inputs.task], WAIT_CAP)
    c = jnp.where(_kind(inputs, ArcKind.TASK_TO_UNSCHED),
                  5 * _SCALE * (wait + 1), c)
    # crossing a rack boundary to reach the machine costs a hop
    c = jnp.where(_kind(inputs, ArcKind.RACK_TO_MACHINE), _SCALE // 2, c)
    return _finish(inputs, c)


def octopus_cost(inputs: CostInputs) -> jax.Array:
    """Load-balancing policy — the reference's shipped selector
    (deploy/poseidon.cfg:6-7): busy machines price up, so flow spreads.
    """
    load = (inputs.machine_load * 100).astype(jnp.int32)
    slots = inputs.machine_used_slots * _SCALE
    per_machine = (load + slots)[inputs.machine]
    c = jnp.zeros_like(inputs.kind)
    to_machine = (_kind(inputs, ArcKind.CLUSTER_TO_MACHINE)
                  | _kind(inputs, ArcKind.RACK_TO_MACHINE)
                  | _kind(inputs, ArcKind.TASK_TO_MACHINE))
    c = jnp.where(to_machine, per_machine, c)
    c = jnp.where(_kind(inputs, ArcKind.MACHINE_TO_SINK), per_machine, c)
    c = jnp.where(_kind(inputs, ArcKind.TASK_TO_CLUSTER), _SCALE, c)
    c = jnp.where(_kind(inputs, ArcKind.TASK_TO_UNSCHED), COST_CAP // 4, c)
    return _finish(inputs, c)


def wharemap_cost(inputs: CostInputs) -> jax.Array:
    """Interference scoring from observed samples (Whare-Map-style).

    Prices a task onto a machine by the product of the machine's observed
    load and the task's observed hunger — co-locating a hot task on a hot
    machine is the expensive corner. Unsampled entities degrade to pure
    load balancing.
    """
    hunger = jnp.clip(inputs.task_usage[inputs.task]
                      + inputs.task_cpu[inputs.task].astype(jnp.float32)
                      / 1000.0, 0.1, 8.0)
    load = inputs.machine_load[inputs.machine]
    interf = (hunger * load * 100.0).astype(jnp.int32)
    c = jnp.zeros_like(inputs.kind)
    direct = (_kind(inputs, ArcKind.TASK_TO_MACHINE)
              | _kind(inputs, ArcKind.CLUSTER_TO_MACHINE)
              | _kind(inputs, ArcKind.RACK_TO_MACHINE))
    c = jnp.where(direct, interf, c)
    c = jnp.where(_kind(inputs, ArcKind.TASK_TO_CLUSTER), 2 * _SCALE, c)
    c = jnp.where(_kind(inputs, ArcKind.TASK_TO_UNSCHED), COST_CAP // 4, c)
    return _finish(inputs, c)


def coco_cost(inputs: CostInputs) -> jax.Array:
    """Multi-dimensional co-location policy (CoCo-style).

    Cost is the tightest normalized resource fit across CPU and memory:
    placing a demanding task on a machine with little headroom is
    penalized superlinearly, so the solver packs across dimensions.
    """
    cpu_req = inputs.task_cpu[inputs.task].astype(jnp.float32) / 1000.0
    mem_req = inputs.task_mem_kb[inputs.task].astype(jnp.float32)
    cpu_head = jnp.maximum(1.0 - inputs.machine_load[inputs.machine], 0.05)
    mem_head = jnp.maximum(inputs.machine_mem_free[inputs.machine], 0.05)
    fit = jnp.maximum(cpu_req / cpu_head,
                      mem_req / (mem_head * (1 << 20)))
    sq = jnp.clip(fit, 0.0, 4.0)
    score = (sq * sq * 100.0).astype(jnp.int32)
    c = jnp.zeros_like(inputs.kind)
    placing = (_kind(inputs, ArcKind.TASK_TO_MACHINE)
               | _kind(inputs, ArcKind.CLUSTER_TO_MACHINE)
               | _kind(inputs, ArcKind.RACK_TO_MACHINE))
    c = jnp.where(placing, score, c)
    c = jnp.where(_kind(inputs, ArcKind.TASK_TO_CLUSTER), 3 * _SCALE, c)
    wait = jnp.minimum(inputs.task_wait[inputs.task], WAIT_CAP)
    c = jnp.where(_kind(inputs, ArcKind.TASK_TO_UNSCHED),
                  COST_CAP // 4 + 5 * _SCALE * wait, c)
    return _finish(inputs, c)


CostModelFn = Callable[[CostInputs], jax.Array]

# Name registry + the reference's integer selector compatibility
# (deploy/poseidon.cfg:7 selects 6, the load-balancing policy).
COST_MODELS: dict[str, CostModelFn] = {
    "trivial": trivial_cost,
    "random": random_cost,
    "quincy": quincy_cost,
    "wharemap": wharemap_cost,
    "coco": coco_cost,
    "octopus": octopus_cost,
}

COST_MODEL_SELECTORS: dict[int, str] = {
    0: "trivial",
    1: "random",
    3: "quincy",
    4: "wharemap",
    5: "coco",
    6: "octopus",
}


def resolve_cost_model_name(name_or_selector: str | int) -> str:
    """Canonical registry name for a name or the reference's integer
    flag. Digit strings count as integer selectors — the flag surface
    is stringly typed (``--flow_scheduling_cost_model=6``)."""
    if isinstance(name_or_selector, str) and name_or_selector.isdigit():
        name_or_selector = int(name_or_selector)
    if isinstance(name_or_selector, int):
        try:
            return COST_MODEL_SELECTORS[name_or_selector]
        except KeyError:
            raise KeyError(
                f"unknown cost model selector {name_or_selector}; "
                f"known: {sorted(COST_MODEL_SELECTORS)}"
            ) from None
    return name_or_selector


def get_cost_model(name_or_selector: str | int) -> CostModelFn:
    """Look up a cost model by name or by the reference's integer flag."""
    name = resolve_cost_model_name(name_or_selector)
    try:
        return COST_MODELS[name]
    except KeyError:
        raise KeyError(
            f"unknown cost model {name!r}; known: {sorted(COST_MODELS)}"
        ) from None


# ---------------------------------------------------------------------------
# per-term attribution: the explainer's API (obs/explain.py)
# ---------------------------------------------------------------------------
#
# Each model's cost is, by construction, a SUM of named terms per arc
# (locality, load, wait-aging, fixed channel fees...). The term
# functions below recompute each model with the identical expressions,
# split into those named components, and ``_overlay_terms`` applies the
# ``_finish`` overlays (domain clamp, preemption penalty, hysteresis
# discount) as explicit adjustment terms — so for every arc the term
# values sum BIT-EXACTLY to the registry model's priced output on the
# same backend (asserted by ``tests/test_explain.py`` across models,
# and by ``arc_cost_terms`` itself at call time). Float-derived
# quantities (octopus load, wharemap interference, coco fit) are kept
# as single terms: splitting them would reassociate float arithmetic
# and break the bit-exactness contract.


def _zmask(inputs: CostInputs, mask, value):
    z = jnp.zeros_like(inputs.kind)
    return jnp.where(mask, value, z)


def _trivial_terms(inputs: CostInputs) -> dict[str, jax.Array]:
    return {
        "unsched_base": _zmask(
            inputs, _kind(inputs, ArcKind.TASK_TO_UNSCHED), 5 * _SCALE
        ),
        "wildcard_base": _zmask(
            inputs, _kind(inputs, ArcKind.TASK_TO_CLUSTER), 2 * _SCALE
        ),
    }


def _random_terms(inputs: CostInputs) -> dict[str, jax.Array]:
    # the hash is one indivisible term (there is nothing to attribute)
    x = (inputs.kind.astype(jnp.uint32) * jnp.uint32(0x9E3779B9)
         + inputs.task.astype(jnp.uint32) * jnp.uint32(0x85EBCA6B)
         + inputs.machine.astype(jnp.uint32) * jnp.uint32(0xC2B2AE35)
         + jnp.uint32(42))
    x = x ^ (x >> 15)
    x = x * jnp.uint32(0x2C1B3C6D)
    x = x ^ (x >> 12)
    c = (x % jnp.uint32(100)).astype(jnp.int32)
    c = jnp.where(
        _kind(inputs, ArcKind.TASK_TO_UNSCHED), COST_CAP // 2, c
    )
    return {"hash": c}


def _quincy_terms(inputs: CostInputs) -> dict[str, jax.Array]:
    total = inputs.task_input[inputs.task]
    remote = jnp.maximum(total - inputs.weight, 0)
    pref = (_kind(inputs, ArcKind.TASK_TO_MACHINE)
            | _kind(inputs, ArcKind.TASK_TO_RACK))
    cluster = _kind(inputs, ArcKind.TASK_TO_CLUSTER)
    unsched = _kind(inputs, ArcKind.TASK_TO_UNSCHED)
    wait = jnp.minimum(inputs.task_wait[inputs.task], WAIT_CAP)
    z = jnp.zeros_like(inputs.kind)
    return {
        "remote_data": jnp.where(
            pref, remote, jnp.where(cluster, total, z)
        ),
        "wildcard_base": _zmask(inputs, cluster, _SCALE),
        "wait_aging": _zmask(inputs, unsched, 5 * _SCALE * wait),
        "unsched_base": _zmask(inputs, unsched, 5 * _SCALE),
        "rack_hop": _zmask(
            inputs, _kind(inputs, ArcKind.RACK_TO_MACHINE), _SCALE // 2
        ),
    }


def _octopus_terms(inputs: CostInputs) -> dict[str, jax.Array]:
    load = (inputs.machine_load * 100).astype(jnp.int32)
    slots = inputs.machine_used_slots * _SCALE
    routed = (_kind(inputs, ArcKind.CLUSTER_TO_MACHINE)
              | _kind(inputs, ArcKind.RACK_TO_MACHINE)
              | _kind(inputs, ArcKind.TASK_TO_MACHINE)
              | _kind(inputs, ArcKind.MACHINE_TO_SINK))
    return {
        "machine_load": _zmask(inputs, routed, load[inputs.machine]),
        "used_slots": _zmask(inputs, routed, slots[inputs.machine]),
        "wildcard_base": _zmask(
            inputs, _kind(inputs, ArcKind.TASK_TO_CLUSTER), _SCALE
        ),
        "unsched_base": _zmask(
            inputs, _kind(inputs, ArcKind.TASK_TO_UNSCHED),
            COST_CAP // 4,
        ),
    }


def _wharemap_terms(inputs: CostInputs) -> dict[str, jax.Array]:
    hunger = jnp.clip(inputs.task_usage[inputs.task]
                      + inputs.task_cpu[inputs.task].astype(jnp.float32)
                      / 1000.0, 0.1, 8.0)
    load = inputs.machine_load[inputs.machine]
    interf = (hunger * load * 100.0).astype(jnp.int32)
    direct = (_kind(inputs, ArcKind.TASK_TO_MACHINE)
              | _kind(inputs, ArcKind.CLUSTER_TO_MACHINE)
              | _kind(inputs, ArcKind.RACK_TO_MACHINE))
    return {
        "interference": _zmask(inputs, direct, interf),
        "wildcard_base": _zmask(
            inputs, _kind(inputs, ArcKind.TASK_TO_CLUSTER), 2 * _SCALE
        ),
        "unsched_base": _zmask(
            inputs, _kind(inputs, ArcKind.TASK_TO_UNSCHED),
            COST_CAP // 4,
        ),
    }


def _coco_terms(inputs: CostInputs) -> dict[str, jax.Array]:
    cpu_req = inputs.task_cpu[inputs.task].astype(jnp.float32) / 1000.0
    mem_req = inputs.task_mem_kb[inputs.task].astype(jnp.float32)
    cpu_head = jnp.maximum(1.0 - inputs.machine_load[inputs.machine], 0.05)
    mem_head = jnp.maximum(inputs.machine_mem_free[inputs.machine], 0.05)
    fit = jnp.maximum(cpu_req / cpu_head,
                      mem_req / (mem_head * (1 << 20)))
    sq = jnp.clip(fit, 0.0, 4.0)
    score = (sq * sq * 100.0).astype(jnp.int32)
    placing = (_kind(inputs, ArcKind.TASK_TO_MACHINE)
               | _kind(inputs, ArcKind.CLUSTER_TO_MACHINE)
               | _kind(inputs, ArcKind.RACK_TO_MACHINE))
    unsched = _kind(inputs, ArcKind.TASK_TO_UNSCHED)
    wait = jnp.minimum(inputs.task_wait[inputs.task], WAIT_CAP)
    return {
        "resource_fit": _zmask(inputs, placing, score),
        "wildcard_base": _zmask(
            inputs, _kind(inputs, ArcKind.TASK_TO_CLUSTER), 3 * _SCALE
        ),
        "wait_aging": _zmask(inputs, unsched, 5 * _SCALE * wait),
        "unsched_base": _zmask(inputs, unsched, COST_CAP // 4),
    }


COST_TERM_FNS: dict[str, Callable[[CostInputs], dict[str, jax.Array]]] = {
    "trivial": _trivial_terms,
    "random": _random_terms,
    "quincy": _quincy_terms,
    "wharemap": _wharemap_terms,
    "coco": _coco_terms,
    "octopus": _octopus_terms,
}


def _overlay_terms(
    inputs: CostInputs, terms: dict[str, jax.Array]
) -> dict[str, jax.Array]:
    """Apply the shared ``_finish`` overlays as explicit adjustment
    terms, so the returned dict sums to the model's final arc cost on
    every slot (padding slots included — everything masks to 0)."""
    raw = None
    for v in terms.values():
        raw = v if raw is None else raw + v
    clipped = jnp.clip(raw, 0, COST_CAP).astype(jnp.int32)
    running = inputs.task_running[inputs.task]
    preempt = running & (
        inputs.kind == jnp.int32(int(ArcKind.TASK_TO_UNSCHED))
    )
    after_pre = jnp.where(
        preempt,
        jnp.minimum(clipped + PREEMPTION_PENALTY, DOMAIN_SAFE_COST),
        clipped,
    )
    after_disc = jnp.maximum(after_pre - inputs.discount, 0)
    out = dict(terms)
    out["domain_clamp"] = clipped - raw
    out["preemption_penalty"] = after_pre - clipped
    out["hysteresis_discount"] = after_disc - after_pre
    return {
        k: jnp.where(inputs.valid, v, 0) for k, v in out.items()
    }


def arc_cost_terms(
    name_or_selector: str | int, inputs: CostInputs
) -> dict[str, np.ndarray]:
    """Named per-arc cost terms for a registry model, as HOST arrays.

    The returned ``{term_name: int32[E]}`` values sum bit-exactly to
    ``get_cost_model(name)(inputs)`` on the same backend — verified at
    call time (a mismatch raises, so the explainer can never report a
    breakdown that does not add up to the solver's arc cost). Zero-
    everywhere terms are kept: consumers drop them per decision."""
    name = resolve_cost_model_name(name_or_selector)
    try:
        raw_fn = COST_TERM_FNS[name]
    except KeyError:
        raise KeyError(
            f"no term attribution for cost model {name!r}; "
            f"known: {sorted(COST_TERM_FNS)}"
        ) from None
    terms_dev = _overlay_terms(inputs, raw_fn(inputs))
    total_dev = COST_MODELS[name](inputs)
    host = jax.device_get((terms_dev, total_dev))
    terms = {k: np.asarray(v, np.int32) for k, v in host[0].items()}
    total = np.asarray(host[1], np.int32)
    acc = np.zeros_like(total, np.int64)
    for v in terms.values():
        acc += v
    if not np.array_equal(acc, total.astype(np.int64)):
        bad = int(np.flatnonzero(acc != total)[0])
        raise AssertionError(
            f"term breakdown for model {name!r} does not sum to the "
            f"priced arc cost (first mismatch at arc {bad}: "
            f"{int(acc[bad])} != {int(total[bad])})"
        )
    return terms
