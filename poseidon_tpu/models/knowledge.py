"""KnowledgeBase': bounded ring buffers of cluster utilization samples.

The reference feeds node/pod utilization into Firmament's KnowledgeBase
every poll tick (reference src/firmament/knowledge_base_populator.cc:65-99:
``AddMachineSample`` / ``AddTaskSample``), bounded by
``--max_sample_queue_size=100`` (reference deploy/poseidon.cfg:5); the cost
models price interference and load from those samples (SURVEY.md section
2.2). Here the store is a fixed-shape numpy ring per machine/task so the
aggregates the cost models consume are O(1) vectorized reductions, ready
to ship to device as dense arrays.
"""

from __future__ import annotations

import dataclasses

import numpy as np

DEFAULT_QUEUE_SIZE = 100  # reference deploy/poseidon.cfg:5


@dataclasses.dataclass(frozen=True)
class MachineSample:
    """One utilization sample for a machine.

    Mirrors the fields the reference's populator fills into
    ``MachinePerfStatisticsSample`` (knowledge_base_populator.cc:68-81):
    free RAM and per-cpu idle fraction (the reference fabricates idle from
    allocatable/capacity counts, :35-63 — here it is a real input).
    """

    cpu_idle: float        # [0, 1] fraction of CPU idle
    mem_free_frac: float   # [0, 1] fraction of memory free


@dataclasses.dataclass(frozen=True)
class TaskSample:
    """One usage sample for a running task (TaskPerfStatisticsSample,
    knowledge_base_populator.cc:84-99, plus the final-report fields the
    reference stubs out at :101-113)."""

    cpu_usage: float       # cores actually used
    mem_usage_kb: int


class KnowledgeBase:
    """Fixed-capacity sample rings keyed by machine / task name.

    ``machine_load()`` and friends return dense arrays aligned to a caller
    -supplied name order, so cost models can consume them directly as
    device arrays.
    """

    def __init__(self, queue_size: int = DEFAULT_QUEUE_SIZE):
        if queue_size <= 0:
            raise ValueError("queue_size must be positive")
        self.queue_size = queue_size
        self._machines: dict[str, tuple[np.ndarray, np.ndarray, int]] = {}
        self._tasks: dict[str, tuple[np.ndarray, np.ndarray, int]] = {}

    # ---- ingestion ----

    def add_machine_sample(self, name: str, sample: MachineSample) -> None:
        if name not in self._machines:
            self._machines[name] = (
                np.zeros(self.queue_size, np.float32),
                np.zeros(self.queue_size, np.float32),
                0,
            )
        idle, free, n = self._machines[name]
        idle[n % self.queue_size] = sample.cpu_idle
        free[n % self.queue_size] = sample.mem_free_frac
        self._machines[name] = (idle, free, n + 1)

    def add_task_sample(self, uid: str, sample: TaskSample) -> None:
        if uid not in self._tasks:
            self._tasks[uid] = (
                np.zeros(self.queue_size, np.float32),
                np.zeros(self.queue_size, np.float32),
                0,
            )
        cpu, mem, n = self._tasks[uid]
        cpu[n % self.queue_size] = sample.cpu_usage
        mem[n % self.queue_size] = float(sample.mem_usage_kb)
        self._tasks[uid] = (cpu, mem, n + 1)

    # ---- aggregates (dense, order given by the caller) ----

    def _mean(self, store, names, which: int, default: float) -> np.ndarray:
        out = np.full(len(names), default, np.float32)
        for i, name in enumerate(names):
            entry = store.get(name)
            if entry is None or entry[2] == 0:
                continue
            buf, n = entry[which], min(entry[2], self.queue_size)
            out[i] = float(buf[:n].mean())
        return out

    def machine_cpu_idle(self, names: list[str]) -> np.ndarray:
        """Mean idle fraction per machine; 1.0 (fully idle) if unsampled."""
        return self._mean(self._machines, names, 0, 1.0)

    def machine_mem_free(self, names: list[str]) -> np.ndarray:
        return self._mean(self._machines, names, 1, 1.0)

    def machine_load(self, names: list[str]) -> np.ndarray:
        """1 - idle: the load signal Octopus/CoCo price (0 if unsampled)."""
        return 1.0 - self.machine_cpu_idle(names)

    def task_cpu_usage(self, uids: list[str]) -> np.ndarray:
        return self._mean(self._tasks, uids, 0, 0.0)
