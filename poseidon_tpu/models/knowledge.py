"""KnowledgeBase': bounded ring buffers of cluster utilization samples.

The reference feeds node/pod utilization into Firmament's KnowledgeBase
every poll tick (reference src/firmament/knowledge_base_populator.cc:65-99:
``AddMachineSample`` / ``AddTaskSample``), bounded by
``--max_sample_queue_size=100`` (reference deploy/poseidon.cfg:5); the cost
models price interference and load from those samples (SURVEY.md section
2.2). Here the store is a fixed-shape numpy ring per machine/task so the
aggregates the cost models consume are O(1) vectorized reductions, ready
to ship to device as dense arrays.
"""

from __future__ import annotations

import dataclasses

import numpy as np

DEFAULT_QUEUE_SIZE = 100  # reference deploy/poseidon.cfg:5


@dataclasses.dataclass(frozen=True)
class MachineSample:
    """One utilization sample for a machine.

    Mirrors the fields the reference's populator fills into
    ``MachinePerfStatisticsSample`` (knowledge_base_populator.cc:68-81):
    free RAM and per-cpu idle fraction (the reference fabricates idle from
    allocatable/capacity counts, :35-63 — here it is a real input).
    """

    cpu_idle: float        # [0, 1] fraction of CPU idle
    mem_free_frac: float   # [0, 1] fraction of memory free


@dataclasses.dataclass(frozen=True)
class TaskSample:
    """One usage sample for a running task (TaskPerfStatisticsSample,
    knowledge_base_populator.cc:84-99, plus the final-report fields the
    reference stubs out at :101-113)."""

    cpu_usage: float       # cores actually used
    mem_usage_kb: int


class _RingStore:
    """2-D sample rings: one row per name, running sums for O(1) means.

    The round-3 advisor flagged the per-name Python loop in the old
    ``_mean`` — it sat inside the priced path at 12k machines every
    round. Storage here is ``[n_fields, rows, queue_size]`` with a
    per-row running sum maintained on insert (overwrite subtracts the
    evicted sample), so an aggregate over N names is one gather +
    divide. The only per-name Python left is the name->row dict lookup
    (~1 ms for 12k names).
    """

    def __init__(self, queue_size: int, n_fields: int):
        self.queue_size = queue_size
        self.n_fields = n_fields
        self._idx: dict[str, int] = {}
        self._free: list[int] = []   # rows of retired names, reusable
        cap = 256
        self._buf = np.zeros((n_fields, cap, queue_size), np.float32)
        self._sum = np.zeros((n_fields, cap), np.float64)
        self._count = np.zeros(cap, np.int64)

    def _row(self, name: str) -> int:
        row = self._idx.get(name)
        if row is None:
            if self._free:
                row = self._free.pop()
            else:
                row = len(self._idx)
                if row >= self._count.shape[0]:
                    cap = self._count.shape[0] * 2
                    self._buf = np.concatenate(
                        [self._buf, np.zeros_like(self._buf)], axis=1
                    )
                    self._sum = np.concatenate(
                        [self._sum, np.zeros_like(self._sum)], axis=1
                    )
                    self._count = np.concatenate(
                        [self._count, np.zeros(cap // 2, np.int64)]
                    )
            self._idx[name] = row
        return row

    def retire(self, name: str) -> None:
        """Free a name's row for reuse (a forever-running daemon with
        pod churn must not grow a ring per retired uid forever)."""
        row = self._idx.pop(name, None)
        if row is not None:
            self._buf[:, row, :] = 0
            self._sum[:, row] = 0
            self._count[row] = 0
            self._free.append(row)

    def add(self, name: str, *values: float) -> None:
        row = self._row(name)
        slot = self._count[row] % self.queue_size
        for f, v in enumerate(values):
            # accumulate the float32-rounded value the buffer stores, so
            # the eventual eviction subtracts exactly what was added (a
            # full-precision add would leave a permanent residual per
            # sample — unbounded drift in a forever-running daemon)
            v32 = np.float32(v)
            self._sum[f, row] += float(v32) - float(self._buf[f, row, slot])
            self._buf[f, row, slot] = v32
        self._count[row] += 1

    def export_state(self) -> dict:
        """Host-array snapshot for checkpointing (ha/checkpoint.py).

        The rings mutate in place every observe tick, so the arrays are
        copied here; ``restore_state`` of the returned dict reproduces
        the store bit-exactly — the aggregates the cost models consume
        are running sums over these buffers, so a restored scheduler
        prices the next round from the same utilization history the
        crashed one held, not from one cold re-observed sample.
        """
        return {
            "buf": np.array(self._buf, copy=True),
            "sum": np.array(self._sum, copy=True),
            "count": np.array(self._count, copy=True),
            "idx": dict(self._idx),
            "free": list(self._free),
            "queue_size": self.queue_size,
        }

    def restore_state(self, state: dict) -> None:
        """Adopt an ``export_state`` snapshot wholesale."""
        if int(state["queue_size"]) != self.queue_size:
            raise ValueError(
                f"checkpointed queue_size {state['queue_size']} != "
                f"configured {self.queue_size}"
            )
        self._buf = np.array(state["buf"], np.float32, copy=True)
        self._sum = np.array(state["sum"], np.float64, copy=True)
        self._count = np.array(state["count"], np.int64, copy=True)
        self._idx = {str(k): int(v) for k, v in state["idx"].items()}
        self._free = [int(r) for r in state["free"]]

    def means(
        self, names: list[str], field: int, default: float
    ) -> np.ndarray:
        n = len(names)
        rows = np.fromiter(
            (self._idx.get(name, -1) for name in names), np.int64, n
        )
        r = np.maximum(rows, 0)
        denom = np.minimum(self._count[r], self.queue_size)
        out = np.where(
            (rows >= 0) & (denom > 0),
            self._sum[field][r] / np.maximum(denom, 1),
            default,
        )
        return out.astype(np.float32)


class KnowledgeBase:
    """Fixed-capacity sample rings keyed by machine / task name.

    ``machine_load()`` and friends return dense arrays aligned to a caller
    -supplied name order, so cost models can consume them directly as
    device arrays.
    """

    def __init__(self, queue_size: int = DEFAULT_QUEUE_SIZE):
        if queue_size <= 0:
            raise ValueError("queue_size must be positive")
        self.queue_size = queue_size
        self._machines = _RingStore(queue_size, 2)
        self._tasks = _RingStore(queue_size, 2)

    # ---- ingestion ----

    def add_machine_sample(self, name: str, sample: MachineSample) -> None:
        self._machines.add(name, sample.cpu_idle, sample.mem_free_frac)

    def add_task_sample(self, uid: str, sample: TaskSample) -> None:
        self._tasks.add(uid, sample.cpu_usage, float(sample.mem_usage_kb))

    def retire_task(self, uid: str) -> None:
        """Drop a retired pod's ring (called when the bridge retires it)."""
        self._tasks.retire(uid)

    def retire_machine(self, name: str) -> None:
        """Drop a removed node's ring."""
        self._machines.retire(name)

    # ---- aggregates (dense, order given by the caller) ----

    def machine_cpu_idle(self, names: list[str]) -> np.ndarray:
        """Mean idle fraction per machine; 1.0 (fully idle) if unsampled."""
        return self._machines.means(names, 0, 1.0)

    def machine_mem_free(self, names: list[str]) -> np.ndarray:
        return self._machines.means(names, 1, 1.0)

    def machine_load(self, names: list[str]) -> np.ndarray:
        """1 - idle: the load signal Octopus/CoCo price (0 if unsampled)."""
        return 1.0 - self.machine_cpu_idle(names)

    def task_cpu_usage(self, uids: list[str]) -> np.ndarray:
        return self._tasks.means(uids, 0, 0.0)

    # ---- checkpoint/restore (ha/checkpoint.py) ----

    def export_state(self) -> dict:
        """Both stores' ring state, copied (see ``_RingStore``)."""
        return {
            "queue_size": self.queue_size,
            "machines": self._machines.export_state(),
            "tasks": self._tasks.export_state(),
        }

    def restore_state(self, state: dict) -> None:
        if int(state["queue_size"]) != self.queue_size:
            raise ValueError(
                f"checkpointed queue_size {state['queue_size']} != "
                f"configured {self.queue_size}"
            )
        self._machines.restore_state(state["machines"])
        self._tasks.restore_state(state["tasks"])
