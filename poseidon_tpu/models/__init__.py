"""L3' cost-model layer: vectorized arc pricing + sample knowledge base."""

from poseidon_tpu.models.costs import (  # noqa: F401
    COST_CAP,
    COST_MODELS,
    COST_MODEL_SELECTORS,
    CostInputs,
    build_cost_inputs,
    coco_cost,
    get_cost_model,
    octopus_cost,
    quincy_cost,
    random_cost,
    trivial_cost,
    wharemap_cost,
)
from poseidon_tpu.models.knowledge import (  # noqa: F401
    KnowledgeBase,
    MachineSample,
    TaskSample,
)
