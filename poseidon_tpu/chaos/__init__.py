"""Deterministic chaos harness: seeded fault schedules + invariants.

The failure-domain survival story (node-failure storms, apiserver
outages, overload bursts) is only real if it is *machine-checked*:
``scenarios.py`` drives the REAL daemon loop (cli.run_loop, fake
apiserver, journal/outbox/guard all live) through seeded,
round-scheduled fault injections and asserts the invariants that
define "survived" — exactly-once actuation, zero lost pods, guard
release within the grace bound, and bounded time back to a certified
round after the fault clears. Run as fuzz in tests/test_chaos.py and
as bench config 15 ``chaos_recovery``.
"""

from poseidon_tpu.chaos.scenarios import (
    ChaosOrchestrator,
    ChaosScenario,
    FaultAction,
    InvariantReport,
    check_invariants,
    read_stats,
    rounds_to_recover,
    run_daemon_scenario,
    scenario_apiserver_outage,
    scenario_composite,
    scenario_node_storm,
    scenario_overload_burst,
    seed_cluster,
)

__all__ = [
    "ChaosOrchestrator",
    "ChaosScenario",
    "FaultAction",
    "InvariantReport",
    "check_invariants",
    "read_stats",
    "rounds_to_recover",
    "run_daemon_scenario",
    "scenario_apiserver_outage",
    "scenario_composite",
    "scenario_node_storm",
    "scenario_overload_burst",
    "seed_cluster",
]
