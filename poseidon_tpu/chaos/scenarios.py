"""Seeded, schedule-driven fault orchestration + survival invariants.

**Why deterministic.** Chaos testing that fires faults off wall-clock
timers produces unreproducible failures; this harness keys every
injection on *completed round numbers* (the ``round_hook`` seam
``cli.run_loop`` exposes: the hook runs on the driver thread between
rounds) and draws every random choice (which nodes a storm kills,
which pods a burst adds) from one seeded ``random.Random`` — the same
scenario + seed replays the same fault sequence against the same
daemon decisions, so a failed invariant is a debuggable artifact, not
a flake.

**The orchestrator** drives the fake apiserver's injection surface:
``fail_next`` / ``rate_limit_next`` / ``disconnect_next`` /
``delay_next`` (hung apiserver) / ``gone_next_watch`` /
``apply_then_disconnect_next`` / ``compact_watch_log`` /
``set_outage`` (whole-control-plane 503 window) plus ``node_storm``
(seeded mass ``drop_node``) and ``pod_burst`` (seeded arrivals).

**The invariants** (``check_invariants``) define "survived":

- *exactly-once actuation*: in the apiserver's ordered ``op_log``, no
  pod is bound twice without an intervening eviction or node-death
  orphaning — retries, journal replays, and outbox replays collapsed
  idempotently;
- *zero lost pods*: every pod the apiserver knows ends the run
  Running with a node (nothing stranded Pending, nothing forgotten);
- *guard release within the bound*: every EVICTION_GUARD_HOLD run is
  closed by a RELEASE, and an accepted release lands within the
  strike/grace bound of the first hold;
- *bounded recovery*: the first post-fault-clear round with no
  pending, no unscheduled, and no parked displacement arrives within
  ``recover_within`` rounds (``rounds_to_recover`` measures it);
- *no silent degrades*: ``degrades_total`` stays zero — every
  recovery round kept its exactness certificate.

Scenarios are plain data (``ChaosScenario``); ``run_daemon_scenario``
runs one against the REAL daemon loop — journal, outbox, guard,
watchdog, metrics all live — and returns the evidence (stats rows,
trace events, the server's final state) for the checker.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import random

log = logging.getLogger(__name__)

# the injection vocabulary (FaultAction.kind)
ACTIONS = frozenset({
    "fail_next",                  # args: n
    "rate_limit_next",            # args: n, retry_after_s
    "disconnect_next",            # args: n
    "delay_next",                 # args: n, seconds
    "gone_next_watch",            # args: n
    "apply_then_disconnect_next",  # args: n
    "compact_watch_log",          # args: -
    "outage_begin",               # args: writes_only? (reads-OK/
                                  # writes-down etcd-quorum shape)
    "outage_end",                 # args: -
    "node_storm",                 # args: kill (count; seeded choice)
    "pod_burst",                  # args: n, cpu?, memory?
})


@dataclasses.dataclass(frozen=True)
class FaultAction:
    """One scheduled injection: fires after round ``at_round``
    completes (the hook's rounds-completed counter)."""

    at_round: int
    kind: str
    args: dict = dataclasses.field(default_factory=dict)

    def __post_init__(self):
        if self.kind not in ACTIONS:
            raise ValueError(f"unknown chaos action {self.kind!r}")


@dataclasses.dataclass(frozen=True)
class ChaosScenario:
    """A named, seeded fault schedule over a synthetic cluster."""

    name: str
    seed: int
    actions: tuple[FaultAction, ...]
    rounds: int               # total daemon rounds to drive
    fault_clear_round: int    # last round with an active/armed fault
    recover_within: int       # max rounds after clear to full recovery
    nodes: int = 16
    pods: int = 64
    flags: tuple[str, ...] = ()   # extra cli flags (e.g. --watch=true)


class ChaosOrchestrator:
    """Applies a scenario's due actions from the ``round_hook`` seam
    (driver thread, between rounds — deterministic by construction).
    ``applied`` records (round, kind, detail) for the post-mortem."""

    def __init__(self, server, scenario: ChaosScenario):
        self.server = server
        self.scenario = scenario
        self.rng = random.Random(scenario.seed)
        self.applied: list[tuple[int, str, str]] = []
        self._by_round: dict[int, list[FaultAction]] = {}
        for a in scenario.actions:
            self._by_round.setdefault(a.at_round, []).append(a)

    def on_round(self, rounds_completed: int, result=None) -> None:
        for a in self._by_round.pop(rounds_completed, []):
            detail = self._apply(a)
            self.applied.append((rounds_completed, a.kind, detail))
            log.info(
                "chaos[%s] round %d: %s %s",
                self.scenario.name, rounds_completed, a.kind, detail,
            )

    def _apply(self, a: FaultAction) -> str:
        s, args = self.server, a.args
        if a.kind == "fail_next":
            s.fail_next(args.get("n", 1))
            return f"n={args.get('n', 1)}"
        if a.kind == "rate_limit_next":
            s.rate_limit_next(
                args.get("n", 1), args.get("retry_after_s", 0.02)
            )
            return f"n={args.get('n', 1)}"
        if a.kind == "disconnect_next":
            s.disconnect_next(args.get("n", 1))
            return f"n={args.get('n', 1)}"
        if a.kind == "delay_next":
            s.delay_next(args.get("n", 1), args.get("seconds", 0.5))
            return f"n={args.get('n', 1)} s={args.get('seconds', 0.5)}"
        if a.kind == "gone_next_watch":
            s.gone_next_watch(args.get("n", 1))
            return f"n={args.get('n', 1)}"
        if a.kind == "apply_then_disconnect_next":
            s.apply_then_disconnect_next(args.get("n", 1))
            return f"n={args.get('n', 1)}"
        if a.kind == "compact_watch_log":
            s.compact_watch_log()
            return ""
        if a.kind == "outage_begin":
            s.set_outage(
                True, writes_only=args.get("writes_only", False)
            )
            return "writes_only" if args.get("writes_only") else ""
        if a.kind == "outage_end":
            s.set_outage(False)
            return ""
        if a.kind == "node_storm":
            kill = args.get("kill", 1)
            with s._lock:
                alive = sorted(s.nodes)
            victims = self.rng.sample(alive, min(kill, len(alive)))
            for name in victims:
                s.drop_node(name)
            return f"killed={victims}"
        if a.kind == "pod_burst":
            n = args.get("n", 16)
            base = self.rng.randrange(1_000_000)
            for i in range(n):
                s.add_pod(
                    f"burst-{base}-{i:04d}",
                    cpu=args.get("cpu", "100m"),
                    memory=args.get("memory", "64Mi"),
                )
            return f"n={n}"
        raise AssertionError(a.kind)


def seed_cluster(
    server, nodes: int, pods: int, *, racks: int = 4, seed: int = 0,
    max_pods_per_node: int = 10,
) -> None:
    """Populate the fake apiserver with a deterministic cluster (all
    pods Pending; the daemon's first rounds place them)."""
    rng = random.Random(seed)
    names = [f"node-{i:03d}" for i in range(nodes)]
    for i, name in enumerate(names):
        server.add_node(
            name, rack=f"rack-{i % racks}", pods=max_pods_per_node
        )
    for i in range(pods):
        prefs = {}
        if rng.random() < 0.5:
            prefs = {rng.choice(names): rng.randrange(100, 500)}
        server.add_pod(
            f"pod-{i:04d}", cpu="100m", memory="64Mi",
            data_prefs=prefs or None,
        )


# ---------------------------------------------------------------------------
# the three acceptance scenarios (+ the CI composite)
# ---------------------------------------------------------------------------


def scenario_node_storm(
    *, seed: int = 0, nodes: int = 16, pods: int = 64,
    kill: int = 9, at_round: int = 4, rounds: int = 26,
) -> ChaosScenario:
    """Mass node loss vs the eviction guard: >50% of nodes die at
    once (poll mode — the guard holds the implausible shrink, accepts
    it at the strike/grace bound, and the displaced pods drain through
    the staged-requeue budget)."""
    # guard: 3 strikes to accept, then ceil(displaced/budget) staged
    # waves — the small budget forces a real multi-round drain
    return ChaosScenario(
        name="node_storm", seed=seed, nodes=nodes, pods=pods,
        actions=(FaultAction(at_round, "node_storm", {"kill": kill}),),
        rounds=rounds, fault_clear_round=at_round,
        recover_within=rounds - at_round - 1,
        flags=("--max_migrations_per_round=12",),
    )


def scenario_apiserver_outage(
    *, seed: int = 1, nodes: int = 12, pods: int = 36,
    begin: int = 1, duration: int = 6, rounds: int = 60,
) -> ChaosScenario:
    """A whole-control-plane outage window right as a round's binding
    POSTs go out: the outbox parks them, degraded=outage is declared,
    rounds keep running from last-known state, and recovery replays
    the outbox idempotently (exactly-once)."""
    return ChaosScenario(
        name="apiserver_outage", seed=seed, nodes=nodes, pods=pods,
        actions=(
            FaultAction(begin, "outage_begin"),
            FaultAction(begin + duration, "outage_end"),
        ),
        rounds=rounds, fault_clear_round=begin + duration,
        recover_within=rounds - begin - duration - 1,
        # pipelined: POSTs ride the overlap window, so the outage
        # window catches the staged POSTs exactly as decided
        flags=("--round_pipeline=true",),
    )


def scenario_overload_burst(
    *, seed: int = 2, nodes: int = 24, pods: int = 24,
    burst: int = 150, at_round: int = 2, rounds: int = 12,
) -> ChaosScenario:
    """An arrival burst plus a 429 throttle burst: the tick path must
    absorb the whole burst in a bounded number of certified rounds
    (placement is not budget-staged — only node-death re-queue is)
    while the client's retry path rides out the throttles."""
    return ChaosScenario(
        name="overload_burst", seed=seed, nodes=nodes, pods=pods,
        actions=(
            FaultAction(at_round, "pod_burst", {"n": burst}),
            FaultAction(at_round, "rate_limit_next",
                        {"n": 8, "retry_after_s": 0.02}),
        ),
        rounds=rounds, fault_clear_round=at_round + 1,
        recover_within=rounds - at_round - 2,
    )


def scenario_composite(
    *, seed: int = 3, nodes: int = 24, pods: int = 40,
    rounds: int = 90,
) -> ChaosScenario:
    """The CI smoke composite: an arrival burst whose binding POSTs
    ride straight into an apiserver outage window (outbox parks +
    replays, degraded=outage declared and cleared), then a >50% node
    storm (the mass-eviction guard holds, accepts, and the displaced
    pods drain through the staged-requeue budget), then a 429 +
    arrival burst — one daemon survives all three in sequence (poll
    mode: the guard is a snapshot defense, so the storm must arrive
    as a poll diff to exercise it)."""
    return ChaosScenario(
        name="composite", seed=seed, nodes=nodes, pods=pods,
        actions=(
            # burst decided at round ~9; its POSTs ride the next
            # tick's overlap window — exactly when the outage begins
            FaultAction(8, "pod_burst", {"n": 24}),
            FaultAction(9, "outage_begin"),
            FaultAction(16, "outage_end"),
            # 13 of 24 nodes (54%): over the guard threshold
            FaultAction(35, "node_storm", {"kill": 13}),
            FaultAction(55, "rate_limit_next",
                        {"n": 6, "retry_after_s": 0.02}),
            FaultAction(55, "pod_burst", {"n": 32}),
        ),
        rounds=rounds, fault_clear_round=56,
        recover_within=rounds - 57,
        flags=("--max_migrations_per_round=8",),
    )


SCENARIOS = {
    "node_storm": scenario_node_storm,
    "apiserver_outage": scenario_apiserver_outage,
    "overload_burst": scenario_overload_burst,
    "composite": scenario_composite,
}


# ---------------------------------------------------------------------------
# the daemon driver
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ScenarioRun:
    """One scenario's evidence bundle."""

    scenario: ChaosScenario
    exit_code: int
    stats: list[dict]
    trace_events: list
    applied: list[tuple[int, str, str]]
    server: object            # the (stopped) FakeApiServer
    stats_path: str = ""
    trace_path: str = ""


def run_daemon_scenario(
    scenario: ChaosScenario, workdir: str, *,
    polling_ms: float = 30.0, extra_flags: tuple[str, ...] = (),
) -> ScenarioRun:
    """Drive the REAL daemon loop (cli.run_loop) through one scenario
    against a fresh fake apiserver; returns the evidence bundle. The
    server is stopped (but its final state kept) before returning."""
    from poseidon_tpu.apiclient.fake_server import FakeApiServer
    from poseidon_tpu.cli import parse_args, run_loop
    from poseidon_tpu.trace import read_trace

    server = FakeApiServer().start()
    try:
        seed_cluster(
            server, scenario.nodes, scenario.pods, seed=scenario.seed
        )
        orch = ChaosOrchestrator(server, scenario)
        stats_path = os.path.join(
            workdir, f"{scenario.name}-stats.jsonl"
        )
        trace_path = os.path.join(
            workdir, f"{scenario.name}-trace.jsonl"
        )
        for path in (stats_path, trace_path):
            # the daemon appends; a re-run of the same scenario in
            # the same workdir (the bench's warm+counted passes) must
            # start from empty evidence files
            if os.path.exists(path):
                os.remove(path)
        argv = [
            f"--k8s_apiserver_port={server.port}",
            f"--polling_frequency={int(polling_ms * 1000)}",
            f"--max_rounds={scenario.rounds}",
            f"--stats_json={stats_path}",
            f"--trace_log={trace_path}",
            "--max_solver_runtime=30000000",
            *scenario.flags,
            *extra_flags,
        ]
        args = parse_args(argv)
        code = run_loop(args, round_hook=orch.on_round)
        server.apply_pending()
        stats = read_stats(stats_path)
        events = list(read_trace(trace_path))
        return ScenarioRun(
            scenario=scenario, exit_code=code, stats=stats,
            trace_events=events, applied=list(orch.applied),
            server=server, stats_path=stats_path,
            trace_path=trace_path,
        )
    finally:
        server.stop()


def read_stats(path: str) -> list[dict]:
    """The daemon's --stats_json lines (one SchedulerStats per round,
    file order = round order)."""
    out: list[dict] = []
    if not os.path.exists(path):
        return out
    with open(path) as fh:
        for line in fh:
            if line.strip():
                out.append(json.loads(line))
    return out


# ---------------------------------------------------------------------------
# invariants
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class InvariantReport:
    ok: bool
    failures: list[str]
    details: dict

    def assert_ok(self) -> None:
        assert self.ok, "; ".join(self.failures)


def rounds_to_recover(
    stats: list[dict], after_round: int
) -> int | None:
    """Rounds from ``after_round`` to the first FULLY recovered round
    (no pending, no unscheduled, no parked displacement) that is never
    followed by new scheduling pressure. None = never recovered."""
    recovered_at = None
    for row in stats:
        rn = row.get("round_num", 0)
        if rn <= after_round:
            continue
        settled = (
            row.get("pods_pending", 0) == 0
            and row.get("pods_unscheduled", 0) == 0
            and row.get("displaced_parked", 0) == 0
            and row.get("outbox_pending", 0) == 0
        )
        if settled and recovered_at is None:
            recovered_at = rn
        elif not settled:
            recovered_at = None  # pressure returned: not recovered yet
    if recovered_at is None:
        return None
    return recovered_at - after_round


def check_invariants(
    run: ScenarioRun, *,
    expect_guard: bool = False,
    guard_release_rounds: int | None = None,
) -> InvariantReport:
    """Machine-check the survival invariants over one scenario run."""
    failures: list[str] = []
    details: dict = {}
    server = run.server
    stats = run.stats

    if run.exit_code != 0:
        failures.append(f"daemon exited {run.exit_code}")

    # ---- exactly-once actuation (the apiserver's ordered op_log:
    # a pod may be re-bound only after an eviction or a node-death
    # orphaning put it back to Pending) ----
    bound: dict[str, str] = {}
    double_binds: list[str] = []
    for op, pod, node in server.op_log:
        if op == "bind":
            if pod in bound:
                double_binds.append(
                    f"{pod}: bound to {node} while bound to "
                    f"{bound[pod]}"
                )
            bound[pod] = node
        elif op in ("evict", "orphan"):
            bound.pop(pod, None)
    details["op_log_len"] = len(server.op_log)
    details["double_binds"] = double_binds
    if double_binds:
        failures.append(
            f"exactly-once violated: {double_binds[:5]} "
            f"(+{max(len(double_binds) - 5, 0)} more)"
        )

    # ---- zero lost pods: everything the apiserver knows ends
    # Running on a live node ----
    lost = []
    with server._lock:
        for key, doc in server.pods.items():
            phase = doc.get("status", {}).get("phase", "")
            node = doc.get("spec", {}).get("nodeName", "")
            if phase != "Running" or not node:
                lost.append(f"{key} ({phase or 'no phase'})")
            elif node not in server.nodes:
                lost.append(f"{key} (on dead node {node})")
    details["lost_pods"] = lost
    if lost:
        failures.append(
            f"{len(lost)} pod(s) not Running on a live node at end: "
            f"{lost[:5]}"
        )

    # ---- guard holds are always closed, accepted within the bound --
    holds: dict[str, int] = {}      # kind -> first-hold round
    releases: list[tuple[str, str, int]] = []
    open_holds: dict[str, int] = {}
    for ev in run.trace_events:
        if ev.event == "EVICTION_GUARD_HOLD":
            kind = (ev.detail or {}).get("kind", "?")
            holds.setdefault(kind, ev.round_num)
            open_holds.setdefault(kind, ev.round_num)
        elif ev.event == "EVICTION_GUARD_RELEASE":
            d = ev.detail or {}
            kind = d.get("kind", "?")
            releases.append((kind, d.get("outcome", "?"),
                             ev.round_num))
            first = open_holds.pop(kind, None)
            if (
                d.get("outcome") == "accepted"
                and guard_release_rounds is not None
                and first is not None
                and ev.round_num - first > guard_release_rounds
            ):
                failures.append(
                    f"guard {kind} released after "
                    f"{ev.round_num - first} rounds "
                    f"(bound {guard_release_rounds})"
                )
    details["guard_holds"] = holds
    details["guard_releases"] = releases
    if open_holds:
        failures.append(
            f"guard hold(s) never released: {open_holds}"
        )
    if expect_guard and not holds:
        failures.append(
            "expected the mass-eviction guard to hold, but it never "
            "fired"
        )
    if expect_guard and not any(
        o == "accepted" for _, o, _ in releases
    ):
        failures.append("guard never ACCEPTED the shrink")

    # ---- bounded recovery to a settled certified state ----
    rtr = rounds_to_recover(stats, run.scenario.fault_clear_round)
    details["rounds_to_recover"] = rtr
    if rtr is None:
        failures.append(
            f"never recovered after round "
            f"{run.scenario.fault_clear_round}"
        )
    elif rtr > run.scenario.recover_within:
        failures.append(
            f"recovery took {rtr} rounds "
            f"(bound {run.scenario.recover_within})"
        )

    # ---- no silent degrades: every solve kept its certificate ----
    degrades = max(
        (row.get("degrades_total", 0) for row in stats), default=0
    )
    details["degrades_total"] = degrades
    if degrades:
        failures.append(f"{degrades} dense-lane degrade(s) during run")

    return InvariantReport(
        ok=not failures, failures=failures, details=details
    )
