"""The solver front door: priced flow network in, placements-ready flows out.

This is the seam where the reference shells out to an external MCMF
binary per scheduling round (``--flow_scheduling_solver`` /
``--flow_scheduling_binary``, reference deploy/poseidon.cfg:8-10, invoked
from Firmament inside ``ScheduleAllJobs``, reference
src/firmament/scheduler_bridge.cc:170-172). Here the same seam dispatches
to the TPU dense-auction kernel, with two honest fallbacks:

- a graph that does not match the builder taxonomy (hand-written DIMACS,
  exotic topologies) cannot use the transportation form — it solves on
  the C++ CPU oracle instead;
- the auction certifies its own exactness (primal-dual gap < scale); if
  certification fails (adversarial tie structures can exhaust the round
  fuse), the solve re-runs on the oracle. No silent wrong answers.

The returned ``SolveOutcome.state`` is the device-resident warm handle:
pass it back as ``warm`` on the next round over the same cluster shape
and the solve skips the eps ladder entirely (measured at the BASELINE
flagship scale: ~10 ms warm vs ~100 ms cold vs ~270 ms oracle) — the
TPU-native equivalent of the reference's ``--run_incremental_scheduler``
mode (deploy/poseidon.cfg:12).
"""

from __future__ import annotations

import dataclasses
import logging
import time
import weakref

import numpy as np

from poseidon_tpu.graph.builder import GraphMeta
from poseidon_tpu.graph.network import FlowNetwork
from poseidon_tpu.ops.dense_auction import (
    CostDomainTooLarge,
    DenseMemoryTooLarge,
    DenseState,
    solve_transport_dense,
)
from poseidon_tpu.ops.transport import (
    NotSchedulingShaped,
    TransportInstance,
    TransportTopology,
    extract_topology,
    flows_from_assignment,
    instance_from_topology,
)

log = logging.getLogger(__name__)

# Small-instance dispatch thresholds. Below this size the ~ms-scale TPU
# per-launch dispatch floor exceeds the whole subprocess-oracle solve
# (PERF.md "config 1": a 100-task solve is ~1 round of real work but
# pays the full launch floor; measured crossover ~1k tasks, widening
# with machine count because the oracle's graph grows with M). The
# bounds are conservative — between them and the crossover the TPU path
# merely ties.
SMALL_INSTANCE_TASKS = 256
SMALL_INSTANCE_MACHINES = 64


def is_small_instance(n_tasks: int, n_machines: int) -> bool:
    """True when the subprocess oracle beats the TPU launch floor
    (shared by the front door and the resident solver)."""
    return (
        0 < n_tasks <= SMALL_INSTANCE_TASKS
        and n_machines <= SMALL_INSTANCE_MACHINES
    )


@dataclasses.dataclass(frozen=True)
class SolveOutcome:
    """Result of one scheduling solve, whatever backend produced it."""

    flows: np.ndarray        # int32 per-arc flows over the real arcs
    cost: int                # exact integer objective
    backend: str             # "dense_auction" | "oracle"
    exact: bool              # certified optimal (always True on return)
    solve_ms: float          # wall time of the successful solve
    state: DenseState | None  # warm handle for the next round (TPU path)
    instance: TransportInstance | None
    # per-task machine index (or -1) when the backend produced an
    # assignment directly — lets callers skip flow decomposition
    # entirely (the general path-peeling costs ~130 ms at the flagship
    # scale; the auction already knows every task's machine)
    assignment: np.ndarray | None = None


def assignment_from_outcome(
    outcome: SolveOutcome, meta: GraphMeta, net: FlowNetwork
) -> np.ndarray:
    """Per-task machine indices (-1 = unscheduled) for any outcome.

    This is the delta extractor's input (``graph.deltas
    .extract_deltas``): backends that assign directly (the dense
    auction) return it as-is; flow-only backends (oracle fallbacks, the
    general lane) decompose their flows into placements first.
    """
    if outcome.assignment is not None:
        return np.asarray(outcome.assignment, np.int32)
    from poseidon_tpu.graph.decompose import extract_placements

    host = net.to_host()
    placements = extract_placements(
        np.asarray(outcome.flows, np.int64), meta,
        host["src"], host["dst"],
    )
    midx = {name: i for i, name in enumerate(meta.machine_names)}
    asg = np.full(len(meta.task_uids), -1, np.int32)
    for i, uid in enumerate(meta.task_uids):
        m = placements.get(uid)
        if m is not None:
            asg[i] = midx[m]
    return asg


# Topology cache: repeated solves over the SAME GraphMeta object (what-
# if sweeps, bench reps, warm re-solves over an unchanged graph) skip
# the O(arcs) taxonomy re-validation — only the cost refill
# (``instance_from_topology``, pure vectorized numpy) runs per call.
# Keyed by id(meta) with a weakref finalizer so entries die with their
# meta (GraphMeta holds ndarrays and is not hashable).
_TOPO_CACHE: dict[int, TransportTopology] = {}


def _topology_for(net: FlowNetwork, meta: GraphMeta):
    """(topology, host arrays) for a priced net — cached per meta."""
    if int(net.n_arcs) != int(meta.n_arcs) or int(net.n_nodes) != int(
        meta.n_nodes
    ):
        raise NotSchedulingShaped(
            f"network ({net.n_nodes} nodes / {net.n_arcs} arcs) does "
            f"not match the builder metadata ({meta.n_nodes} / "
            f"{meta.n_arcs})"
        )
    host = net.to_host()
    cached = _TOPO_CACHE.get(id(meta))
    if cached is not None:
        # capacities live in the NET, not the meta: refill the
        # cap-derived fields from this call's arc table so a re-solve
        # over the same meta with changed caps is not answered from a
        # stale skeleton. The cheap parallel-cap consistency rule
        # (cluster->machine and rack->machine caps mirror the
        # machine->sink slots) is re-checked; a mismatch means the
        # caller mutated caps outside the taxonomy — fall through to
        # the full validating extraction (which raises).
        cap = np.asarray(host["cap"], np.int64)
        slots = cap[cached.arc_m2s].astype(np.int32)
        r2m_ok = cached.arc_r2m >= 0
        if (cap[cached.arc_c2m] == slots).all() and (
            cap[cached.arc_r2m[r2m_ok]] == slots[r2m_ok]
        ).all():
            return dataclasses.replace(
                cached,
                slots=slots,
                job_sink_cap=cap[cached.arc_job_sink],
            ), host
        _TOPO_CACHE.pop(id(meta), None)
    topo = extract_topology(meta, host["src"], host["dst"], host["cap"])
    _TOPO_CACHE[id(meta)] = topo
    weakref.finalize(meta, _TOPO_CACHE.pop, id(meta), None)
    return topo, host


def solve_scheduling(
    net: FlowNetwork,
    meta: GraphMeta,
    *,
    warm: DenseState | None = None,
    oracle_fallback: bool = True,
    oracle_timeout_s: float = 1000.0,
    small_to_oracle: bool = True,
    topology: TransportTopology | None = None,
) -> SolveOutcome:
    """Solve a priced scheduling network exactly; prefer the TPU kernel.

    ``warm`` is a previous round's ``SolveOutcome.state`` over the same
    padded shapes — prices and assignments carry over on-device and the
    solve re-settles at eps = 1 (the incremental path). Shape changes
    (cluster grew past a padding bucket) silently fall back to a cold
    solve.

    ``small_to_oracle`` lets the dispatcher route instances under the
    SMALL_INSTANCE_* thresholds straight to the subprocess oracle, where
    the TPU per-launch floor exceeds the whole CPU solve. Differential
    tests that specifically exercise the dense kernel pass False.

    ``topology`` (optional) is a pre-derived transport skeleton (e.g.
    ``topology_from_columns`` from the incremental builder) — passing
    it skips the O(arcs) taxonomy validation; repeated calls over the
    same ``meta`` object hit an internal topology cache either way.

    Error surface: with ``oracle_fallback=False``, kernel-envelope
    guards re-raise their typed exceptions (``CostDomainTooLarge``,
    ``DenseMemoryTooLarge``, ``ValueError``), while a solve that runs
    but cannot certify — the dense auction exhausting its round fuse,
    or the general-graph backend failing its guards — surfaces
    ``RuntimeError`` (NOT ``NotSchedulingShaped``: a non-taxonomy graph
    routes to the general JAX backend, not to an exception).
    """
    t0 = time.perf_counter()
    # size dispatch BEFORE extraction: meta alone names the instance
    # size, and paying even the (cheap) transportation extract on a
    # path whose whole point is "the oracle solves this faster than
    # any device overhead" would hand the comparison back
    if (
        small_to_oracle
        and oracle_fallback
        and warm is None
        and is_small_instance(
            len(meta.task_uids), len(meta.machine_names)
        )
    ):
        return _solve_on_oracle(
            net, t0, why="small-instance", timeout_s=oracle_timeout_s
        )
    try:
        if topology is not None:
            host = net.to_host()
        else:
            topology, host = _topology_for(net, meta)
        inst = instance_from_topology(topology, host["cost"])
    except NotSchedulingShaped:
        return _solve_general(
            net, t0, oracle_fallback=oracle_fallback,
            timeout_s=oracle_timeout_s,
        )

    try:
        res, state = solve_transport_dense(inst, warm=warm)
    except CostDomainTooLarge:
        if not oracle_fallback:
            raise
        return _solve_on_oracle(net, t0, why="cost-domain", timeout_s=oracle_timeout_s)
    except DenseMemoryTooLarge:
        # the [Tp, Mp] table would blow the HBM budget: degrade loudly
        # (the guard, not an OOM, decides) — same seam as cost-domain
        log.warning(
            "instance %dx%d exceeds the dense HBM budget; degrading "
            "to oracle", inst.n_tasks, inst.n_machines,
        )
        if not oracle_fallback:
            raise
        return _solve_on_oracle(net, t0, why="memory-envelope", timeout_s=oracle_timeout_s)
    except ValueError:
        # defensive: an instance outside the kernel's envelope (e.g.
        # negative costs from a custom model) must degrade, not crash —
        # but loudly, so a masked kernel regression stays discoverable
        log.exception(
            "dense kernel rejected the instance; degrading to oracle"
        )
        if not oracle_fallback:
            raise
        return _solve_on_oracle(net, t0, why="kernel-envelope", timeout_s=oracle_timeout_s)
    if not res.converged and warm is not None:
        # a stale warm start can strand the eps=1 settle; retry cold
        res, state = solve_transport_dense(inst, warm=None)
    if res.converged:
        flows = flows_from_assignment(inst, res, int(net.n_arcs))
        return SolveOutcome(
            flows=flows,
            cost=res.cost,
            backend="dense_auction",
            exact=True,
            solve_ms=(time.perf_counter() - t0) * 1000,
            state=state,
            instance=inst,
            assignment=res.assignment,
        )
    if not oracle_fallback:
        raise RuntimeError(
            f"dense auction did not certify (gap still open after "
            f"{res.rounds} rounds) and oracle fallback is disabled"
        )
    return _solve_on_oracle(net, t0, why="uncertified", timeout_s=oracle_timeout_s)


def _solve_general(
    net: FlowNetwork, t0: float, *, oracle_fallback: bool,
    timeout_s: float,
) -> SolveOutcome:
    """Non-taxonomy graphs (hand-written DIMACS, exotic topologies):
    the exact general-graph JAX backend (``ops/cost_scaling``, the
    device-side cs2 analog), with the C++ oracle only on its guards —
    the int32 excess-wrap precheck, a blown sweep fuse, or an instance
    the forcing-arc construction reports capacity-infeasible. The
    reference solves every graph through the same external-solver seam
    (scheduler_bridge.cc:170-172); this is that seam's general lane.
    """
    import jax

    from poseidon_tpu.ops.cost_scaling import (
        solve_cost_scaling,
        solution_cost,
    )

    guard_err: ValueError | None = None
    try:
        res = solve_cost_scaling(net)
        conv, feas = jax.device_get((res.converged, res.feasible))
        if bool(conv) and bool(feas):
            return SolveOutcome(
                flows=np.asarray(jax.device_get(res.flows), np.int32),
                cost=solution_cost(net, res),
                backend="cost_scaling",
                exact=True,
                solve_ms=(time.perf_counter() - t0) * 1000,
                state=None,
                instance=None,
            )
        why = "general-unconverged" if not bool(conv) else "general-infeasible"
    except ValueError as e:
        # the excess-wrap precheck (capacities too large for the int32
        # accumulators) — a documented guard, not a kernel bug
        log.warning("general JAX backend rejected the graph: %s", e)
        guard_err = e
        why = "general-guard"
    if not oracle_fallback:
        # chain the guard's ValueError so the RuntimeError's traceback
        # names WHICH precheck tripped (ADVICE round 5)
        raise RuntimeError(
            f"general JAX solve failed ({why}) and oracle fallback is "
            f"disabled"
        ) from guard_err
    return _solve_on_oracle(net, t0, why=why, timeout_s=timeout_s)


def _solve_on_oracle(
    net: FlowNetwork, t0: float, why: str, timeout_s: float = 1000.0
) -> SolveOutcome:
    from poseidon_tpu.oracle import solve_oracle

    o = solve_oracle(net, algorithm="cost_scaling", timeout_s=timeout_s)
    return SolveOutcome(
        flows=np.asarray(o.flows, np.int32),
        cost=int(o.cost),
        backend=f"oracle:{why}",
        exact=True,
        solve_ms=(time.perf_counter() - t0) * 1000,
        state=None,
        instance=None,
    )
