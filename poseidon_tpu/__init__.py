"""poseidon_tpu — a TPU-native cluster flow-scheduling framework.

Re-implements the capability surface of Poseidon (Firmament's Kubernetes
integration; reference: /root/reference, see SURVEY.md) as a from-scratch
JAX/XLA framework: the cluster is modeled as a min-cost max-flow problem
whose arc/node tables live as padded device arrays and whose solve runs as
a jit-compiled cost-scaling kernel on TPU, instead of the reference's
fork/exec of a CPU solver binary (reference deploy/poseidon.cfg:8-10).

Layers (SURVEY.md section 7):
  graph/     L0  — structure-of-arrays flow network, builder, DIMACS I/O
  oracle/    L2a'— C++ CPU MCMF oracle (correctness + baseline)
  ops/       L1  — JAX solver kernels (SSP, cost-scaling push-relabel)
  models/    L3' — vectorized cost models (Trivial, Quincy, CoCo, Whare-Map)
  bridge/    L4' — scheduler bridge + pod state machine
  apiclient/ L2b'— Kubernetes API client + fake apiserver fixture
  parallel/       — device mesh / shard_map solver partitioning
"""

__version__ = "0.1.0"
