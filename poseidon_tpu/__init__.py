"""poseidon_tpu — a TPU-native cluster flow-scheduling framework.

Re-implements the capability surface of Poseidon (Firmament's Kubernetes
integration; reference: /root/reference, see SURVEY.md) as a from-scratch
JAX/XLA framework: the cluster is modeled as a min-cost max-flow problem
whose tables live as padded device arrays and whose solve runs as one
jit-compiled dense-auction kernel on TPU, instead of the reference's
fork/exec of a CPU solver binary (reference deploy/poseidon.cfg:8-10).

Layers (SURVEY.md section 7):
  graph/     L0  — structure-of-arrays flow network, builder, DIMACS I/O
  oracle/    L2a'— C++ CPU MCMF oracle (correctness + baseline)
  ops/       L1  — JAX solver kernels (dense auction, SSP, cost-scaling,
                   vmap what-if batching)
  models/    L3' — vectorized cost models (Trivial, Quincy, CoCo,
                   Whare-Map, Octopus) + KnowledgeBase sample rings
  parallel/      — device-mesh sharding (NamedSharding / shard_map+psum)
  solver.py      — the front door: solve_scheduling() with warm handles
  bridge/    L4' — scheduler bridge: pod/node state machine, stats,
                   decision log, restart reconcile
  apiclient/ L2b'— Kubernetes API client + fake apiserver fixture
  cli.py     L5' — the scheduling daemon (poll loop, reference flags)
"""

from poseidon_tpu.solver import SolveOutcome, solve_scheduling

__version__ = "0.4.0"

__all__ = ["SolveOutcome", "solve_scheduling", "__version__"]
