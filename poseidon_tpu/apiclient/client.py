"""Kubernetes core-v1 REST client (the reference's five-operation surface).

Mirrors src/apiclient/k8s_api_client.{h,cc}: GET ``nodes`` / ``pods``
(optionally label-filtered) parsed into the framework's ``Machine`` /
``Task`` DTOs, and the bindings POST that makes placements real
(k8s_api_client.cc:67-94, JSON shape at :75-79). Differences on purpose:

- unit parsing is correct for the full k8s quantity grammar (m-suffixed
  CPU, Ki/Mi/Gi/K/M/G memory) instead of the reference's "strip the last
  two characters and hope it was Ki" (k8s_api_client.cc:260-265) — a
  noted fidelity gap (SURVEY §3.4);
- the namespace comes from the pod instead of being hardcoded
  ``default`` (k8s_api_client.cc:222);
- transport errors raise ``ApiError`` after bounded retries instead of
  dissolving into logged JSON (utils.cc:47-61); the driver loop decides
  to skip the tick. Retries use jittered exponential backoff and apply
  only to failures that CAN heal (429, 5xx, transport/decode errors);
  a 404/400 fails fast — re-asking the same question three times just
  delays the inevitable and hammers a struggling apiserver. A 429's
  ``Retry-After`` header is honored as a lower bound on the delay;
- list pagination is followed (``metadata.continue`` tokens, chunked via
  ``limit``). The reference does one unpaginated GET and parses whatever
  came back (k8s_api_client.cc:100-160); against an apiserver that
  chunks its responses that silently drops every item after the first
  page — and a dropped page reads as "those pods/nodes are gone", which
  would mass-evict scheduler state. A page fetch that still fails after
  retries raises instead of returning the partial list for the same
  reason.

Transport is stdlib urllib on purpose: the control plane is a few small
JSON GETs per 10-second tick (deploy/poseidon.cfg / --polling_frequency),
three orders of magnitude off the solve path.
"""

from __future__ import annotations

import http.client
import json
import logging
import random
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Callable

from poseidon_tpu.cluster import Machine, Task, TaskPhase

log = logging.getLogger(__name__)

RACK_LABELS = (
    "topology.kubernetes.io/zone",
    "failure-domain.beta.kubernetes.io/zone",
    "rack",
)


class ApiError(RuntimeError):
    """The apiserver could not be reached or answered garbage.

    ``code`` carries the HTTP status when one was received (0 for
    transport-level failures), so callers can branch on protocol
    answers — 404 pod-gone in ``get_pod``, 409 binding-conflict in
    ``bind_pod_to_node``, 409 lease-held in ``acquire_lease`` —
    without parsing the message string.
    """

    def __init__(self, msg: str, code: int = 0):
        super().__init__(msg)
        self.code = code


def backoff_delay(
    attempt: int,
    *,
    base_s: float = 0.05,
    cap_s: float = 2.0,
    rng: Callable[[], float] = random.random,
) -> float:
    """Jittered exponential backoff: ``min(cap, base·2^attempt)``
    scaled by a uniform [0.5, 1.5) jitter factor.

    The jitter matters operationally: a fleet of schedulers whose
    apiserver hiccuped would otherwise all retry on the same metronome
    and re-create the thundering herd that caused the hiccup. Shared by
    the request retry loop here and the watch-stream reconnects
    (apiclient/watch.py).
    """
    return min(cap_s, base_s * (2.0 ** attempt)) * (0.5 + rng())


# retry-stat classes: every retried failure is attributed to exactly
# one of these, so "the apiserver is hanging" (timeout) reads
# differently from "the apiserver is erroring" (5xx) in the stats —
# a hung apiserver is the common real-world outage shape and the two
# need different operator responses (socket timeouts vs error budgets)
RETRY_CLASSES = ("5xx", "429", "timeout", "transport", "decode")


def _failure_class(e: Exception) -> str:
    """Attribute one retried failure to a RETRY_CLASSES bucket."""
    if isinstance(e, urllib.error.HTTPError):
        return "429" if e.code == 429 else "5xx"
    if isinstance(e, json.JSONDecodeError):
        return "decode"
    # socket timeouts surface either bare (http.client reads) or
    # wrapped in URLError(reason=timeout) (urlopen connects)
    if isinstance(e, TimeoutError):
        return "timeout"
    if isinstance(e, urllib.error.URLError) and isinstance(
        getattr(e, "reason", None), TimeoutError
    ):
        return "timeout"
    return "transport"


def _wire_failure(code: int) -> bool:
    """True when an ApiError's code means the WIRE (not the request)
    is the problem: transport-level (0), throttled past the retry
    budget (429), or server-side trouble (5xx). The single source of
    the outage ladder's unreachable-vs-rejected split — bind and
    evict must never disagree on it."""
    return code == 0 or code == 429 or code >= 500


def parse_cpu(q: str | int | float) -> float:
    """k8s CPU quantity -> cores ("100m" -> 0.1, "2" -> 2.0)."""
    if isinstance(q, (int, float)):
        return float(q)
    q = q.strip()
    if not q:
        return 0.0
    if q.endswith("m"):
        return float(q[:-1]) / 1000.0
    return float(q)


_MEM_FACTORS = {"Ki": 1, "Mi": 1 << 10, "Gi": 1 << 20, "Ti": 1 << 30}


def parse_memory_kb(q: str | int) -> int:
    """k8s memory quantity -> KiB ("128Mi" -> 131072, "1Gi" -> 1048576,
    plain integers are bytes)."""
    if isinstance(q, int):
        return q >> 10
    q = q.strip()
    if not q:
        return 0
    for suffix in ("Ki", "Mi", "Gi", "Ti"):
        if q.endswith(suffix):
            return int(float(q[: -len(suffix)]) * _MEM_FACTORS[suffix])
    for suffix, f in (("T", 976562500), ("G", 976563), ("M", 977),
                      ("k", 1), ("K", 1)):
        if q.endswith(suffix):
            return int(float(q[:-1]) * f)
    return int(q) >> 10  # bare bytes


class K8sApiClient:
    """Five operations against one base URI (k8s_api_client.h:44-48)."""

    def __init__(
        self,
        host: str = "localhost",
        port: int = 8080,
        api_version: str = "v1",
        *,
        timeout_s: float = 10.0,
        retries: int = 2,
        page_limit: int = 500,
        backoff_base_s: float = 0.05,
        backoff_cap_s: float = 2.0,
    ):
        self.base = f"http://{host}:{port}/api/{api_version}"
        self.timeout_s = timeout_s
        self.retries = retries
        self.page_limit = page_limit
        self.backoff_base_s = backoff_base_s
        self.backoff_cap_s = backoff_cap_s
        # per-class retried-failure counts (RETRY_CLASSES); requests
        # run concurrently from the binding-POST pool, so increments
        # hold a lock. A hung apiserver ("timeout") is counted
        # distinctly from an erroring one ("5xx").
        self.retry_stats: dict[str, int] = dict.fromkeys(
            RETRY_CLASSES, 0
        )
        self._stats_lock = threading.Lock()
        log.info("k8s api client -> %s", self.base)

    # ---- transport -----------------------------------------------------

    def _count_failure(self, e: Exception) -> None:
        with self._stats_lock:
            self.retry_stats[_failure_class(e)] += 1

    def _request(
        self, path: str, body: dict | None = None,
        method: str | None = None,
    ) -> dict:
        url = f"{self.base}/{path}"
        data = None
        headers = {}
        if body is not None:
            data = json.dumps(body).encode()
            headers["Content-Type"] = "application/json"
        last: Exception | None = None
        for attempt in range(self.retries + 1):
            retry_after = ""
            try:
                req = urllib.request.Request(
                    url, data=data, headers=headers, method=method
                )
                with urllib.request.urlopen(
                    req, timeout=self.timeout_s
                ) as resp:
                    payload = resp.read()
                return json.loads(payload) if payload else {}
            except urllib.error.HTTPError as e:
                # checked BEFORE the transport clause: HTTPError is an
                # OSError, and retrying a 404/400 just burns every
                # attempt on an answer that will not change. Only 429
                # (throttled) and 5xx (server-side trouble) can heal.
                if e.code != 429 and e.code < 500:
                    raise ApiError(
                        f"{url}: HTTP {e.code}", code=e.code
                    ) from e
                if e.code == 429:
                    retry_after = e.headers.get("Retry-After", "")
                last = e
                self._count_failure(e)
            except (
                OSError,
                http.client.HTTPException,
                json.JSONDecodeError,
            ) as e:
                # OSError covers URLError, TimeoutError AND the raw
                # socket errors (ConnectionResetError) that surface
                # under concurrent bindings POSTs mid-body-read;
                # HTTPException covers IncompleteRead when the server
                # drops the connection mid-body. A socket timeout (the
                # hung-apiserver case) is attributed to its own retry-
                # stat class, distinct from 5xx/transport.
                last = e
                self._count_failure(e)
            if attempt < self.retries:
                delay = backoff_delay(
                    attempt,
                    base_s=self.backoff_base_s,
                    cap_s=self.backoff_cap_s,
                )
                if retry_after:
                    try:
                        delay = max(delay, float(retry_after))
                    except ValueError:
                        pass  # HTTP-date form: keep the jittered delay
                time.sleep(delay)
        raise ApiError(
            f"{url}: {last}",
            code=last.code
            if isinstance(last, urllib.error.HTTPError) else 0,
        ) from last

    def _list(self, resource: str, selector: str = "") -> list[dict]:
        return self._list_rv(resource, selector)[0]

    def _list_rv(
        self, resource: str, selector: str = ""
    ) -> tuple[list[dict], int]:
        """Chunked list: follow ``metadata.continue`` until exhausted.
        Returns ``(items, resourceVersion)`` — the rv is the watch
        protocol's starting point (apiclient/watch.py).

        All pages of one logical list are fetched before parsing; a page
        failure (after per-request retries) raises so the caller never
        sees a silently truncated snapshot — the bridge would read the
        missing tail as mass deletion.
        """
        items: list[dict] = []
        token = ""
        rv = 0
        # bounded like every other failure mode in this client: a server
        # that replays the same continue token (or pages forever) must
        # surface as a skipped tick, not a silent daemon hang
        max_pages = 10_000
        for _ in range(max_pages):
            params: dict[str, str] = {}
            if selector:
                params["labelSelector"] = selector
            if self.page_limit > 0:
                params["limit"] = str(self.page_limit)
            if token:
                params["continue"] = token
            path = resource
            if params:
                path += "?" + urllib.parse.urlencode(params)
            doc = self._request(path)
            items.extend(doc.get("items", []))
            meta = doc.get("metadata", {})
            try:
                rv = int(meta.get("resourceVersion", rv) or rv)
            except (TypeError, ValueError):
                pass  # apiservers may use opaque rvs; watch needs ints
            next_token = meta.get("continue", "") or ""
            if not next_token:
                return items, rv
            if next_token == token:
                raise ApiError(
                    f"{resource}: apiserver replayed continue token "
                    f"{token!r}"
                )
            token = next_token
        raise ApiError(f"{resource}: pagination exceeded {max_pages} pages")

    # ---- nodes ---------------------------------------------------------

    def nodes_with_label(self, selector: str = "") -> list[Machine]:
        out = []
        for item in self._list("nodes", selector):
            try:
                out.append(self._parse_node(item))
            except (KeyError, ValueError) as e:
                log.error("skipping unparseable node: %s", e)
        return out

    def all_nodes(self) -> list[Machine]:
        return self.nodes_with_label("")

    def nodes_with_rv(self) -> tuple[list[Machine], int]:
        """Full node list plus the list's ``resourceVersion`` — the
        snapshot+rv pair a watch stream continues from."""
        items, rv = self._list_rv("nodes")
        out = []
        for item in items:
            try:
                out.append(self._parse_node(item))
            except (KeyError, ValueError) as e:
                log.error("skipping unparseable node: %s", e)
        return out, rv

    @staticmethod
    def _parse_node(item: dict) -> Machine:
        meta = item["metadata"]
        status = item.get("status", {})
        cap = status.get("capacity", {})
        alloc = status.get("allocatable", cap)
        labels = meta.get("labels", {})
        rack = ""
        for key in RACK_LABELS:
            if key in labels:
                rack = labels[key]
                break
        return Machine(
            name=meta["name"],
            cpu_capacity=parse_cpu(cap.get("cpu", "0")),
            cpu_allocatable=parse_cpu(alloc.get("cpu", "0")),
            memory_capacity_kb=parse_memory_kb(cap.get("memory", "0")),
            memory_allocatable_kb=parse_memory_kb(
                alloc.get("memory", "0")
            ),
            rack=rack,
            max_tasks=int(cap.get("pods", 0) or 0),
        )

    # ---- pods ----------------------------------------------------------

    def pods_with_label(self, selector: str = "") -> list[Task]:
        out = []
        for item in self._list("pods", selector):
            try:
                out.append(self._parse_pod(item))
            except (KeyError, ValueError) as e:
                log.error("skipping unparseable pod: %s", e)
        return out

    def all_pods(self) -> list[Task]:
        return self.pods_with_label("")

    def pods_with_rv(self) -> tuple[list[Task], int]:
        """Full pod list plus the list's ``resourceVersion``."""
        items, rv = self._list_rv("pods")
        out = []
        for item in items:
            try:
                out.append(self._parse_pod(item))
            except (KeyError, ValueError) as e:
                log.error("skipping unparseable pod: %s", e)
        return out, rv

    @staticmethod
    def _parse_pod(item: dict) -> Task:
        meta = item["metadata"]
        spec = item.get("spec", {})
        status = item.get("status", {})
        cpu = 0.0
        mem_kb = 0
        for c in spec.get("containers", []):
            req = c.get("resources", {}).get("requests", {})
            cpu += parse_cpu(req.get("cpu", "0"))
            mem_kb += parse_memory_kb(req.get("memory", "0"))
        annotations = meta.get("annotations", {})
        prefs: dict[str, int] = {}
        raw_prefs = annotations.get("poseidon.io/data-prefs", "")
        if raw_prefs:
            try:
                prefs = {
                    k: int(v) for k, v in json.loads(raw_prefs).items()
                }
            except (json.JSONDecodeError, ValueError):
                log.error("bad data-prefs annotation on %s", meta["name"])
        phase_raw = status.get("phase", "Unknown")
        try:
            phase = TaskPhase(phase_raw)
        except ValueError:
            phase = TaskPhase.UNKNOWN
        ns = meta.get("namespace", "default")
        job = meta.get("labels", {}).get("job-name", "")
        return Task(
            # namespace-qualified: pod (and job) names are only unique
            # per namespace; the bridge keys all state by uid, and the
            # graph builder buckets tasks by job_id — an unqualified
            # job label would merge same-named jobs across namespaces
            # into one unscheduled aggregator
            uid=f"{ns}/{meta['name']}",
            namespace=ns,
            job=f"{ns}/{job}" if job else "",
            cpu_request=cpu,
            memory_request_kb=mem_kb,
            phase=phase,
            machine=spec.get("nodeName", "") or "",
            data_prefs=prefs,
        )

    def get_pod(
        self, pod: str, namespace: str = "default"
    ) -> Task | None:
        """One pod's current state, or None when it no longer exists.

        The idempotency primitive: the binding-conflict check and the
        actuation-journal replay (ha/journal.py) both decide "has this
        op's effect already landed" from the answer. ``pod`` accepts
        the same bare-or-qualified forms as ``bind_pod_to_node``.
        """
        if "/" in pod:
            namespace, pod = pod.split("/", 1)
        try:
            doc = self._request(f"namespaces/{namespace}/pods/{pod}")
        except ApiError as e:
            if e.code == 404:
                return None
            raise
        try:
            return self._parse_pod(doc)
        except (KeyError, ValueError) as e:
            raise ApiError(f"unparseable pod {namespace}/{pod}: {e}")

    # ---- bindings ------------------------------------------------------

    def bind_pod_to_node(
        self, pod: str, node: str, namespace: str = "default"
    ) -> bool:
        """POST the binding that makes a placement real
        (k8s_api_client.cc:67-94; body shape at :75-79).

        ``pod`` may be a bare pod name (with ``namespace`` naming its
        namespace) or a qualified ``"ns/name"`` uid as produced by
        ``_parse_pod`` — the qualifier then wins over ``namespace``.
        """
        return self.bind_outcome(pod, node, namespace) == "ok"

    def bind_outcome(
        self, pod: str, node: str, namespace: str = "default"
    ) -> str:
        """Outcome-classified binding POST — the actuation-outbox
        seam (ha/outbox.py). Returns one of:

        - ``"ok"``: the binding landed (or a 409 Conflict whose
          existing binding targets the SAME node — a duplicate of an
          op that already landed: a retried request, a journal replay
          after a crash, a restarted daemon re-actuating. Counting it
          as failed would inflate bind_failures and age/re-queue a
          pod the apiserver already placed exactly where we asked);
        - ``"rejected"``: the apiserver answered and said no (404
          pod/node gone, 409 bound elsewhere, 4xx) — retrying the
          same POST cannot heal it, the pod must be re-queued;
        - ``"unreachable"``: the apiserver could not be reached or
          kept erroring (transport, timeout, 5xx/429 exhausted) —
          the *wire* is the problem, not the decision, so the op
          belongs in the outbox, not back in the solver.
        """
        if "/" in pod:
            namespace, pod = pod.split("/", 1)
        body = {
            "apiVersion": "v1",
            "kind": "Binding",
            "metadata": {"name": pod},
            "target": {
                "apiVersion": "v1", "kind": "Node", "name": node,
            },
        }
        try:
            self._request(f"namespaces/{namespace}/bindings", body)
            return "ok"
        except ApiError as e:
            if e.code == 409:
                try:
                    cur = self.get_pod(pod, namespace=namespace)
                except ApiError:
                    cur = None
                if cur is not None and cur.machine == node:
                    log.info(
                        "binding %s -> %s already exists; counting "
                        "the duplicate POST as success", pod, node,
                    )
                    return "ok"
            log.error("binding %s -> %s failed: %s", pod, node, e)
            return "unreachable" if _wire_failure(e.code) \
                else "rejected"

    # ---- evictions -----------------------------------------------------

    def evict_pod(self, pod: str, namespace: str = "default") -> bool:
        """POST the Eviction subresource that unbinds a running pod.

        The actuation half of the rebalancing deltas the reference
        never implemented: MIGRATE = evict_pod + bind_pod_to_node,
        PREEMPT = evict_pod alone (the pod parks Pending and is
        re-offered with its aging preserved). ``pod`` accepts the same
        bare-or-qualified forms as ``bind_pod_to_node``.
        """
        return self.evict_outcome(pod, namespace) == "ok"

    def evict_outcome(
        self, pod: str, namespace: str = "default"
    ) -> str:
        """Outcome-classified eviction POST; the same
        ok / rejected / unreachable vocabulary as ``bind_outcome``."""
        if "/" in pod:
            namespace, pod = pod.split("/", 1)
        body = {
            "apiVersion": "policy/v1",
            "kind": "Eviction",
            "metadata": {"name": pod, "namespace": namespace},
        }
        try:
            self._request(
                f"namespaces/{namespace}/pods/{pod}/eviction", body
            )
            return "ok"
        except ApiError as e:
            log.error("eviction of %s failed: %s", pod, e)
            return "unreachable" if _wire_failure(e.code) \
                else "rejected"

    # ---- leases (HA leader election, ha/standby.py) --------------------

    def acquire_lease(
        self,
        name: str,
        identity: str,
        duration_s: float,
        namespace: str = "kube-system",
    ) -> bool:
        """PUT the Lease; True = granted (free, expired, or already
        ours — an acquire doubles as a renew). False = held by someone
        else (HTTP 409). Transport failures raise like any request."""
        body = {
            "apiVersion": "coordination.k8s.io/v1",
            "kind": "Lease",
            "metadata": {"name": name, "namespace": namespace},
            "spec": {
                "holderIdentity": identity,
                "leaseDurationSeconds": duration_s,
            },
        }
        try:
            self._request(
                f"namespaces/{namespace}/leases/{name}", body,
                method="PUT",
            )
            return True
        except ApiError as e:
            if e.code == 409:
                return False
            raise

    def read_lease(
        self, name: str, namespace: str = "kube-system"
    ) -> dict | None:
        try:
            return self._request(f"namespaces/{namespace}/leases/{name}")
        except ApiError as e:
            if e.code == 404:
                return None
            raise

    def release_lease(
        self, name: str, identity: str,
        namespace: str = "kube-system",
    ) -> None:
        """DELETE the Lease (clean step-down); a 404/409 (already gone
        / stolen) is not an error worth failing shutdown over."""
        try:
            self._request(
                f"namespaces/{namespace}/leases/{name}"
                f"?holderIdentity={urllib.parse.quote(identity)}",
                method="DELETE",
            )
        except ApiError as e:
            if e.code not in (404, 409):
                raise
