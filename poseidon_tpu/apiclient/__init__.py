"""L2b' — Kubernetes API client + fake apiserver test fixture."""

from poseidon_tpu.apiclient.client import K8sApiClient, parse_cpu, parse_memory_kb
from poseidon_tpu.apiclient.fake_server import FakeApiServer

__all__ = ["K8sApiClient", "FakeApiServer", "parse_cpu", "parse_memory_kb"]
