"""L2b' — Kubernetes API client, watch subsystem + fake apiserver fixture."""

from poseidon_tpu.apiclient.client import K8sApiClient, parse_cpu, parse_memory_kb
from poseidon_tpu.apiclient.fake_server import FakeApiServer
from poseidon_tpu.apiclient.watch import (
    ClusterWatcher,
    ExpressEvents,
    ObserveDelta,
)

__all__ = [
    "K8sApiClient",
    "FakeApiServer",
    "ClusterWatcher",
    "ExpressEvents",
    "ObserveDelta",
    "parse_cpu",
    "parse_memory_kb",
]
