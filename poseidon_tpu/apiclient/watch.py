"""Event-driven cluster observation: the Kubernetes watch protocol.

The reference polls the apiserver with full ``GET /nodes`` + ``GET
/pods`` lists every tick (k8s_api_client.cc:100-209) and re-diffs the
whole cluster — O(cluster) host work per round even when nothing
changed. The real control plane's answer is the watch protocol, and
Firmament itself is built around incremental cluster-state deltas; this
module closes that gap:

- ``ClusterWatcher.sync()`` does ONE paginated LIST per resource to
  seed a snapshot plus its ``resourceVersion``;
- two long-lived chunked watch streams (nodes, pods) then deliver typed
  ``ADDED | MODIFIED | DELETED | BOOKMARK`` events from that rv, each
  stream tracking its own rv so a reconnect resumes exactly where it
  left off (``?watch=true&resourceVersion=N`` returns events with
  rv > N — at-most-once delivery by construction, and ``tick()``
  re-checks ``rv <= applied`` so a replaying server cannot double-apply
  either);
- streams reconnect with jittered exponential backoff
  (``client.backoff_delay``) after transport errors; clean server-side
  closes (idle bookmark + EOF) resume immediately;
- the watcher **degrades loudly to a full LIST resync** — never guesses
  — on ``410 Gone`` (either HTTP shape), an undecodable event, or a
  staleness bound (no stream activity for ``max_lag_s``, the cli's
  ``--watch_max_lag``). Every resync and every error-path reconnect is
  emitted as a ``WATCH_RESYNC`` / ``WATCH_RECONNECT`` trace event and
  surfaced in ``ObserveDelta`` so the bridge counts them in
  ``SchedulerStats``.

Threading model: one daemon reader thread per stream blocks on the
HTTP response and pushes decoded items into a per-stream queue; all
state mutation (rv accounting, resync decisions, object parsing, trace
emission) happens on the caller's thread inside ``tick()``, so the
bridge — which is not thread-safe — only ever sees events from its own
driver loop. ``tick()`` never blocks on the network except during a
resync's LISTs.

The consumer contract (cli.py, tests/test_watch.py):

    delta = watcher.tick()
    if delta.resynced:            # seed, 410, decode error, staleness
        bridge.observe_nodes(delta.nodes)     # snapshot diff path —
        bridge.observe_pods(delta.pods)       # mass-eviction guard on
    else:
        for typ, m in delta.node_events:
            bridge.observe_node_event(typ, m)
        for typ, t in delta.pod_events:
            bridge.observe_pod_event(typ, t)
    bridge.note_watch_activity(delta.resyncs, delta.reconnects)
"""

from __future__ import annotations

import dataclasses
import http.client
import json
import logging
import queue
import threading
import time
import urllib.error
import urllib.parse
import urllib.request

from poseidon_tpu.apiclient.client import (
    ApiError,
    K8sApiClient,
    backoff_delay,
)
from poseidon_tpu.cluster import Machine, Task
from poseidon_tpu.trace import TraceGenerator

log = logging.getLogger(__name__)

RESOURCES = ("nodes", "pods")


class WatchGone(Exception):
    """The apiserver no longer holds history for the requested rv."""


@dataclasses.dataclass
class ObserveDelta:
    """One ``tick()``'s worth of cluster observation.

    Either a full snapshot (``resynced=True``: consume ``nodes`` /
    ``pods`` through the bridge's snapshot-diff path) or incremental
    typed events (``node_events`` / ``pod_events`` as ``(type, obj)``
    pairs, type in ADDED|MODIFIED|DELETED). ``resyncs`` / ``reconnects``
    are this tick's degradation counts for ``SchedulerStats``.
    """

    resynced: bool = False
    nodes: list[Machine] = dataclasses.field(default_factory=list)
    pods: list[Task] = dataclasses.field(default_factory=list)
    node_events: list[tuple[str, Machine]] = dataclasses.field(
        default_factory=list)
    pod_events: list[tuple[str, Task]] = dataclasses.field(
        default_factory=list)
    resyncs: int = 0
    reconnects: int = 0


@dataclasses.dataclass
class ExpressEvents:
    """One ``express_poll()``'s worth of between-tick pod events.

    ``pod_events`` are typed ``(type, Task)`` pairs exactly like
    ``ObserveDelta.pod_events`` — the express driver feeds them to
    ``SchedulerBridge.express_batch``. ``t_first`` is the
    ``perf_counter`` stamp at which the first event was dequeued (the
    event-to-bind latency clock's zero); ``t_events`` carries one such
    dequeue stamp PER event (parallel to ``pod_events``), so the bind
    path can report a real per-event latency sample instead of
    replicating the batch's. ``needs_tick=True`` means
    something the express lane must not handle arrived (node events, a
    410/decode degradation, an un-seeded watcher): the driver should
    fall through to a full observe tick, where the normal resync /
    snapshot-diff guards apply.
    """

    pod_events: list[tuple[str, Task]] = dataclasses.field(
        default_factory=list)
    t_first: float = 0.0
    t_events: list[float] = dataclasses.field(default_factory=list)
    reconnects: int = 0
    needs_tick: bool = False
    # overload backpressure: the pods stream's queue depth exceeded
    # the shed threshold — the express lane stepped aside so the full
    # round (which handles arbitrarily large batches in one solve)
    # absorbs the burst instead of the per-batch fast path grinding
    # through it event by event. Counted loudly by the driver.
    shed: bool = False


class _WatchStream(threading.Thread):
    """One resource's watch connection, kept alive across reconnects.

    Pushes ``("EVENT", rv, type, raw_object)`` / ``("BOOKMARK", rv)`` /
    ``("RECONNECT", reason)`` / ``("GONE", reason)`` items into
    ``self.queue``. After GONE the thread exits — only a full LIST
    resync (which replaces the stream object) can continue.
    """

    def __init__(
        self,
        base: str,
        resource: str,
        start_rv: int,
        *,
        read_timeout_s: float,
        backoff_base_s: float,
        backoff_cap_s: float,
    ):
        super().__init__(daemon=True, name=f"watch-{resource}")
        self.base = base
        self.resource = resource
        self.rv = start_rv        # reconnect-from rv (this thread only)
        self.seen_rv = start_rv   # newest rv enqueued (read by others)
        self.read_timeout_s = read_timeout_s
        self.backoff_base_s = backoff_base_s
        self.backoff_cap_s = backoff_cap_s
        self.queue: queue.Queue = queue.Queue()
        self.gone = threading.Event()
        self.last_activity = time.monotonic()
        # reconnect coalescing: during a long apiserver outage the
        # retry loop fails every backoff period — enqueueing one
        # RECONNECT item per attempt would grow the pending-event
        # queue without bound for as long as the outage lasts. Only
        # the FIRST failure of a consecutive run is enqueued (it
        # carries the reason); the rest advance this monotonic
        # counter, which the consumer folds into its reconnect counts
        # (the seen_rv read pattern). Queue memory during an outage
        # is O(1), the counts stay exact.
        self.coalesced_reconnects = 0
        self._halt = threading.Event()
        self._resp = None

    # ---- lifecycle ----

    def stop(self) -> None:
        self._halt.set()
        resp = self._resp
        if resp is not None:
            try:
                resp.close()  # unblocks a reader parked in readline
            except Exception:
                pass

    # ---- the reconnect loop ----

    def run(self) -> None:  # pta: background-thread
        attempt = 0
        while not (self._halt.is_set() or self.gone.is_set()):
            try:
                resp = self._connect()
            except WatchGone as e:
                self._push_gone(str(e))
                return
            except (OSError, http.client.HTTPException,
                    urllib.error.URLError) as e:
                if self._halt.is_set():
                    return
                if attempt == 0:
                    self.queue.put(
                        ("RECONNECT", f"connect failed: {e}")
                    )
                else:
                    # consecutive failures coalesce (bounded queue
                    # memory over a long outage); the count stays
                    # exact via the monotonic counter
                    self.coalesced_reconnects += 1
                time.sleep(backoff_delay(
                    attempt, base_s=self.backoff_base_s,
                    cap_s=self.backoff_cap_s,
                ))
                attempt += 1
                continue
            self._resp = resp
            self.last_activity = time.monotonic()
            clean = self._consume(resp)
            try:
                resp.close()
            except Exception:
                pass
            self._resp = None
            if self._halt.is_set() or self.gone.is_set():
                return
            if clean:
                attempt = 0  # routine idle close: resume immediately
            else:
                self.queue.put(("RECONNECT", "stream error"))
                time.sleep(backoff_delay(
                    attempt, base_s=self.backoff_base_s,
                    cap_s=self.backoff_cap_s,
                ))
                attempt += 1

    def _connect(self):  # pta: background-thread
        params = urllib.parse.urlencode({
            "watch": "true",
            "resourceVersion": str(self.rv),
            "allowWatchBookmarks": "true",
        })
        url = f"{self.base}/{self.resource}?{params}"
        try:
            return urllib.request.urlopen(
                urllib.request.Request(url),
                timeout=self.read_timeout_s,
            )
        except urllib.error.HTTPError as e:
            if e.code == 410:
                raise WatchGone(
                    f"rv {self.rv} expired (HTTP 410)"
                ) from e
            raise

    def _push_gone(self, reason: str) -> None:  # pta: background-thread
        self.gone.set()
        self.queue.put(("GONE", reason))

    def _consume(self, resp) -> bool:  # pta: background-thread
        """Decode one connection's stream; True = clean server close.

        http.client's chunked reader swallows an abrupt mid-stream cut
        (IncompleteRead surfaces as a silent EOF), so transport alone
        cannot tell a dirty close from a server ending its watch
        window. The protocol-level tell: a server closing *cleanly*
        ends with a BOOKMARK (we request allowWatchBookmarks); an EOF
        whose last delivered item was a real event means the stream
        died mid-flow and the reconnect is counted + backed off.
        """
        ended_on_bookmark = False
        try:
            for raw in resp:
                if self._halt.is_set():
                    return True
                line = raw.strip()
                if not line:
                    continue
                try:
                    doc = json.loads(line)
                    typ = doc["type"]
                    obj = doc["object"]
                except (json.JSONDecodeError, KeyError, TypeError) as e:
                    # an undecodable stream cannot be trusted to have
                    # delivered everything before the garbage either:
                    # degrade loudly, never guess
                    self._push_gone(f"undecodable watch line: {e!r}")
                    return False
                if typ == "ERROR":
                    code = obj.get("code") if isinstance(obj, dict) \
                        else None
                    self._push_gone(
                        "rv expired (410 ERROR event)" if code == 410
                        else f"watch ERROR event: {obj}"
                    )
                    return False
                try:
                    rv = int(
                        obj.get("metadata", {})
                        .get("resourceVersion", 0) or 0
                    )
                except (TypeError, ValueError):
                    rv = 0
                self.last_activity = time.monotonic()
                if rv > self.rv:
                    self.rv = rv
                if typ == "BOOKMARK":
                    self.queue.put(("BOOKMARK", rv))
                    ended_on_bookmark = True
                else:
                    self.queue.put(("EVENT", rv, typ, obj))
                    ended_on_bookmark = False
                # seen_rv advances only AFTER the item is enqueued:
                # wait_caught_up readers must find the event already
                # in the queue when they observe the new rv
                if rv > self.seen_rv:
                    self.seen_rv = rv
            return ended_on_bookmark or self._halt.is_set()
        except TimeoutError:
            # an idle read window elapsing on a quiet stream is NOT a
            # stream error: real apiservers space bookmarks/window
            # closes further apart than the socket timeout, and
            # treating the timeout as dirty would back off and count
            # reconnects forever on a perfectly healthy idle cluster.
            # Resume immediately from the current rv; last_activity
            # refreshes on the reconnect, so the staleness bound only
            # fires when a stream cannot be RE-ESTABLISHED for
            # max_lag_s (TimeoutError must precede OSError: it is one)
            return True
        except (OSError, http.client.HTTPException, ValueError,
                AttributeError):
            # AttributeError: http.client nulls its fp when stop()
            # closes the response under a parked readline
            return self._halt.is_set()


class ClusterWatcher:
    """Holds the seed snapshot + two watch streams; see module doc."""

    def __init__(
        self,
        client: K8sApiClient,
        *,
        trace: TraceGenerator | None = None,
        max_lag_s: float = 30.0,
        read_timeout_s: float | None = None,
        backoff_base_s: float = 0.05,
        backoff_cap_s: float = 2.0,
        metrics=None,
    ):
        self.client = client
        self.trace = trace or TraceGenerator()
        # observability (obs.SchedulerMetrics or None): resyncs and
        # reconnects are recorded at their trace-emit sites, on the
        # caller's thread, from the reason strings already in hand
        self.metrics = metrics
        self.max_lag_s = max_lag_s
        self.read_timeout_s = (
            read_timeout_s if read_timeout_s is not None
            else client.timeout_s
        )
        self.backoff_base_s = backoff_base_s
        self.backoff_cap_s = backoff_cap_s
        self._streams: dict[str, _WatchStream] = {}
        # per-resource cursor into each stream's coalesced-reconnect
        # counter (folded into tick()'s counts; reset when sync/resume
        # replace the stream objects — any unfolded residual is
        # carried so the counts stay exact across a resync)
        self._coalesced_seen: dict[str, int] = dict.fromkeys(
            RESOURCES, 0
        )
        self._carry_coalesced: dict[str, int] = dict.fromkeys(
            RESOURCES, 0
        )
        self._applied_rv: dict[str, int] = dict.fromkeys(RESOURCES, 0)
        self._seeded = False
        # a degradation whose resync LIST has not succeeded yet; kept
        # so a failed resync is RETRIED next tick (and still counted/
        # traced when it finally lands) instead of silently stranding
        # the watcher with no streams
        self._resync_reason = ""
        # lifetime counters (per-tick deltas ride on ObserveDelta)
        self.resyncs_total = 0
        self.reconnects_total = 0

    # ---- lifecycle ----

    def stop(self) -> None:
        for s in self._streams.values():
            s.stop()
        for s in self._streams.values():
            s.join(timeout=2.0)
        self._streams = {}

    def __enter__(self) -> "ClusterWatcher":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    # ---- sync (seed / resync) ----

    def _carry_residual_coalesced(self) -> None:
        """Before discarding the stream objects, bank each stream's
        not-yet-folded coalesced reconnects — a resync mid-outage
        must not lose exactly the counts the outage minted."""
        for resource, s in self._streams.items():
            residual = (
                s.coalesced_reconnects
                - self._coalesced_seen.get(resource, 0)
            )
            if residual > 0:
                self._carry_coalesced[resource] = (
                    self._carry_coalesced.get(resource, 0) + residual
                )

    def _take_carry(self) -> int:
        """Fold the banked residuals into the metrics; returns the
        total (the caller adds it to this tick's reconnect count,
        whose flow already feeds ``reconnects_total``)."""
        total = 0
        for resource, n in self._carry_coalesced.items():
            if n > 0:
                total += n
                if self.metrics is not None:
                    self.metrics.record_reconnect(resource, amount=n)
        if total:
            self._carry_coalesced = dict.fromkeys(RESOURCES, 0)
        return total

    def sync(self) -> tuple[list[Machine], list[Task]]:
        """Full paginated LIST of both resources; restarts both streams
        from the snapshot rvs. Raises ``ApiError`` if the LISTs fail
        (the caller skips the tick, like a failed poll) — the watcher
        stays un-seeded so the NEXT tick retries the sync rather than
        ticking over zero streams forever."""
        self._carry_residual_coalesced()
        self.stop()
        self._seeded = False
        nodes, nodes_rv = self.client.nodes_with_rv()
        pods, pods_rv = self.client.pods_with_rv()
        self._coalesced_seen = dict.fromkeys(RESOURCES, 0)
        self._applied_rv = {"nodes": nodes_rv, "pods": pods_rv}
        for resource, rv in (("nodes", nodes_rv), ("pods", pods_rv)):
            s = _WatchStream(
                self.client.base, resource, rv,
                read_timeout_s=self.read_timeout_s,
                backoff_base_s=self.backoff_base_s,
                backoff_cap_s=self.backoff_cap_s,
            )
            self._streams[resource] = s
            s.start()
        self._seeded = True
        return nodes, pods

    def resume(self, rvs: dict[str, int]) -> None:
        """Warm-restore resumption (ha/checkpoint.py): restart both
        streams from CHECKPOINTED resourceVersions without a seeding
        LIST — the restored bridge already holds the snapshot those rvs
        describe, so events with rv > checkpoint replay exactly the
        history the dead process missed. If the apiserver has compacted
        past a checkpointed rv the stream goes 410 and the next
        ``tick()`` degrades to the LOUD full-LIST resync (snapshot-diff
        path, mass-eviction guard armed) — stale resumption never
        guesses."""
        self._carry_residual_coalesced()
        self.stop()
        self._coalesced_seen = dict.fromkeys(RESOURCES, 0)
        self._applied_rv = {
            r: int(rvs.get(r, 0)) for r in RESOURCES
        }
        for resource in RESOURCES:
            s = _WatchStream(
                self.client.base, resource, self._applied_rv[resource],
                read_timeout_s=self.read_timeout_s,
                backoff_base_s=self.backoff_base_s,
                backoff_cap_s=self.backoff_cap_s,
            )
            self._streams[resource] = s
            s.start()
        self._seeded = True

    @property
    def applied_rvs(self) -> dict[str, int]:
        """Per-resource applied resourceVersions (the checkpoint's
        watch-position payload; ``applied_rv`` is the string form)."""
        return dict(self._applied_rv)

    @property
    def applied_rv(self) -> str:
        """The per-resource resourceVersions the bridge has APPLIED up
        to, as one ``nodes=N,pods=M`` string — the stream-position
        stamp the flight recorder records with each round so a dump
        correlates with the apiserver's watch history."""
        return ",".join(
            f"{r}={self._applied_rv[r]}" for r in sorted(
                self._applied_rv
            )
        )

    # ---- the per-tick pump ----

    def tick(self) -> ObserveDelta:
        """Drain both streams into typed events, or degrade to a full
        resync (410 / decode error / staleness). Non-blocking except
        during a resync's LISTs."""
        if not self._seeded:
            # first seed, or the retry of a resync whose LIST failed
            reason = self._resync_reason
            nodes, pods = self.sync()
            carried = self._take_carry()
            self.reconnects_total += carried
            if reason:
                self._resync_reason = ""
                self.resyncs_total += 1
                self.trace.emit(
                    "WATCH_RESYNC", detail={"reason": reason}
                )
                if self.metrics is not None:
                    self.metrics.record_resync(reason)
                return ObserveDelta(
                    resynced=True, nodes=nodes, pods=pods, resyncs=1,
                    reconnects=carried,
                )
            return ObserveDelta(
                resynced=True, nodes=nodes, pods=pods,
                reconnects=carried,
            )
        # residuals banked by a previous sync/resume (streams replaced
        # mid-outage) fold into this tick's count
        reconnects = self._take_carry()
        node_events: list[tuple[str, Machine]] = []
        pod_events: list[tuple[str, Task]] = []
        resync_reason = ""
        now = time.monotonic()
        for resource, stream in self._streams.items():
            while True:
                try:
                    item = stream.queue.get_nowait()
                except queue.Empty:
                    break
                kind = item[0]
                if kind == "RECONNECT":
                    reconnects += 1
                    self.trace.emit(
                        "WATCH_RECONNECT",
                        detail={"resource": resource,
                                "reason": item[1]},
                    )
                    if self.metrics is not None:
                        self.metrics.record_reconnect(resource)
                elif kind == "BOOKMARK":
                    self._applied_rv[resource] = max(
                        self._applied_rv[resource], item[1]
                    )
                elif kind == "GONE":
                    resync_reason = resync_reason or (
                        f"{resource}: {item[1]}"
                    )
                    break
                else:  # EVENT
                    _, rv, typ, obj = item
                    if rv and rv <= self._applied_rv[resource]:
                        # replayed history (reconnect overlap): a
                        # resync-storm must never double-apply
                        continue
                    try:
                        parsed = self._parse(resource, obj)
                    except (KeyError, ValueError, TypeError) as e:
                        resync_reason = resync_reason or (
                            f"{resource}: unparseable {typ} event: {e!r}"
                        )
                        break
                    if rv:
                        self._applied_rv[resource] = rv
                    if resource == "nodes":
                        node_events.append((typ, parsed))
                    else:
                        pod_events.append((typ, parsed))
            # fold the stream's coalesced (queue-suppressed)
            # reconnects into this tick's counts — exact totals with
            # O(1) queue memory over a long outage
            cr = stream.coalesced_reconnects
            coalesced = cr - self._coalesced_seen[resource]
            if coalesced > 0:
                self._coalesced_seen[resource] = cr
                reconnects += coalesced
                if self.metrics is not None:
                    self.metrics.record_reconnect(
                        resource, amount=coalesced
                    )
            if not resync_reason and stream.gone.is_set():
                resync_reason = f"{resource}: stream gone"
            if not resync_reason and (
                now - stream.last_activity > self.max_lag_s
            ):
                resync_reason = (
                    f"{resource}: no stream activity for "
                    f"{self.max_lag_s:g}s (--watch_max_lag)"
                )
        self.reconnects_total += reconnects
        if resync_reason:
            log.warning(
                "watch degrading to full LIST resync: %s", resync_reason
            )
            # drained-but-unapplied events are superseded by the
            # snapshot; dropping them cannot lose state. Recorded
            # BEFORE the sync so a failed LIST leaves the reason (and
            # the un-seeded state) in place for the next tick's retry.
            self._resync_reason = resync_reason
            nodes, pods = self.sync()
            self._resync_reason = ""
            self.resyncs_total += 1
            self.trace.emit(
                "WATCH_RESYNC", detail={"reason": resync_reason}
            )
            if self.metrics is not None:
                self.metrics.record_resync(resync_reason)
            return ObserveDelta(
                resynced=True, nodes=nodes, pods=pods,
                resyncs=1, reconnects=reconnects,
            )
        return ObserveDelta(
            node_events=node_events, pod_events=pod_events,
            reconnects=reconnects,
        )

    def _parse(self, resource: str, obj: dict):
        if resource == "nodes":
            return self.client._parse_node(obj)
        return self.client._parse_pod(obj)

    # ---- the express window (between-tick pod events) ----

    def _express_nodes_pending(
        self, nodes: _WatchStream | None, out: ExpressEvents
    ) -> bool:
        """True when the nodes stream holds work only a full tick may
        apply. Pure bookkeeping items (BOOKMARK rv advances, counted
        RECONNECTs — idle streams bookmark routinely) are consumed
        here so they cannot pin the express window shut; a real node
        EVENT is pushed back for ``tick()`` and ends the window."""
        if nodes is None:
            return False
        if nodes.gone.is_set():
            return True
        while True:
            # peek under the queue lock: a get+put-back would reorder
            # the stream behind later events and the rv guard would
            # then silently drop the displaced one
            with nodes.queue.mutex:
                head = (
                    nodes.queue.queue[0] if nodes.queue.queue else None
                )
            if head is None:
                return False
            kind = head[0]
            if kind not in ("BOOKMARK", "RECONNECT"):
                return True  # EVENT or GONE: tick's business
            item = nodes.queue.get_nowait()
            if item[0] == "BOOKMARK":
                self._applied_rv["nodes"] = max(
                    self._applied_rv["nodes"], item[1]
                )
            else:
                out.reconnects += 1
                self.reconnects_total += 1
                self.trace.emit(
                    "WATCH_RECONNECT",
                    detail={"resource": "nodes", "reason": item[1]},
                )
                if self.metrics is not None:
                    self.metrics.record_reconnect("nodes")

    def express_poll(
        self, timeout_s: float, max_events: int = 16,
        shed_queue: int = 0,
    ) -> ExpressEvents:
        """Block up to ``timeout_s`` for pod watch events between round
        ticks; returns as soon as a small batch is available.

        The express lane's event source: waits on the pods stream for
        the FIRST event, then drains whatever else already arrived (up
        to ``max_events`` — the express batch bound). rv accounting is
        shared with ``tick()`` so a later tick can never double-apply
        an express-consumed event. Anything outside the express
        vocabulary — node events waiting, a stream gone/undecodable,
        an un-seeded watcher — sets ``needs_tick`` and leaves the rest
        for the full observe tick (410/staleness resyncs stay on the
        tick path, where the snapshot-diff guards live).
        """
        out = ExpressEvents()
        pods = self._streams.get("pods")
        nodes = self._streams.get("nodes")
        if not self._seeded or pods is None or pods.gone.is_set():
            out.needs_tick = True
            return out
        if shed_queue > 0 and pods.queue.qsize() > shed_queue:
            # overload shed: more events are queued than the express
            # lane should grind through batch by batch — hand the
            # whole burst to the tick path's single full solve.
            # qsize() is advisory but one-sided-safe here: an
            # undercount delays the shed by one poll, never loses
            # events (they stay queued for tick()).
            out.needs_tick = True
            out.shed = True
            return out
        deadline = time.monotonic() + timeout_s
        while len(out.pod_events) < max_events:
            if self._express_nodes_pending(nodes, out):
                # node events reshape the machine axis: the express
                # patch vocabulary cannot follow, tick handles them
                out.needs_tick = True
                break
            try:
                if out.pod_events:
                    item = pods.queue.get_nowait()
                else:
                    wait = deadline - time.monotonic()
                    if wait <= 0:
                        # zero-timeout poll (a stream lane's later
                        # window): drain what already arrived, never
                        # block
                        item = pods.queue.get_nowait()
                    else:
                        item = pods.queue.get(timeout=min(wait, 0.05))
            except queue.Empty:
                if out.pod_events or time.monotonic() >= deadline:
                    break
                continue
            if not out.pod_events:
                out.t_first = time.perf_counter()
            kind = item[0]
            if kind == "RECONNECT":
                out.reconnects += 1
                self.reconnects_total += 1
                self.trace.emit(
                    "WATCH_RECONNECT",
                    detail={"resource": "pods", "reason": item[1]},
                )
                if self.metrics is not None:
                    self.metrics.record_reconnect("pods")
            elif kind == "BOOKMARK":
                self._applied_rv["pods"] = max(
                    self._applied_rv["pods"], item[1]
                )
            elif kind == "GONE":
                # put it back for tick() so the resync keeps its reason
                pods.queue.put(item)
                out.needs_tick = True
                break
            else:  # EVENT
                _, rv, typ, obj = item
                if rv and rv <= self._applied_rv["pods"]:
                    continue  # replayed history: never double-apply
                try:
                    parsed = self._parse("pods", obj)
                except (KeyError, ValueError, TypeError) as e:
                    # same degradation as tick(): an unparseable event
                    # means the stream cannot be trusted — mark it gone
                    # with the real reason and let the tick resync
                    pods.queue.put(
                        ("GONE", f"unparseable {typ} event: {e!r}")
                    )
                    pods.gone.set()
                    out.needs_tick = True
                    break
                if rv:
                    self._applied_rv["pods"] = rv
                out.pod_events.append((typ, parsed))
                out.t_events.append(time.perf_counter())
        return out

    def express_poll_windows(
        self, timeout_s: float, max_events: int = 16,
        windows: int = 1, shed_queue: int = 0,
    ) -> list[ExpressEvents]:
        """The stream lane's event source: up to ``windows`` coalesced
        express windows from one poll call. The first window blocks
        like ``express_poll``; later windows only DRAIN what already
        arrived (timeout 0) — a backlogged stream fills K windows for
        one scanned device dispatch, an idle one returns a single
        window and the driver flushes short. Stops early at a window
        that needs the tick path (node events, gone stream, shed) or
        that came back empty; the returned list always carries at
        least one entry, and only its LAST entry can have
        ``needs_tick``/``shed`` set. rv accounting is shared with
        ``tick()`` exactly as in ``express_poll``."""
        out: list[ExpressEvents] = []
        for w in range(max(windows, 1)):
            ev = self.express_poll(
                timeout_s if w == 0 else 0.0,
                max_events=max_events, shed_queue=shed_queue,
            )
            if not ev.pod_events and w > 0 and not (
                ev.needs_tick or ev.shed or ev.reconnects
            ):
                break  # drained dry: flush what we have
            out.append(ev)
            if ev.needs_tick or ev.shed:
                break
        return out

    # ---- test/bench helpers ----

    def wait_caught_up(self, rv: int, timeout_s: float = 5.0) -> bool:
        """Block until every stream has ENQUEUED events up to ``rv`` (or
        gone 410, which the next ``tick`` turns into a resync). Lets
        hermetic tests and the bench make event arrival deterministic
        without polling ``tick`` in a loop."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if all(
                s.gone.is_set() or s.seen_rv >= rv
                for s in self._streams.values()
            ) and self._streams:
                return True
            if not self._seeded:
                return False
            time.sleep(0.005)
        return False


# re-exported for callers that only import the watch module
__all__ = [
    "ClusterWatcher",
    "ExpressEvents",
    "ObserveDelta",
    "WatchGone",
    "ApiError",
]
