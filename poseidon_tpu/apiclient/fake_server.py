"""In-process fake Kubernetes apiserver for hermetic end-to-end tests.

The reference leaves this as a seam — all apiserver traffic goes through
one base URI (k8s_api_client.h:61) and the JSON shapes are documented in
comments (k8s_api_client.cc:96-99) — but never builds the fixture
(SURVEY §4: zero tests). This serves the core-v1 subset the client uses:

- ``GET /api/v1/nodes``  (optional labelSelector, exact-match subset)
- ``GET /api/v1/pods``
- ``GET /api/v1/{nodes,pods}?watch=true&resourceVersion=N`` — the watch
  protocol: a chunked stream of ``{"type": ADDED|MODIFIED|DELETED|
  BOOKMARK, "object": ...}`` lines for every mutation with rv > N, in
  mutation order, each object stamped with its ``metadata.
  resourceVersion``. Idle streams get a BOOKMARK (current rv, no
  object) and a clean close, like a real apiserver ending a watch
  window; clients reconnect from their last rv. A watch from an rv
  older than the retained event log answers ``410 Gone`` (both shapes
  the real control plane uses: a plain HTTP 410, and an in-stream
  ``ERROR`` event with ``code: 410``).
- ``POST /api/v1/namespaces/{ns}/bindings`` — applies the binding: the
  pod's ``spec.nodeName`` is set and its phase flips to Running on the
  NEXT poll or watch-stream wake (bindings are acknowledged before they
  are observable, like the real control plane).
- ``POST /api/v1/namespaces/{ns}/pods/{name}/eviction`` — unbinds the
  pod: ``spec.nodeName`` is cleared and its phase flips back to Pending
  on the NEXT poll. Evictions and bindings are applied in POST order,
  so a MIGRATE (evict + re-bind) lands as one visible move.

Fault injection for resilience tests: ``fail_next(n)`` makes the next n
requests return HTTP 500; ``rate_limit_next(n)`` answers 429 with a
``Retry-After`` header; ``disconnect_next(n)`` closes the connection
mid-body (a promised Content-Length never delivered); ``delay_next(n,
seconds)`` serves the next n requests only after sleeping — the HUNG
apiserver (the common real outage shape): a client whose socket
timeout is shorter sees a read timeout, not an error status;
``set_outage(True)`` answers EVERY request 503 until cleared (a whole
apiserver outage window, time-based rather than request-counted;
``writes_only=True`` fails only mutations — the reads-OK/writes-down
shape of an etcd write-quorum loss);
``drop_node(name)``
removes a node between polls (the node-removal path the reference never
handled) and — like the real node-lifecycle controller — orphans its
bound pods back to Pending (``orphan_pods=False`` restores the old
leave-them-bound behavior); ``truncate_lists(n)`` serves only the
first n items WITHOUT a
continue token (a partial snapshot masquerading as complete — the
failure mode the bridge's mass-eviction guard exists for). Watch-side:
``gone_next_watch(n)`` answers the next n watch connects with HTTP 410;
``disconnect_watch_next(n)`` cuts n active watch streams mid-event-flow
without a terminating chunk; ``corrupt_next_watch(n)`` emits undecodable
JSON lines; ``compact_watch_log()`` forgets all history so any resumed
rv is too old (the natural 410).

List requests honor ``limit``/``continue`` pagination the way the real
apiserver chunks responses, so the client's token-following is testable,
and every list carries ``metadata.resourceVersion`` so a watch can
continue exactly where the list snapshot ended.
"""

from __future__ import annotations

import bisect
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse


class FakeApiServer:
    """Runs on a random localhost port; mutate state between polls."""

    def __init__(self):
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self.nodes: dict[str, dict] = {}
        self.pods: dict[str, dict] = {}
        self.bindings: list[tuple[str, str]] = []
        self.evictions: list[str] = []
        # the ONE ordered actuation history ("bind"|"evict", pod, node)
        # in accepted-POST order — the chaos invariant checker's
        # exactly-once evidence (bindings/evictions above are separate
        # lists and lose the interleaving)
        self.op_log: list[tuple[str, str, str]] = []
        # bind/evict ops applied in POST order on the next pods poll
        self._pending_ops: list[tuple[str, str, str]] = []
        self._fail_next = 0
        self._rate_limit_next = 0
        self._rate_limit_retry_after = 0.05
        self._disconnect_next = 0
        # slow-response injection: the next n requests sleep this long
        # before answering (a HUNG apiserver — clients with shorter
        # socket timeouts see a read timeout, not an error)
        self._delay_next = 0
        self._delay_s = 0.0
        # outage window: while set, EVERY request answers 503 —
        # or only mutations (POST/PUT/DELETE) with writes_only, the
        # reads-OK/writes-down shape an etcd write-quorum loss has
        self._outage = False
        self._outage_writes_only = False
        # crash-consistency injection (ha/ tests): the next n mutation
        # POSTs are APPLIED and then the connection dies without a
        # response — the "op landed but the caller never learned"
        # world a process crash between POST and ack produces
        self._apply_then_disconnect_next = 0
        self._truncate = 0
        self.requests_served = 0
        # Lease objects (leader election): key "ns/name" ->
        # {holder, duration_s, renew_unix, transitions}
        self.leases: dict[str, dict] = {}
        # ---- watch protocol state ----
        # monotonic resourceVersion; every mutation appends one
        # (rv, kind, type, object-copy) record to the event log
        self._rv = 0
        self._events: list[tuple[int, str, str, dict]] = []
        # rv horizon: a watch may only resume from rv >= this (older
        # history has been compacted away -> 410 Gone)
        self._compact_floor = 0
        self._event_retention = 10_000
        self._gone_next_watch = 0
        self._disconnect_watch_next = 0
        self._corrupt_next_watch = 0
        self._closing = False
        # how long an idle watch stream waits for events before sending
        # a bookmark and closing cleanly (clients reconnect from rv)
        self.watch_idle_close_s = 0.25
        self.watch_bookmarks = True

        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # silence
                pass

            def _reply(
                self, code: int, doc: dict,
                headers: dict[str, str] | None = None,
            ):
                payload = json.dumps(doc).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(payload)

            def _drop_mid_body(self):
                """Promise a body, deliver half of it, cut the
                connection — the client's read raises IncompleteRead
                (the mid-body transport error class)."""
                payload = json.dumps({"items": []}).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header(
                    "Content-Length", str(len(payload) * 2)
                )
                self.end_headers()
                self.wfile.write(payload)
                self.wfile.flush()
                self.close_connection = True

            def _injected_fault(self, write: bool = False) -> str:
                """Consume one injected request-level fault, if armed."""
                with server._lock:
                    server.requests_served += 1
                    if server._outage and (
                        write or not server._outage_writes_only
                    ):
                        return "outage"
                    if server._fail_next > 0:
                        server._fail_next -= 1
                        return "fail"
                    if server._rate_limit_next > 0:
                        server._rate_limit_next -= 1
                        return "rate"
                    if server._disconnect_next > 0:
                        server._disconnect_next -= 1
                        return "disconnect"
                    if server._delay_next > 0:
                        server._delay_next -= 1
                        return "delay"
                return ""

            def _apply_fault(self, fault: str) -> bool:
                if fault == "fail":
                    self._reply(500, {"error": "injected"})
                elif fault == "outage":
                    self._reply(503, {"error": "outage window"})
                elif fault == "rate":
                    self._reply(
                        429, {"error": "throttled"},
                        headers={
                            "Retry-After":
                                str(server._rate_limit_retry_after)
                        },
                    )
                elif fault == "disconnect":
                    self._drop_mid_body()
                elif fault == "delay":
                    # slow, not broken: sleep OUTSIDE the lock, then
                    # serve normally — a client whose socket timeout is
                    # shorter has hung up by then (its write error is
                    # swallowed by the quiet server's handle_error)
                    time.sleep(server._delay_s)
                    return False
                else:
                    return False
                return True

            def do_GET(self):
                if self._apply_fault(self._injected_fault()):
                    return
                url = urlparse(self.path)
                query = parse_qs(url.query)
                if (
                    query.get("watch", ["false"])[0] == "true"
                    and url.path in ("/api/v1/nodes", "/api/v1/pods")
                ):
                    try:
                        self._serve_watch(
                            url.path.rsplit("/", 1)[1], query
                        )
                    except (OSError, ValueError):
                        pass  # client went away mid-stream
                    return
                with server._lock:
                    selector = query.get("labelSelector", [""])[0]
                    parts = url.path.strip("/").split("/")
                    if url.path == "/api/v1/nodes":
                        items = server._select(
                            server.nodes.values(), selector
                        )
                        self._reply(200, server._page(items, query))
                    elif url.path == "/api/v1/pods":
                        server._apply_pending()
                        items = server._select(
                            server.pods.values(), selector
                        )
                        self._reply(200, server._page(items, query))
                    # api/v1/namespaces/{ns}/pods/{name}: the single-
                    # pod read the binding-conflict check and the
                    # actuation-journal replay decide idempotency from
                    elif (
                        len(parts) == 6
                        and parts[2] == "namespaces"
                        and parts[4] == "pods"
                    ):
                        server._apply_pending()
                        doc = server.pods.get(f"{parts[3]}/{parts[5]}")
                        if doc is None:
                            self._reply(
                                404,
                                {"error": f"no pod "
                                          f"{parts[3]}/{parts[5]}"},
                            )
                        else:
                            self._reply(200, doc)
                    # api/v1/namespaces/{ns}/leases/{name}
                    elif (
                        len(parts) == 6
                        and parts[2] == "namespaces"
                        and parts[4] == "leases"
                    ):
                        lease = server.leases.get(
                            f"{parts[3]}/{parts[5]}"
                        )
                        if lease is None:
                            self._reply(404, {"error": self.path})
                        else:
                            self._reply(
                                200, server._lease_doc(
                                    parts[3], parts[5], lease
                                )
                            )
                    else:
                        self._reply(404, {"error": self.path})

            # ---- the watch stream ----------------------------------

            def _chunk(self, doc: dict) -> None:
                self._chunk_raw(json.dumps(doc).encode() + b"\n")

            def _chunk_raw(self, data: bytes) -> None:
                self.wfile.write(
                    f"{len(data):X}\r\n".encode() + data + b"\r\n"
                )
                self.wfile.flush()

            def _serve_watch(self, kind: str, query: dict) -> None:
                rv = int(
                    query.get("resourceVersion", ["0"])[0] or 0
                )
                with server._lock:
                    if server._gone_next_watch > 0:
                        server._gone_next_watch -= 1
                        gone = "http"
                    elif rv < server._compact_floor:
                        gone = "stream"
                    else:
                        gone = ""
                if gone == "http":
                    self._reply(
                        410,
                        {"kind": "Status", "code": 410,
                         "reason": "Expired"},
                    )
                    return
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()
                if gone == "stream":
                    # the real apiserver's other 410 shape: an ERROR
                    # event inside an accepted stream
                    self._chunk({
                        "type": "ERROR",
                        "object": {"kind": "Status", "code": 410,
                                   "reason": "Expired"},
                    })
                    self._chunk_raw(b"")
                    return
                while True:
                    with server._cond:
                        if server._closing:
                            break
                        server._apply_pending()
                        batch = server._events_after(rv, kind)
                        if not batch:
                            server._cond.wait(
                                server.watch_idle_close_s
                            )
                            if server._closing:
                                break
                            server._apply_pending()
                            batch = server._events_after(rv, kind)
                        cur_rv = server._rv
                        disconnect = corrupt = False
                        if batch:
                            if server._disconnect_watch_next > 0:
                                server._disconnect_watch_next -= 1
                                disconnect = True
                            elif server._corrupt_next_watch > 0:
                                server._corrupt_next_watch -= 1
                                corrupt = True
                    if disconnect:
                        # mid-stream cut: one event goes out, then the
                        # connection dies without a terminating chunk
                        self._chunk({
                            "type": batch[0][2], "object": batch[0][3],
                        })
                        self.connection.close()
                        return
                    if corrupt:
                        self._chunk_raw(b'{"type": "ADDED", "obj\n')
                        self._chunk_raw(b"")
                        return
                    if batch:
                        for rv_i, _k, typ, obj in batch:
                            self._chunk({"type": typ, "object": obj})
                            rv = rv_i
                        continue
                    # idle window elapsed: bookmark + clean close
                    if server.watch_bookmarks:
                        self._chunk({
                            "type": "BOOKMARK",
                            "object": {
                                "kind": kind,
                                "metadata": {
                                    "resourceVersion": str(cur_rv)
                                },
                            },
                        })
                    break
                self._chunk_raw(b"")  # terminating chunk

            def do_POST(self):
                fault = self._injected_fault(write=True)
                if self._apply_fault(fault):
                    return
                with server._lock:
                    url = urlparse(self.path)
                    parts = url.path.strip("/").split("/")
                    # api/v1/namespaces/{ns}/bindings
                    if (
                        len(parts) == 5
                        and parts[2] == "namespaces"
                        and parts[4] == "bindings"
                    ):
                        n = int(self.headers.get("Content-Length", 0))
                        body = json.loads(self.rfile.read(n) or b"{}")
                        pod = body["metadata"]["name"]
                        node = body["target"]["name"]
                        # pods are stored per namespace (the URL names
                        # it) — same-named pods in two namespaces are
                        # distinct objects, like the real apiserver
                        key = f"{parts[3]}/{pod}"
                        if key not in server.pods:
                            self._reply(404, {"error": f"no pod {key}"})
                            return
                        if node not in server.nodes:
                            self._reply(404, {"error": f"no node {node}"})
                            return
                        # the real apiserver answers 409 Conflict when
                        # a binding already exists; queued ops fold in
                        # first so "already bound" is authoritative in
                        # POST order (a MIGRATE's evict+bind still
                        # lands as one move)
                        server._apply_pending()
                        cur = server.pods[key].get("spec", {}).get(
                            "nodeName", ""
                        )
                        if cur:
                            self._reply(
                                409,
                                {"kind": "Status", "code": 409,
                                 "reason": "Conflict",
                                 "message": f"pod {key} is already "
                                            f"bound to {cur}"},
                            )
                            return
                        server._pending_ops.append(("bind", key, node))
                        server.bindings.append((key, node))
                        server.op_log.append(("bind", key, node))
                        # wake parked watch streams so the binding
                        # becomes observable at their next wake, like
                        # the next poll would make it
                        server._cond.notify_all()
                        if server._take_apply_then_disconnect():
                            # crash injection: the op IS applied, the
                            # caller never hears back
                            self.close_connection = True
                            return
                        self._reply(201, {"status": "Bound"})
                    # api/v1/namespaces/{ns}/pods/{name}/eviction
                    elif (
                        len(parts) == 7
                        and parts[2] == "namespaces"
                        and parts[4] == "pods"
                        and parts[6] == "eviction"
                    ):
                        key = f"{parts[3]}/{parts[5]}"
                        if key not in server.pods:
                            self._reply(404, {"error": f"no pod {key}"})
                            return
                        server._pending_ops.append(("evict", key, ""))
                        server.evictions.append(key)
                        server.op_log.append(("evict", key, ""))
                        server._cond.notify_all()
                        if server._take_apply_then_disconnect():
                            self.close_connection = True
                            return
                        self._reply(201, {"status": "Evicted"})
                    else:
                        self._reply(404, {"error": self.path})

            # ---- leases (leader election, ha/standby.py) -----------

            def do_PUT(self):
                if self._apply_fault(self._injected_fault(write=True)):
                    return
                url = urlparse(self.path)
                parts = url.path.strip("/").split("/")
                if not (
                    len(parts) == 6
                    and parts[2] == "namespaces"
                    and parts[4] == "leases"
                ):
                    self._reply(404, {"error": self.path})
                    return
                n = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(n) or b"{}")
                spec = body.get("spec", {})
                holder = str(spec.get("holderIdentity", ""))
                duration = float(
                    spec.get("leaseDurationSeconds", 15) or 15
                )
                key = f"{parts[3]}/{parts[5]}"
                with server._lock:
                    cur = server.leases.get(key)
                    now = time.time()
                    expired = (
                        cur is not None
                        and now - cur["renew_unix"] > cur["duration_s"]
                    )
                    if (cur is None or expired
                            or cur["holder"] == holder):
                        transitions = (
                            cur["transitions"]
                            + (1 if cur["holder"] != holder else 0)
                        ) if cur is not None else 0
                        server.leases[key] = {
                            "holder": holder,
                            "duration_s": duration,
                            "renew_unix": now,
                            "transitions": transitions,
                        }
                        self._reply(
                            200, server._lease_doc(
                                parts[3], parts[5], server.leases[key]
                            )
                        )
                    else:
                        self._reply(
                            409,
                            {"kind": "Status", "code": 409,
                             "reason": "Conflict",
                             "details": {"holder": cur["holder"]}},
                        )

            def do_DELETE(self):
                if self._apply_fault(self._injected_fault(write=True)):
                    return
                url = urlparse(self.path)
                query = parse_qs(url.query)
                parts = url.path.strip("/").split("/")
                if not (
                    len(parts) == 6
                    and parts[2] == "namespaces"
                    and parts[4] == "leases"
                ):
                    self._reply(404, {"error": self.path})
                    return
                key = f"{parts[3]}/{parts[5]}"
                identity = query.get("holderIdentity", [""])[0]
                with server._lock:
                    cur = server.leases.get(key)
                    if cur is None:
                        self._reply(404, {"error": self.path})
                    elif identity and cur["holder"] != identity:
                        self._reply(
                            409,
                            {"kind": "Status", "code": 409,
                             "reason": "Conflict",
                             "details": {"holder": cur["holder"]}},
                        )
                    else:
                        del server.leases[key]
                        self._reply(200, {"status": "Released"})

        class _QuietServer(ThreadingHTTPServer):
            def handle_error(self, request, client_address):
                # a delayed reply hitting a hung-up client (the
                # delay_next injection outliving the client's socket
                # timeout) is the EXPECTED outcome, not a server bug —
                # keep the default traceback spam out of test output
                import sys
                exc = sys.exc_info()[1]
                if isinstance(exc, (BrokenPipeError,
                                    ConnectionResetError)):
                    return
                super().handle_error(request, client_address)

        self._httpd = _QuietServer(("127.0.0.1", 0), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )

    # ---- lifecycle -----------------------------------------------------

    def start(self) -> "FakeApiServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        with self._cond:
            self._closing = True
            self._cond.notify_all()
        self._httpd.shutdown()
        self._httpd.server_close()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # ---- state helpers -------------------------------------------------

    def _emit(self, kind: str, typ: str, obj: dict) -> None:
        """Append one watch event (lock held). The object is deep-copied
        and stamped with its rv, since the live dicts mutate in place."""
        self._rv += 1
        copy = json.loads(json.dumps(obj))
        copy.setdefault("metadata", {})["resourceVersion"] = str(
            self._rv
        )
        self._events.append((self._rv, kind, typ, copy))
        if len(self._events) > self._event_retention:
            # trim in one slice (amortized O(1) per event, not a
            # pop(0) shuffle of the whole retained log each time)
            cut = len(self._events) - self._event_retention
            self._compact_floor = self._events[cut - 1][0]
            del self._events[:cut]
        self._cond.notify_all()

    def _events_after(self, rv: int, kind: str) -> list[tuple]:
        """Events with rv' > rv for ``kind`` (lock held). The log is
        rv-sorted, so the resume point is a binary search — a stream
        wake is O(log E + batch), not a rescan of the retained log."""
        idx = bisect.bisect_right(self._events, rv, key=lambda e: e[0])
        return [e for e in self._events[idx:] if e[1] == kind]

    def current_rv(self) -> int:
        with self._lock:
            return self._rv

    def apply_pending(self) -> None:
        """Make queued bind/evict ops observable NOW (tests use this to
        pin the visibility point that a poll's GET or a watch stream's
        next wake would otherwise pick nondeterministically)."""
        with self._lock:
            self._apply_pending()

    @staticmethod
    def _lease_doc(ns: str, name: str, lease: dict) -> dict:
        return {
            "apiVersion": "coordination.k8s.io/v1",
            "kind": "Lease",
            "metadata": {"name": name, "namespace": ns},
            "spec": {
                "holderIdentity": lease["holder"],
                "leaseDurationSeconds": lease["duration_s"],
                "renewTime": lease["renew_unix"],
                "leaseTransitions": lease["transitions"],
            },
        }

    def _take_apply_then_disconnect(self) -> bool:
        """Consume one armed apply-then-disconnect fault (lock held)."""
        if self._apply_then_disconnect_next > 0:
            self._apply_then_disconnect_next -= 1
            return True
        return False

    @staticmethod
    def _select(items, selector: str) -> list[dict]:
        out = list(items)
        if selector:
            want = dict(
                kv.split("=", 1) for kv in selector.split(",") if "=" in kv
            )
            out = [
                i for i in out
                if all(
                    i.get("metadata", {}).get("labels", {}).get(k) == v
                    for k, v in want.items()
                )
            ]
        return out

    def _page(self, items: list[dict], query: dict) -> dict:
        """Apply truncation fault, then limit/continue chunking. The
        continue token is the plain offset (opaque to clients anyway).
        Every page carries the list's ``resourceVersion``."""
        if self._truncate > 0:
            items = items[: self._truncate]
        offset = int(query.get("continue", ["0"])[0] or 0)
        limit = int(query.get("limit", ["0"])[0] or 0)
        meta = {"resourceVersion": str(self._rv)}
        if limit <= 0:
            return {"items": items[offset:], "metadata": meta}
        chunk = items[offset: offset + limit]
        doc: dict = {"items": chunk, "metadata": meta}
        if offset + limit < len(items):
            doc["metadata"]["continue"] = str(offset + limit)
        return doc

    def _apply_pending(self) -> None:
        """Bindings/evictions become observable on the next pods poll or
        watch-stream wake, applied in POST order (a MIGRATE's evict +
        re-bind collapses to one visible move)."""
        for op, pod, node in self._pending_ops:
            doc = self.pods.get(pod)
            if doc is None:
                continue
            if op == "bind":
                doc.setdefault("spec", {})["nodeName"] = node
                doc.setdefault("status", {})["phase"] = "Running"
            else:  # evict
                doc.setdefault("spec", {}).pop("nodeName", None)
                doc.setdefault("status", {})["phase"] = "Pending"
            self._emit("pods", "MODIFIED", doc)
        self._pending_ops.clear()

    def add_node(
        self,
        name: str,
        *,
        cpu: str = "8",
        memory: str = "16Gi",
        pods: int = 10,
        rack: str = "",
    ) -> None:
        labels = {"rack": rack} if rack else {}
        with self._lock:
            typ = "MODIFIED" if name in self.nodes else "ADDED"
            self.nodes[name] = {
                "metadata": {"name": name, "labels": labels},
                "status": {
                    "capacity": {
                        "cpu": cpu, "memory": memory, "pods": str(pods),
                    },
                    "allocatable": {
                        "cpu": cpu, "memory": memory, "pods": str(pods),
                    },
                },
            }
            self._emit("nodes", typ, self.nodes[name])

    def add_pod(
        self,
        name: str,
        *,
        namespace: str = "default",
        cpu: str = "100m",
        memory: str = "128Mi",
        job: str = "",
        data_prefs: dict[str, int] | None = None,
        phase: str = "Pending",
        node: str = "",
    ) -> None:
        meta: dict = {"name": name, "namespace": namespace, "labels": {}}
        if job:
            meta["labels"]["job-name"] = job
        if data_prefs:
            meta["annotations"] = {
                "poseidon.io/data-prefs": json.dumps(data_prefs)
            }
        key = f"{namespace}/{name}"
        with self._lock:
            typ = "MODIFIED" if key in self.pods else "ADDED"
            self.pods[key] = {
                "metadata": meta,
                "spec": {
                    "containers": [
                        {
                            "resources": {
                                "requests": {"cpu": cpu, "memory": memory}
                            }
                        }
                    ],
                    **({"nodeName": node} if node else {}),
                },
                "status": {"phase": phase},
            }
            self._emit("pods", typ, self.pods[key])

    def delete_pod(self, name: str, namespace: str = "default") -> None:
        """Remove a pod outright (k8s object deletion -> DELETED event;
        polls simply stop listing it)."""
        key = name if "/" in name else f"{namespace}/{name}"
        with self._lock:
            doc = self.pods.pop(key, None)
            if doc is not None:
                self._emit("pods", "DELETED", doc)

    def drop_node(self, name: str, orphan_pods: bool = True) -> None:
        """Remove a node. Like the real node-lifecycle controller,
        pods bound to it are orphaned back to Pending (nodeName
        cleared, MODIFIED events) so the scheduler's re-placement
        bindings do not 409 against a binding to a dead node;
        ``orphan_pods=False`` leaves them bound (the stale-cache
        shape some control planes expose briefly)."""
        with self._lock:
            doc = self.nodes.pop(name, None)
            if doc is not None:
                # fold queued bind/evict ops FIRST: a bind POSTed but
                # not yet applied to this node would otherwise escape
                # the orphan scan (nodeName still unset) and later
                # land the pod Running on a dead node
                self._apply_pending()
                self._emit("nodes", "DELETED", doc)
                if orphan_pods:
                    for key, pod in self.pods.items():
                        if pod.get("spec", {}).get("nodeName") == name:
                            pod["spec"].pop("nodeName", None)
                            pod.setdefault("status", {})[
                                "phase"] = "Pending"
                            # the op_log's "this pod may legitimately
                            # be re-bound" marker (chaos exactly-once
                            # checker): node death, not an eviction
                            self.op_log.append(("orphan", key, name))
                            self._emit("pods", "MODIFIED", pod)

    def fail_next(self, n: int) -> None:
        with self._lock:
            self._fail_next = n

    def rate_limit_next(self, n: int, retry_after_s: float = 0.05) -> None:
        """Answer the next n requests with 429 + ``Retry-After``."""
        with self._lock:
            self._rate_limit_next = n
            self._rate_limit_retry_after = retry_after_s

    def disconnect_next(self, n: int) -> None:
        """Cut the next n requests mid-body (Content-Length promised,
        half delivered)."""
        with self._lock:
            self._disconnect_next = n

    def delay_next(self, n: int, seconds: float) -> None:
        """Serve the next n requests only after sleeping ``seconds``
        — the hung apiserver. A client whose socket timeout is
        shorter sees a READ TIMEOUT (counted distinctly from 5xx in
        ``K8sApiClient.retry_stats``), not an error status; the
        request is otherwise served normally after the sleep."""
        with self._lock:
            self._delay_next = n
            self._delay_s = seconds

    def set_outage(self, on: bool, writes_only: bool = False) -> None:
        """An apiserver outage window: while on, EVERY request
        (lists, watches, mutations, leases) answers 503. Time-based
        where ``fail_next`` is request-counted — the shape a real
        control-plane outage has. ``writes_only=True`` fails only the
        mutations (POST/PUT/DELETE) while reads keep answering — the
        reads-OK/writes-down shape an etcd write-quorum loss produces
        (a successful poll must NOT clear a declared outage while
        actuations still cannot land)."""
        with self._lock:
            self._outage = on
            self._outage_writes_only = writes_only

    def apply_then_disconnect_next(self, n: int) -> None:
        """The crash-consistency fault: the next n mutation POSTs are
        APPLIED server-side, then the connection dies without a
        response — exactly what a scheduler crash between the POST
        landing and the ack being read produces. The caller's journal
        replay must treat the re-issued op as already-applied (bind
        409-on-same-target = success), never double-actuate."""
        with self._lock:
            self._apply_then_disconnect_next = n

    def gone_next_watch(self, n: int) -> None:
        """Answer the next n watch connects with HTTP 410 Gone."""
        with self._lock:
            self._gone_next_watch = n

    def disconnect_watch_next(self, n: int) -> None:
        """Cut n watch streams mid-event-flow (one event delivered,
        then the connection dies without a terminating chunk)."""
        with self._lock:
            self._disconnect_watch_next = n

    def corrupt_next_watch(self, n: int) -> None:
        """Emit undecodable JSON on n watch streams (the decode-error
        degrade path)."""
        with self._lock:
            self._corrupt_next_watch = n

    def compact_watch_log(self) -> None:
        """Forget all watch history: any resumed rv is now too old, so
        the next reconnect gets the in-stream 410 ERROR event."""
        with self._lock:
            self._compact_floor = self._rv
            self._events.clear()

    def truncate_lists(self, n: int) -> None:
        """Serve only the first n items of every list, with no continue
        token (0 restores full lists)."""
        with self._lock:
            self._truncate = n

    def succeed_pod(self, name: str, namespace: str = "default") -> None:
        key = name if "/" in name else f"{namespace}/{name}"
        with self._lock:
            doc = self.pods.get(key)
            if doc is not None:
                doc["status"]["phase"] = "Succeeded"
                self._emit("pods", "MODIFIED", doc)
