"""In-process fake Kubernetes apiserver for hermetic end-to-end tests.

The reference leaves this as a seam — all apiserver traffic goes through
one base URI (k8s_api_client.h:61) and the JSON shapes are documented in
comments (k8s_api_client.cc:96-99) — but never builds the fixture
(SURVEY §4: zero tests). This serves the core-v1 subset the client uses:

- ``GET /api/v1/nodes``  (optional labelSelector, exact-match subset)
- ``GET /api/v1/pods``
- ``POST /api/v1/namespaces/{ns}/bindings`` — applies the binding: the
  pod's ``spec.nodeName`` is set and its phase flips to Running on the
  NEXT poll (bindings are acknowledged before they are observable, like
  the real control plane).
- ``POST /api/v1/namespaces/{ns}/pods/{name}/eviction`` — unbinds the
  pod: ``spec.nodeName`` is cleared and its phase flips back to Pending
  on the NEXT poll. Evictions and bindings are applied in POST order,
  so a MIGRATE (evict + re-bind) lands as one visible move.

Fault injection for resilience tests: ``fail_next(n)`` makes the next n
requests return HTTP 500; ``drop_node(name)`` removes a node between
polls (the node-removal path the reference never handled);
``truncate_lists(n)`` serves only the first n items WITHOUT a continue
token (a partial snapshot masquerading as complete — the failure mode
the bridge's mass-eviction guard exists for).

List requests honor ``limit``/``continue`` pagination the way the real
apiserver chunks responses, so the client's token-following is testable.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse


class FakeApiServer:
    """Runs on a random localhost port; mutate state between polls."""

    def __init__(self):
        self._lock = threading.Lock()
        self.nodes: dict[str, dict] = {}
        self.pods: dict[str, dict] = {}
        self.bindings: list[tuple[str, str]] = []
        self.evictions: list[str] = []
        # bind/evict ops applied in POST order on the next pods poll
        self._pending_ops: list[tuple[str, str, str]] = []
        self._fail_next = 0
        self._truncate = 0
        self.requests_served = 0

        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # silence
                pass

            def _reply(self, code: int, doc: dict):
                payload = json.dumps(doc).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def do_GET(self):
                with server._lock:
                    server.requests_served += 1
                    if server._fail_next > 0:
                        server._fail_next -= 1
                        self._reply(500, {"error": "injected"})
                        return
                    url = urlparse(self.path)
                    query = parse_qs(url.query)
                    selector = query.get("labelSelector", [""])[0]
                    if url.path == "/api/v1/nodes":
                        items = server._select(
                            server.nodes.values(), selector
                        )
                        self._reply(200, server._page(items, query))
                    elif url.path == "/api/v1/pods":
                        server._apply_pending()
                        items = server._select(
                            server.pods.values(), selector
                        )
                        self._reply(200, server._page(items, query))
                    else:
                        self._reply(404, {"error": self.path})

            def do_POST(self):
                with server._lock:
                    server.requests_served += 1
                    if server._fail_next > 0:
                        server._fail_next -= 1
                        self._reply(500, {"error": "injected"})
                        return
                    url = urlparse(self.path)
                    parts = url.path.strip("/").split("/")
                    # api/v1/namespaces/{ns}/bindings
                    if (
                        len(parts) == 5
                        and parts[2] == "namespaces"
                        and parts[4] == "bindings"
                    ):
                        n = int(self.headers.get("Content-Length", 0))
                        body = json.loads(self.rfile.read(n) or b"{}")
                        pod = body["metadata"]["name"]
                        node = body["target"]["name"]
                        # pods are stored per namespace (the URL names
                        # it) — same-named pods in two namespaces are
                        # distinct objects, like the real apiserver
                        key = f"{parts[3]}/{pod}"
                        if key not in server.pods:
                            self._reply(404, {"error": f"no pod {key}"})
                            return
                        if node not in server.nodes:
                            self._reply(404, {"error": f"no node {node}"})
                            return
                        server._pending_ops.append(("bind", key, node))
                        server.bindings.append((key, node))
                        self._reply(201, {"status": "Bound"})
                    # api/v1/namespaces/{ns}/pods/{name}/eviction
                    elif (
                        len(parts) == 7
                        and parts[2] == "namespaces"
                        and parts[4] == "pods"
                        and parts[6] == "eviction"
                    ):
                        key = f"{parts[3]}/{parts[5]}"
                        if key not in server.pods:
                            self._reply(404, {"error": f"no pod {key}"})
                            return
                        server._pending_ops.append(("evict", key, ""))
                        server.evictions.append(key)
                        self._reply(201, {"status": "Evicted"})
                    else:
                        self._reply(404, {"error": self.path})

        self._httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )

    # ---- lifecycle -----------------------------------------------------

    def start(self) -> "FakeApiServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # ---- state helpers -------------------------------------------------

    @staticmethod
    def _select(items, selector: str) -> list[dict]:
        out = list(items)
        if selector:
            want = dict(
                kv.split("=", 1) for kv in selector.split(",") if "=" in kv
            )
            out = [
                i for i in out
                if all(
                    i.get("metadata", {}).get("labels", {}).get(k) == v
                    for k, v in want.items()
                )
            ]
        return out

    def _page(self, items: list[dict], query: dict) -> dict:
        """Apply truncation fault, then limit/continue chunking. The
        continue token is the plain offset (opaque to clients anyway)."""
        if self._truncate > 0:
            items = items[: self._truncate]
        offset = int(query.get("continue", ["0"])[0] or 0)
        limit = int(query.get("limit", ["0"])[0] or 0)
        if limit <= 0:
            return {"items": items[offset:]}
        chunk = items[offset: offset + limit]
        doc: dict = {"items": chunk, "metadata": {}}
        if offset + limit < len(items):
            doc["metadata"]["continue"] = str(offset + limit)
        return doc

    def _apply_pending(self) -> None:
        """Bindings/evictions become observable on the next pods poll,
        applied in POST order (a MIGRATE's evict + re-bind collapses to
        one visible move)."""
        for op, pod, node in self._pending_ops:
            doc = self.pods.get(pod)
            if doc is None:
                continue
            if op == "bind":
                doc.setdefault("spec", {})["nodeName"] = node
                doc.setdefault("status", {})["phase"] = "Running"
            else:  # evict
                doc.setdefault("spec", {}).pop("nodeName", None)
                doc.setdefault("status", {})["phase"] = "Pending"
        self._pending_ops.clear()

    def add_node(
        self,
        name: str,
        *,
        cpu: str = "8",
        memory: str = "16Gi",
        pods: int = 10,
        rack: str = "",
    ) -> None:
        labels = {"rack": rack} if rack else {}
        with self._lock:
            self.nodes[name] = {
                "metadata": {"name": name, "labels": labels},
                "status": {
                    "capacity": {
                        "cpu": cpu, "memory": memory, "pods": str(pods),
                    },
                    "allocatable": {
                        "cpu": cpu, "memory": memory, "pods": str(pods),
                    },
                },
            }

    def add_pod(
        self,
        name: str,
        *,
        namespace: str = "default",
        cpu: str = "100m",
        memory: str = "128Mi",
        job: str = "",
        data_prefs: dict[str, int] | None = None,
        phase: str = "Pending",
        node: str = "",
    ) -> None:
        meta: dict = {"name": name, "namespace": namespace, "labels": {}}
        if job:
            meta["labels"]["job-name"] = job
        if data_prefs:
            meta["annotations"] = {
                "poseidon.io/data-prefs": json.dumps(data_prefs)
            }
        with self._lock:
            self.pods[f"{namespace}/{name}"] = {
                "metadata": meta,
                "spec": {
                    "containers": [
                        {
                            "resources": {
                                "requests": {"cpu": cpu, "memory": memory}
                            }
                        }
                    ],
                    **({"nodeName": node} if node else {}),
                },
                "status": {"phase": phase},
            }

    def drop_node(self, name: str) -> None:
        with self._lock:
            self.nodes.pop(name, None)

    def fail_next(self, n: int) -> None:
        with self._lock:
            self._fail_next = n

    def truncate_lists(self, n: int) -> None:
        """Serve only the first n items of every list, with no continue
        token (0 restores full lists)."""
        with self._lock:
            self._truncate = n

    def succeed_pod(self, name: str, namespace: str = "default") -> None:
        key = name if "/" in name else f"{namespace}/{name}"
        with self._lock:
            doc = self.pods.get(key)
            if doc is not None:
                doc["status"]["phase"] = "Succeeded"
