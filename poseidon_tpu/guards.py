"""Runtime enforcement of the contracts the static pass (analysis/)
checks at review time.

Three guards, all cheap enough to stay on in production:

- ``no_implicit_transfers()``: ``jax.transfer_guard("disallow")`` as a
  context manager. The resident round runs under it — any implicit
  host sync (``float()`` on a device array, a numpy coercion of a
  traced result, a stray dispatch on host operands) raises instead of
  silently re-adding the ~100 ms per-sync charge PR 1 removed.
  Explicit ``jax.device_put`` / ``jax.device_get`` stay permitted;
  pairing with the PTA001 lint keeps those to the sanctioned sites.
- ``sanctioned_transfer()``: ``jax.transfer_guard("allow")`` for the
  round's one blessed fetch (and the degrade paths), making the
  allow-list visible in the code instead of implied.
- ``CompileCounter``: counts XLA backend compiles via
  ``jax.monitoring`` so tests can assert the steady-state recompile
  budget (zero) — a recompile regression fails tier-1, not just bench.

``FetchTimeout`` is raised by the resident solver when the pipelined
round's background placement fetch exceeds its deadline
(``--max_solver_runtime``); the bridge turns it into a FETCH_TIMEOUT
trace event + ``SchedulerStats.fetch_timeouts`` so the degradation is
loud, then the driver skips the tick like any other failed round.
"""

from __future__ import annotations

import contextlib
import threading

import jax

try:  # jax >= 0.3.18; poseidon_tpu.compat covers older shims elsewhere
    _transfer_guard = jax.transfer_guard
except AttributeError:  # pragma: no cover - ancient jax
    _transfer_guard = None


class FetchTimeout(RuntimeError):
    """The background placement fetch missed its deadline."""


@contextlib.contextmanager
def no_implicit_transfers():
    """Disallow implicit device<->host transfers inside the block.

    No-op when this jax has no transfer guard (the static PTA001 pass
    still covers the contract there).
    """
    if _transfer_guard is None:  # pragma: no cover - ancient jax
        yield
        return
    with _transfer_guard("disallow"):
        yield


@contextlib.contextmanager
def sanctioned_transfer():
    """Explicitly allow transfers: the round's blessed fetch sites."""
    if _transfer_guard is None:  # pragma: no cover - ancient jax
        yield
        return
    with _transfer_guard("allow"):
        yield


# ---- compile counting --------------------------------------------------

_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
_counter_lock = threading.Lock()
_active_counters: list["CompileCounter"] = []
_listener_installed = False
# optional duration sink (obs satellite): the same monitoring event
# carries the compile's duration in seconds — a daemon wires this to
# SchedulerMetrics.record_compile so every XLA compile lands in the
# poseidon_xla_compile_ms histogram. One process-global slot (the
# listener itself is process-global and cannot be unregistered).
_duration_sink = None


def set_compile_duration_sink(sink) -> bool:
    """Install a ``fn(duration_ms: float)`` receiving every XLA
    backend compile's latency (None to clear). Returns False when
    this jax has no monitoring hook."""
    global _duration_sink
    if not _install_listener():
        return False
    with _counter_lock:
        _duration_sink = sink
    return True


def _on_event(name: str, *args, **_kw) -> None:
    if name != _COMPILE_EVENT:
        return
    with _counter_lock:
        for c in _active_counters:
            c.count += 1
        sink = _duration_sink
    if sink is not None and args:
        try:
            sink(float(args[0]) * 1000.0)
        except Exception:  # a metrics failure must not break compiles
            pass


def _install_listener() -> bool:
    """Register the monitoring listener once per process. jax has no
    unregister (only clear-all, which would drop other listeners), so
    the hook stays installed and counters activate/deactivate."""
    global _listener_installed
    if _listener_installed:
        return True
    try:
        jax.monitoring.register_event_duration_secs_listener(_on_event)
    except AttributeError:  # pragma: no cover - jax without monitoring
        return False
    _listener_installed = True
    return True


class CompileCounter:
    """Context manager counting XLA backend compiles in the block.

    ``supported`` is False when this jax exposes no monitoring hook —
    callers (the budget tests) skip rather than pass vacuously.
    """

    def __init__(self) -> None:
        self.count = 0
        self.supported = False

    def __enter__(self) -> "CompileCounter":
        self.supported = _install_listener()
        with _counter_lock:
            _active_counters.append(self)
        return self

    def __exit__(self, *exc) -> None:
        with _counter_lock:
            _active_counters.remove(self)
