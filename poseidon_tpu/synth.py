"""Synthetic cluster generators for the BASELINE benchmark ladder.

The reference publishes no benchmarks (SURVEY.md section 6); the driver's
north star is the BASELINE.md config ladder (Trivial 10/100 -> Quincy
1k/10k -> CoCo 1k -> trace replay -> vmap x64). These generators produce
``ClusterState`` instances at those scales with realistic structure: racks
of ~32 machines, multi-task jobs, Zipf-ish data-locality preferences, and
a fraction of already-running tasks occupying slots.
"""

from __future__ import annotations

import numpy as np

from poseidon_tpu.cluster import ClusterState, Machine, Task, TaskPhase


def make_synthetic_cluster(
    n_machines: int,
    n_tasks: int,
    *,
    seed: int = 0,
    machines_per_rack: int = 32,
    max_tasks_per_machine: int = 10,
    prefs_per_task: int = 2,
    tasks_per_job: int = 8,
    running_fraction: float = 0.0,
) -> ClusterState:
    """A synthetic cluster shaped like the BASELINE configs.

    ``running_fraction`` of the tasks are marked RUNNING and bound to a
    machine (consuming slots via the builder's discounting); the rest are
    PENDING and carry ``prefs_per_task`` data-locality preferences drawn
    with rack affinity (a task's preferred machines cluster in one rack,
    like Quincy input-data placement).
    """
    rng = np.random.default_rng(seed)
    n_racks = max(1, (n_machines + machines_per_rack - 1) // machines_per_rack)
    machines = [
        Machine(
            name=f"m{i:05d}",
            rack=f"rack{i % n_racks:03d}",
            cpu_capacity=float(rng.choice([8, 16, 32])),
            cpu_allocatable=float(rng.choice([6, 12, 24])),
            memory_capacity_kb=int(rng.choice([1, 2, 4])) << 24,
            memory_allocatable_kb=int(rng.choice([1, 2, 4])) << 23,
            max_tasks=max_tasks_per_machine,
        )
        for i in range(n_machines)
    ]

    n_running = int(n_tasks * running_fraction)
    tasks: list[Task] = []
    for j in range(n_tasks):
        running = j < n_running
        prefs: dict[str, int] = {}
        if not running and prefs_per_task:
            # rack-affine preferences: most of a task's input lives in one
            # rack, so its preferred machines (and one rack pref) do too
            home = int(rng.integers(0, n_racks))
            in_home = np.flatnonzero(
                np.arange(n_machines) % n_racks == home
            )
            k = min(prefs_per_task, len(in_home))
            for m in rng.choice(in_home, size=k, replace=False):
                prefs[machines[int(m)].name] = int(rng.integers(20, 200))
            if rng.random() < 0.3:
                prefs[f"rack{home:03d}"] = int(rng.integers(10, 100))
        tasks.append(
            Task(
                uid=f"pod-{j:06d}",
                job=f"job-{j // tasks_per_job:05d}",
                cpu_request=float(rng.choice([0.1, 0.25, 0.5, 1.0])),
                memory_request_kb=int(rng.choice([1, 2, 8])) << 18,
                phase=TaskPhase.RUNNING if running else TaskPhase.PENDING,
                machine=(
                    machines[int(rng.integers(0, n_machines))].name
                    if running else ""
                ),
                data_prefs=prefs,
                wait_rounds=int(rng.integers(0, 4)),
            )
        )
    return ClusterState(machines=machines, tasks=tasks)


# ---- the BASELINE.md ladder ----

def config1_trivial_small(seed: int = 0) -> ClusterState:
    """BASELINE config 1: Trivial model, 10 nodes / 100 pods."""
    return make_synthetic_cluster(10, 100, seed=seed, prefs_per_task=0,
                                  max_tasks_per_machine=12)


def config2_quincy_flagship(seed: int = 0) -> ClusterState:
    """BASELINE config 2: Quincy, 1k nodes / 10k pods (the headline)."""
    return make_synthetic_cluster(1000, 10_000, seed=seed,
                                  prefs_per_task=2)


def config3_coco(seed: int = 0) -> ClusterState:
    """BASELINE config 3: CoCo interference, 1k nodes."""
    return make_synthetic_cluster(1000, 8000, seed=seed, prefs_per_task=1,
                                  running_fraction=0.2)


def config5_whatif(seed: int = 0) -> ClusterState:
    """BASELINE config 5 cluster: Quincy at 1k machines / 4k pods.

    Round 3 benched what-if batching only on the config-1 toy, where
    per-variant overhead dominates and serial CPU solves win (VERDICT
    round 3, Weak #5). The batched-vmap capability pays off where one
    solve is expensive and the lockstep variants amortize it — this is
    that scale.
    """
    return make_synthetic_cluster(1000, 4000, seed=seed, prefs_per_task=2)


def config6_rebalance(
    n_machines: int = 48,
    n_running: int = 120,
    *,
    seed: int = 0,
) -> ClusterState:
    """Config 6: a drifted cluster for the rebalancing bench.

    Every task is already RUNNING, crowded onto the first quarter of
    the machines (the packing a restart-adoption or a long
    arrival-burst leaves behind), while each task's input data lives on
    a machine drawn across the whole cluster. A place-only scheduler is
    stuck with this packing forever; the rebalancing subsystem
    (``--enable_preemption``) migrates tasks toward their data under
    the churn budget until the cluster quiesces.
    """
    rng = np.random.default_rng(seed)
    crowd = max(n_machines // 4, 1)
    slots = -(-n_running // crowd) + 2  # crowded fit + headroom
    machines = [
        Machine(
            name=f"m{i:03d}",
            rack=f"rack{i % 4}",
            cpu_capacity=16.0,
            cpu_allocatable=16.0,
            memory_capacity_kb=1 << 24,
            memory_allocatable_kb=1 << 24,
            max_tasks=slots,
        )
        for i in range(n_machines)
    ]
    tasks = [
        Task(
            uid=f"run-{j:04d}",
            job=f"job-{j // 6}",
            cpu_request=0.25,
            memory_request_kb=1 << 12,
            phase=TaskPhase.RUNNING,
            machine=f"m{j % crowd:03d}",
            data_prefs={
                f"m{int(rng.integers(0, n_machines)):03d}":
                    int(rng.integers(100, 300))
            },
        )
        for j in range(n_running)
    ]
    return ClusterState(machines=machines, tasks=tasks)


def config8_scale(
    n_machines: int = 65_536,
    n_tasks: int = 524_288,
    *,
    seed: int = 0,
    machines_per_rack: int = 512,
    n_skus: int = 2,
    max_tasks_per_machine: int = 10,
) -> ClusterState:
    """Config 8 (scale_ceiling): the cluster the single-chip dense
    table cannot hold — ROADMAP item 1's 64k machines / 512k pods.

    Shaped like a real hyperscale fleet: a small number of hardware
    SKUs (homogeneous machines are the norm at this scale — machine
    diversity shows up as a handful of SKU classes, which is exactly
    what equivalence-class aggregation exploits), big racks, and
    rack-level data preferences (input data is replicated per
    rack/cell, so tasks prefer a rack, not one machine — machine-level
    pins would force singleton classes). Preference weights and
    ``wait_rounds`` are kept small so the quincy cost domain stays
    inside the auction's int32 envelope at T = 512k (the scaled-cost
    bound 2*cmax*(T+1) < 2^27 admits per-arc costs < ~128 there; see
    ops/dense_auction.py's overflow analysis), and capacity has ~25%
    headroom so placed pods do not starve and age past the bound.
    """
    rng = np.random.default_rng(seed)
    n_racks = max(
        1, (n_machines + machines_per_rack - 1) // machines_per_rack
    )
    # SKUs differ in their allocatable/capacity RATIOS (what the
    # knowledge base actually aggregates), so each SKU is a distinct
    # utilization band and classes = racks x SKUs as documented
    skus = [
        (16.0, 12.0, 2 << 24, 1 << 24),   # cpu .75, mem .5
        (32.0, 16.0, 4 << 24, 3 << 24),   # cpu .5,  mem .75
        (8.0, 7.0, 1 << 24, 1 << 23),     # cpu .875, mem .5
        (64.0, 16.0, 8 << 24, 2 << 24),   # cpu .25, mem .25
    ][: max(n_skus, 1)]
    machines = []
    for i in range(n_machines):
        cpu_cap, cpu_alloc, mem_cap, mem_alloc = skus[
            (i // n_racks) % len(skus)
        ]
        machines.append(Machine(
            name=f"m{i:06d}",
            rack=f"rack{i % n_racks:04d}",
            cpu_capacity=cpu_cap,
            cpu_allocatable=cpu_alloc,
            memory_capacity_kb=mem_cap,
            memory_allocatable_kb=mem_alloc,
            max_tasks=max_tasks_per_machine,
        ))
    home = rng.integers(0, n_racks, size=n_tasks)
    weight = rng.integers(1, 4, size=n_tasks)
    tasks = [
        Task(
            uid=f"pod-{j:07d}",
            job=f"job-{j // 16:06d}",
            cpu_request=0.25,
            memory_request_kb=1 << 18,
            data_prefs={f"rack{int(home[j]):04d}": int(weight[j])},
            wait_rounds=0,
        )
        for j in range(n_tasks)
    ]
    return ClusterState(machines=machines, tasks=tasks)


def config8_arrivals(
    n_racks: int,
    n_new: int,
    round_no: int,
    *,
    seed: int = 0,
) -> list[Task]:
    """Per-round arrival burst for the scale_ceiling churn rounds,
    shaped like ``config8_scale``'s pods."""
    rng = np.random.default_rng(seed + round_no)
    home = rng.integers(0, n_racks, size=n_new)
    weight = rng.integers(1, 4, size=n_new)
    return [
        Task(
            uid=f"pod-r{round_no:03d}-{j:06d}",
            job=f"job-r{round_no:03d}-{j // 16:05d}",
            cpu_request=0.25,
            memory_request_kb=1 << 18,
            data_prefs={f"rack{int(home[j]):04d}": int(weight[j])},
            wait_rounds=0,
        )
        for j in range(n_new)
    ]


def config4_trace_replay(
    n_machines: int = 12_000,
    *,
    seed: int = 0,
    arrivals_per_round: int = 500,
    finish_fraction: float = 0.3,
):
    """BASELINE config 4: cluster-trace-style replay (12k machines).

    Returns (machines, round_iter) where round_iter yields per-round
    (new_tasks, finished_uids): a churn stream shaped like cluster-trace
    replays — bursts of arrivals, a fraction of running work finishing
    each round — to drive the bridge's incremental re-solve path. The
    real Google trace is not redistributable; the statistics here (job
    sizes, arrival burstiness) follow its published shape: many small
    jobs, a heavy tail.
    """
    rng = np.random.default_rng(seed)
    base = make_synthetic_cluster(
        n_machines, 0, seed=seed, machines_per_rack=40,
        max_tasks_per_machine=10,
    )
    machines = base.machines

    def rounds():
        counter = 0
        running: list[str] = []
        while True:
            # bursty arrivals: heavy-tailed job sizes
            n_arrive = max(1, int(rng.poisson(arrivals_per_round)))
            new_tasks = []
            while n_arrive > 0:
                job_size = min(int(rng.pareto(1.5)) + 1, 64, n_arrive)
                job = f"tracejob-{counter}"
                for _ in range(job_size):
                    uid = f"tracepod-{counter:07d}"
                    counter += 1
                    prefs = {}
                    if rng.random() < 0.4:
                        m = int(rng.integers(0, n_machines))
                        prefs[machines[m].name] = int(
                            rng.integers(20, 200)
                        )
                    new_tasks.append(
                        Task(
                            uid=uid, job=job,
                            cpu_request=float(
                                rng.choice([0.1, 0.25, 0.5, 1.0])
                            ),
                            memory_request_kb=int(
                                rng.choice([1, 2, 8])
                            ) << 18,
                            data_prefs=prefs,
                        )
                    )
                n_arrive -= job_size
            # a fraction of running work finishes
            n_done = int(len(running) * finish_fraction)
            done = [
                running.pop(int(rng.integers(0, len(running))))
                for _ in range(n_done)
            ]
            running.extend(t.uid for t in new_tasks)
            yield new_tasks, done

    return machines, rounds()
