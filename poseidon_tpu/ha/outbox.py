"""Actuation outbox + apiserver-outage detection (the degradation
ladder between delta extraction and the wire).

The failure this closes: during an apiserver outage every bind/evict
POST fails, the driver re-queues each pod (``binding_failed``), the
next round re-places the SAME pods, re-POSTs, fails again — so an
N-minute outage costs N/tick rounds of full re-POST storms, inflates
``bind_failures`` by pods x rounds, and ages every affected pod as if
the POD were the problem (distorting the wait-aging cost inputs the
solver prices). The reference has no story at all here — its pplx
chains dissolve transport errors into logged JSON.

The ladder:

- **Classify.** ``K8sApiClient.bind_outcome`` / ``evict_outcome``
  split failures into *rejected* (the apiserver answered and said no —
  re-queue the pod, the decision is wrong) and *unreachable* (the WIRE
  is the problem — transport error, socket timeout, 5xx/429 exhausted;
  the decision stands).
- **Park.** Unreachable actuations enter the ``ActuationOutbox``: the
  pod stays optimistically confirmed in bridge state (it does not
  re-enter the solve, does not age, is not re-POSTed by later rounds),
  and its journal intent stays open (ha/journal.py) so a crash during
  the outage replays it like any other incomplete actuation.
- **Declare.** ``OutageDetector`` counts consecutive transport-level
  failures (failed polls/LISTs, unreachable POSTs); at the threshold
  it declares ``degraded=outage`` — OUTAGE trace event, ONE
  ``poseidon_outage_episodes_total`` tick, ``poseidon_outage`` gauge
  (SLO-visible: ``--slo='outage == 0'``), /readyz condition detail.
  Rounds keep solving from last-known state; the observe path keeps
  probing.
- **Retry.** ``pump()`` (driver thread, once per tick) retries due
  entries with jittered exponential backoff. Each retry is IDEMPOTENT
  via the journal-replay semantics: the pod's current state is read
  first, an effect already visible counts as applied, a re-POSTed
  bind that answers 409-on-the-same-target counts as success. One
  probe failure aborts the pump early — a down apiserver is not
  hammered once per entry.
- **Recover / dead-letter.** The first success clears the outage
  (OUTAGE end event, gauge 0) and the pump drains the backlog. An
  entry that outlives ``dead_letter_s`` (or, in age-unbounded
  configurations, exhausts ``max_attempts``) dead-letters LOUDLY: OUTBOX_DEAD_LETTER trace +
  ``poseidon_outbox_dead_letters_total{op}``, and the driver's
  callback re-queues the pod through the normal ``binding_failed`` /
  ``restore_running`` paths (exactly one aging bump for the whole
  outage, not one per round).

Threading: ``enqueue`` may be called from the bounded binding-POST
pool (cli ``_post_bindings`` workers); ``pump`` runs on the driver
thread only. The entry list is guarded by ``_lock`` (declared in
analysis/contracts.py; PTA006-verified).
"""

from __future__ import annotations

import dataclasses
import logging
import random
import threading
import time

from poseidon_tpu.apiclient.client import ApiError, backoff_delay

log = logging.getLogger(__name__)

# pump outcome vocabulary (mirrors journal REPLAY_OUTCOMES where the
# semantics coincide; "dead-letter" and "waiting" are outbox-only)
PUMP_OUTCOMES = (
    "replayed", "already-applied", "stale", "dead-letter", "waiting",
)


@dataclasses.dataclass
class OutboxEntry:
    """One parked actuation awaiting a reachable apiserver."""

    op: str                  # bind | evict | migrate
    uid: str
    machine: str = ""        # bind/migrate target
    from_machine: str = ""   # evict/migrate source
    seq: int = 0             # journal intent seq (0 = unjournaled)
    round_num: int = 0
    attempts: int = 0
    t_enqueued: float = 0.0  # monotonic
    next_retry: float = 0.0  # monotonic


class OutageDetector:
    """Consecutive-transport-failure ladder -> declared outage state.

    Driver-thread-only (fed from the observe loop and the pump's
    outcomes, both on the driver thread). ``on_change`` fires on every
    transition with the new state — the cli wires it to the trace
    event, the metrics gauge/episode counter, and the /readyz detail.
    """

    def __init__(self, threshold: int = 3, *, on_change=None):
        self.threshold = max(1, threshold)
        self.on_change = on_change
        self.consecutive_failures = 0
        self.active = False
        self.episodes = 0

    def note_failure(self) -> bool:
        """One transport-level failure (failed poll/LIST, unreachable
        POST). Returns True when this failure DECLARED the outage."""
        self.consecutive_failures += 1
        if (not self.active
                and self.consecutive_failures >= self.threshold):
            self.active = True
            self.episodes += 1
            log.warning(
                "apiserver outage declared (%d consecutive transport "
                "failures); rounds continue from last-known state, "
                "actuations park in the outbox",
                self.consecutive_failures,
            )
            if self.on_change is not None:
                self.on_change(True)
            return True
        return False

    def note_success(self) -> bool:
        """One successful apiserver interaction. Returns True when it
        CLEARED an active outage."""
        self.consecutive_failures = 0
        if self.active:
            self.active = False
            log.warning("apiserver outage cleared; replaying outbox")
            if self.on_change is not None:
                self.on_change(False)
            return True
        return False


class ActuationOutbox:
    """Parked actuations with per-entry jittered backoff + dead-letter.

    ``on_settled(entry, outcome)`` fires for replayed /
    already-applied / stale entries (the cli marks the journal and
    lifecycle); ``on_dead_letter(entry)`` fires when an entry exhausts
    its budget (the cli re-queues the pod and marks the journal
    failed).
    """

    def __init__(
        self,
        client,
        *,
        max_attempts: int = 8,
        dead_letter_s: float = 120.0,
        base_backoff_s: float = 0.5,
        cap_backoff_s: float = 10.0,
        metrics=None,
        on_settled=None,
        on_dead_letter=None,
        rng=random.random,
    ):
        self.client = client
        self.max_attempts = max_attempts
        self.dead_letter_s = dead_letter_s
        self.base_backoff_s = base_backoff_s
        self.cap_backoff_s = cap_backoff_s
        self.metrics = metrics
        self.on_settled = on_settled
        self.on_dead_letter = on_dead_letter
        self.rng = rng
        self._lock = threading.Lock()
        self._entries: list[OutboxEntry] = []
        # lifetime counters (host ints; read by stats/tests)
        self.retries_total = 0
        self.dead_letters_total = 0
        self.settled_total = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    @property
    def pending(self) -> int:
        return len(self)

    def enqueue(
        self, op: str, uid: str, *, machine: str = "",
        from_machine: str = "", seq: int = 0, round_num: int = 0,
    ) -> None:
        """Park one unreachable actuation (POST-pool or driver
        thread). The first retry waits one base backoff — the POST
        that just failed IS attempt zero."""
        now = time.monotonic()
        entry = OutboxEntry(
            op=op, uid=uid, machine=machine,
            from_machine=from_machine, seq=seq, round_num=round_num,
            attempts=1, t_enqueued=now,
            next_retry=now + backoff_delay(
                0, base_s=self.base_backoff_s,
                cap_s=self.cap_backoff_s, rng=self.rng,
            ),
        )
        with self._lock:
            # one entry per (op, uid): a re-decision for the same pod
            # supersedes the parked one (latest target wins)
            self._entries = [
                e for e in self._entries
                if not (e.op == op and e.uid == uid)
            ]
            self._entries.append(entry)
            pending = len(self._entries)
        log.warning(
            "outbox: parked %s %s -> %s (pending=%d)",
            op, uid, machine or from_machine, pending,
        )
        if self.metrics is not None:
            self.metrics.record_outbox(pending)

    # ---- the retry pump (driver thread, once per tick) -----------------
    # (a pod retired while parked needs no explicit cleanup: the
    # retry's get_pod probe answers "stale" and the entry settles)

    def pump(
        self, now: float | None = None, *, force: bool = False
    ) -> dict[str, int]:
        """Retry due entries idempotently; returns outcome counts.

        The first transport-level probe failure aborts the pump for
        this tick (the apiserver is still down — hammering the rest
        of the backlog would recreate the storm the outbox exists to
        prevent); the failed entry's backoff advances so the next
        pump spaces out. ``force=True`` (graceful shutdown's one
        best-effort drain) treats every entry as due and never
        dead-letters — what stays parked is the journal's problem.
        """
        now = time.monotonic() if now is None else now
        counts = dict.fromkeys(PUMP_OUTCOMES, 0)
        with self._lock:
            entries = list(self._entries)
        if not entries:
            return counts
        retries_before = self.retries_total
        aborted = self._pump_pass(entries, now, counts,
                                  respect_backoff=not force)
        settled = (counts["replayed"] + counts["already-applied"]
                   + counts["stale"])
        if not aborted and settled and self.pending:
            # the wire is PROVABLY healed (something just settled):
            # recovery drains the whole backlog now instead of
            # honoring per-entry backoff stamps minted during the
            # outage — "recovery replays the outbox", not "recovery
            # trickles it out over the old retry schedule"
            counts["waiting"] = 0
            with self._lock:
                remaining = list(self._entries)
            self._pump_pass(remaining, now, counts,
                            respect_backoff=False)
        if self.metrics is not None:
            # one recording per pump (not per entry): pending gauge +
            # the pass's retry count folded in a single call
            self.metrics.record_outbox(
                self.pending,
                retries=self.retries_total - retries_before,
            )
        return counts

    def _pump_pass(
        self, entries: list[OutboxEntry], now: float, counts,
        *, respect_backoff: bool,
    ) -> bool:
        """One pass over ``entries``; True = aborted on an
        unreachable apiserver."""
        for e in entries:
            if respect_backoff and e.next_retry > now:
                counts["waiting"] += 1
                continue
            # with an age bound configured, age is THE bound: the
            # attempt cap applying too would dead-letter mid-outage
            # long before the operator's window (attempts grow one
            # per pump against a down apiserver), re-queue the pod,
            # re-park it next round, and repeat — re-creating the
            # per-cycle aging/bind_failures inflation the outbox
            # exists to prevent. The cap is the backstop for
            # age-unbounded (dead_letter_s == 0) configurations only.
            expired = respect_backoff and (
                (self.dead_letter_s > 0
                 and now - e.t_enqueued >= self.dead_letter_s)
                or (self.dead_letter_s <= 0
                    and e.attempts >= self.max_attempts)
            )
            if expired:
                self._dead_letter(e, counts)
                continue
            self.retries_total += 1
            try:
                outcome = self._retry_one(e)
            except ApiError:
                # still unreachable: back off this entry and stop
                # probing the rest this tick
                self._backoff(e, now)
                counts["waiting"] += 1
                return True
            if outcome == "unreachable":
                self._backoff(e, now)
                counts["waiting"] += 1
                return True
            if outcome in ("replayed", "already-applied", "stale"):
                self._settle(e, outcome, counts)
            else:  # rejected / conflict: the decision cannot land
                self._dead_letter(e, counts)
        return False

    def _retry_one(self, e: OutboxEntry) -> str:
        """One idempotent retry: read-then-write, journal-replay
        semantics (ha/journal.py). Raises ApiError when the state
        probe itself cannot reach the apiserver."""
        pod = self.client.get_pod(e.uid)
        if pod is None:
            return "stale"
        if e.op == "bind":
            if pod.machine == e.machine:
                return "already-applied"
            if pod.machine:
                return "conflict"  # bound elsewhere: not ours to undo
            out = self.client.bind_outcome(
                e.uid, e.machine, namespace=pod.namespace
            )
            return "replayed" if out == "ok" else out
        if e.op == "evict":
            if not pod.machine:
                return "already-applied"
            if e.from_machine and pod.machine != e.from_machine:
                return "conflict"
            out = self.client.evict_outcome(
                e.uid, namespace=pod.namespace
            )
            return "replayed" if out == "ok" else out
        if e.op == "migrate":
            if pod.machine == e.machine:
                return "already-applied"
            if pod.machine and pod.machine != e.from_machine:
                return "conflict"
            if pod.machine == e.from_machine:
                out = self.client.evict_outcome(
                    e.uid, namespace=pod.namespace
                )
                if out != "ok":
                    return out
            out = self.client.bind_outcome(
                e.uid, e.machine, namespace=pod.namespace
            )
            return "replayed" if out == "ok" else out
        return "conflict"

    def _backoff(self, e: OutboxEntry, now: float) -> None:
        with self._lock:
            for live in self._entries:
                if live is e or (
                    live.op == e.op and live.uid == e.uid
                ):
                    live.attempts += 1
                    live.next_retry = now + backoff_delay(
                        live.attempts,
                        base_s=self.base_backoff_s,
                        cap_s=self.cap_backoff_s, rng=self.rng,
                    )
                    break

    def _settle(self, e: OutboxEntry, outcome: str, counts) -> None:
        counts[outcome] += 1
        self.settled_total += 1
        with self._lock:
            self._entries = [
                x for x in self._entries
                if not (x.op == e.op and x.uid == e.uid)
            ]
        log.info(
            "outbox: %s %s -> %s settled (%s, attempt %d)",
            e.op, e.uid, e.machine or e.from_machine, outcome,
            e.attempts,
        )
        if self.metrics is not None:
            self.metrics.record_outbox(self.pending, settled=outcome)
        if self.on_settled is not None:
            self.on_settled(e, outcome)

    def _dead_letter(self, e: OutboxEntry, counts) -> None:
        counts["dead-letter"] += 1
        self.dead_letters_total += 1
        with self._lock:
            self._entries = [
                x for x in self._entries
                if not (x.op == e.op and x.uid == e.uid)
            ]
        log.error(
            "outbox: DEAD-LETTER %s %s -> %s after %d attempts / "
            "%.1fs; re-queueing the pod",
            e.op, e.uid, e.machine or e.from_machine, e.attempts,
            time.monotonic() - e.t_enqueued,
        )
        if self.metrics is not None:
            self.metrics.record_outbox(
                self.pending, dead_letter_op=e.op
            )
        if self.on_dead_letter is not None:
            self.on_dead_letter(e)
