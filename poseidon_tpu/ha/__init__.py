"""High availability: crash-safe checkpoints, actuation journal, HA.

The scheduler replaces kube-scheduler outright — a process death is a
cluster-wide placement outage — and every ounce of its performance
lives in warm state a restart would otherwise throw away (HBM
prices/layouts, grow-only pad floors, the bridge pod state machine,
the watch resourceVersion). This package makes restarts survivable:

- ``checkpoint.py``: atomic versioned warm-state snapshots (tmp +
  rename, checksummed, torn-write tolerant) taken on a round cadence,
  and the restore path that rehydrates bridge + solver + incremental
  builder and resumes the watch from the checkpointed rv;
- ``journal.py``: a write-ahead actuation journal — every bind/evict
  POST is journaled intent -> posted -> confirmed, fsync'd before the
  wire, so a restart replays incomplete actuations idempotently and
  never double-binds or loses a placement the apiserver accepted;
- ``standby.py``: Lease-style leader election + a warm standby that
  follows checkpoints and takes over without a cold start;
- ``outbox.py``: the apiserver-outage degradation ladder — an
  actuation outbox parks unreachable bind/evict POSTs with jittered
  backoff + a dead-letter bound (instead of per-round re-POST storms
  and distorted wait-aging), and an outage detector declares the
  ``degraded=outage`` state rounds keep solving through.
"""

from poseidon_tpu.ha.checkpoint import (
    CheckpointManager,
    CheckpointSnapshot,
    load_latest,
    restore_bridge,
)
from poseidon_tpu.ha.journal import ActuationJournal, replay_journal
from poseidon_tpu.ha.outbox import (
    ActuationOutbox,
    OutageDetector,
    OutboxEntry,
)
from poseidon_tpu.ha.standby import LeaderElector

__all__ = [
    "ActuationJournal",
    "ActuationOutbox",
    "CheckpointManager",
    "CheckpointSnapshot",
    "LeaderElector",
    "OutageDetector",
    "OutboxEntry",
    "load_latest",
    "replay_journal",
    "restore_bridge",
]
