"""Atomic warm-state checkpoints + the warm restore path.

What a checkpoint carries (the full warm surface a restart loses):

- the **warm solve seed** — the host (asg, lvl, floor) mirror of the
  on-HBM ``DenseState`` that the round's ONE sanctioned fetch already
  downloaded (``ResidentSolver.warm_seed_host``, the same seam the
  flight recorder rides) — so the first post-restore round warm-starts
  the exact compiled program instead of a cold solve;
- the solver's **grow-only padding floors** (``pad_floors``) — so the
  restored round pads to the same static shapes and the steady state
  stays at ZERO recompiles across the restart;
- the **bridge pod/machine state machine** — tasks (with their
  bridge-internal ``wait_rounds`` aging), machines, both in dict
  insertion order (the pending order every graph build depends on);
- the **KnowledgeBase sample rings** — the utilization history the
  cost models price from (without it the restored round would price
  from one cold sample and diverge);
- the **incremental-builder columns** (when checkpoint-clean) — so the
  first post-restore build patches O(churn) instead of re-walking the
  cluster; the builder's own self-heal verify guards adoption;
- the **watch resourceVersion** per resource — so the restored watcher
  resumes the event stream exactly where the dead one stopped (a
  compacted rv degrades to the loud 410 resync path, never a guess).

Write discipline: capture is a cheap driver-thread snapshot (dict
copies of the bridge maps — Task/Machine are frozen dataclasses and
the builder columns are copy-on-write, so references stay frozen; the
knowledge rings mutate in place and are the one real copy).
Serialization + disk I/O run on a background writer thread, off the
round's critical path: arrays into ``<stem>.npz`` (tmp + fsync +
rename), then the manifest into ``<stem>.json`` (tmp + fsync + rename)
carrying the npz's SHA-256. A torn write therefore leaves either an
ignored ``*.tmp`` or an npz without a manifest — ``load_latest`` walks
manifests newest-first, verifies the checksum, and falls back to the
previous complete checkpoint on any damage.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import os
import queue
import threading
import time

import numpy as np

from poseidon_tpu.cluster import Machine, Task, TaskPhase
from poseidon_tpu.graph.builder import BuilderColumns

log = logging.getLogger(__name__)

# the checkpoint format version (manifest "format"): bump on layout
# changes so restore refuses snapshots it would misread
CKPT_FORMAT = 1

# numeric BuilderColumns fields riding the npz; the object-dtype
# columns (uids/jobs/run_uids/run_job) ride the manifest as lists
_COLS_NUMERIC = (
    "m_rack", "m_max", "used_slots", "job_idx", "job_counts", "wait",
    "pref_counts", "pref_m", "pref_r", "pref_w", "cpu_milli", "mem_kb",
    "run_machine", "run_wait", "run_cpu", "run_mem", "run_pref_counts",
    "run_pref_m", "run_pref_r", "run_pref_w",
)
_COLS_OBJECT = ("uids", "jobs", "run_uids", "run_job")


@dataclasses.dataclass
class CheckpointSnapshot:
    """One captured warm-state image (host-side, write-ready)."""

    round_num: int
    cost_model: str
    flags: dict
    tasks: list[Task]            # bridge insertion order (load-bearing)
    machines: list[Machine]      # bridge insertion order
    knowledge: dict              # KnowledgeBase.export_state (copies)
    pad_floors: dict
    warm_seed: tuple | None      # host (asg, lvl, floor) or None
    cols: BuilderColumns | None  # patchable builder columns or None
    rv: dict[str, int]           # per-resource watch position
    created_unix: float = 0.0


def _task_doc(t: Task) -> dict:
    return {
        "uid": t.uid, "ns": t.namespace, "job": t.job,
        "cpu": t.cpu_request, "mem": t.memory_request_kb,
        "phase": t.phase.value, "machine": t.machine,
        "prefs": dict(t.data_prefs), "wait": t.wait_rounds,
    }


def _task_from_doc(d: dict) -> Task:
    return Task(
        uid=d["uid"], namespace=d["ns"], job=d["job"],
        cpu_request=float(d["cpu"]), memory_request_kb=int(d["mem"]),
        phase=TaskPhase(d["phase"]), machine=d["machine"],
        data_prefs={k: int(v) for k, v in d["prefs"].items()},
        wait_rounds=int(d["wait"]),
    )


def _machine_doc(m: Machine) -> dict:
    return {
        "name": m.name, "cpu_cap": m.cpu_capacity,
        "cpu_alloc": m.cpu_allocatable,
        "mem_cap": m.memory_capacity_kb,
        "mem_alloc": m.memory_allocatable_kb,
        "rack": m.rack, "max_tasks": m.max_tasks,
    }


def _machine_from_doc(d: dict) -> Machine:
    return Machine(
        name=d["name"], cpu_capacity=float(d["cpu_cap"]),
        cpu_allocatable=float(d["cpu_alloc"]),
        memory_capacity_kb=int(d["mem_cap"]),
        memory_allocatable_kb=int(d["mem_alloc"]),
        rack=d["rack"], max_tasks=int(d["max_tasks"]),
    )


def capture_snapshot(bridge, watcher=None) -> CheckpointSnapshot:
    """Snapshot a bridge's warm state (driver thread, post-round).

    Cheap by design: the task/machine maps shallow-copy (their values
    are frozen dataclasses the bridge replaces, never mutates), the
    warm seed and builder columns are references frozen by the
    copy-on-write discipline, and only the knowledge rings — which DO
    mutate in place — are copied. Amortized over the ``--checkpoint_
    every`` cadence this stays inside the same <2% budget the flight
    recorder's capture meets (bench config 13).
    """
    solver = bridge.solver
    graph = getattr(bridge, "_graph", None)
    cols = graph.checkpoint_columns() if graph is not None else None
    rv: dict[str, int] = {}
    if watcher is not None:
        rv = watcher.applied_rvs
    return CheckpointSnapshot(
        round_num=bridge.round_num,
        cost_model=str(bridge.cost_model),
        flags={
            "enable_preemption": bridge.enable_preemption,
            "migration_hysteresis": bridge.migration_hysteresis,
            "max_migrations_per_round": bridge.max_migrations_per_round,
            "express_lane": bridge.express_lane,
            "incremental_build": bridge.incremental_build,
            "mesh_width": getattr(solver, "mesh_width", 0),
            "aggregate_classes": getattr(
                solver, "aggregate_classes", False
            ),
            "topk_prefs": getattr(solver, "topk_prefs", 0),
        },
        tasks=list(bridge.tasks.values()),
        machines=list(bridge.machines.values()),
        knowledge=bridge.knowledge.export_state(),
        pad_floors=dict(getattr(solver, "pad_floors", {})),
        warm_seed=getattr(solver, "warm_seed_host", None),
        cols=cols,
        rv=rv,
        created_unix=time.time(),
    )


class CheckpointManager:
    """Owns one checkpoint directory: capture, background writes,
    pruning, loading.

    Threading: ``capture``/``write_sync``/``load_latest`` run on the
    driver thread; ``submit`` hands a snapshot to the writer thread
    through a ``queue.Queue`` (snapshots are immutable after capture —
    frozen dataclasses + copy-on-write arrays — so the queue IS the
    handoff). Writer statistics are read and written under ``_lock``
    on both sides (analysis/contracts.py declares the discipline).
    """

    def __init__(
        self,
        out_dir: str,
        *,
        keep: int = 2,
        fsync: bool = True,
        metrics=None,
        crash_hook=None,
    ):
        self.out_dir = out_dir
        self.keep = max(int(keep), 1)
        self.fsync = fsync
        self.metrics = metrics
        # fault-injection seam (tests/test_ha.py crash fuzz): called
        # with a named kill point; raising there simulates a process
        # death at exactly that boundary. None in production.
        self.crash_hook = crash_hook
        self._lock = threading.Lock()
        self._queue: queue.Queue = queue.Queue(maxsize=2)
        self._halt = threading.Event()
        self._thread: threading.Thread | None = None
        self._seq = 0
        # boot-unique, lexicographically-monotonic stem token (epoch
        # milliseconds): a restarted daemon's round numbers can RESET
        # (--restore=false cold start), and round-numbered stems alone
        # would then sort the fresh boot's checkpoints BEFORE the dead
        # boot's — _prune would delete the new ones and load_latest
        # would resurrect the ancient state. Same trick as the flight
        # recorder's boot token.
        self._boot = f"{int(time.time() * 1000):015d}"
        # writer stats (guarded by _lock on both threads)
        self.writes_total = 0
        self.write_failures = 0
        self.last_path = ""
        self.last_bytes = 0
        self.last_unix = 0.0

    # ---- capture (driver thread) --------------------------------------

    def capture(self, bridge, watcher=None) -> CheckpointSnapshot:
        return capture_snapshot(bridge, watcher)

    # ---- the background writer ----------------------------------------

    def submit(self, snap: CheckpointSnapshot) -> None:
        """Queue a snapshot for the writer thread (latest wins: a slow
        disk drops the OLDEST queued snapshot, never blocks a round)."""
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._write_loop, name="ckpt-writer", daemon=True
            )
            self._thread.start()
        while True:
            try:
                self._queue.put_nowait(snap)
                return
            except queue.Full:
                try:
                    dropped = self._queue.get_nowait()
                    log.warning(
                        "checkpoint writer lagging; dropping queued "
                        "round-%d snapshot", dropped.round_num,
                    )
                except queue.Empty:
                    pass

    def _write_loop(self) -> None:  # pta: background-thread
        while not self._halt.is_set():
            try:
                snap = self._queue.get(timeout=0.2)
            except queue.Empty:
                continue
            if snap is None:
                return
            try:
                self.write_sync(snap)
            except Exception:
                with self._lock:
                    self.write_failures += 1
                log.exception("checkpoint write failed")

    def close(self, final_snap: CheckpointSnapshot | None = None) -> None:
        """Drain the writer; optionally write one final synchronous
        checkpoint (the graceful-shutdown path)."""
        if self._thread is not None:
            self._queue.put(None)
            self._thread.join(timeout=10.0)
            self._halt.set()
            self._thread = None
        if final_snap is not None:
            self.write_sync(final_snap)

    # ---- serialization (writer thread or shutdown path) ---------------

    def write_sync(self, snap: CheckpointSnapshot) -> str:
        """Serialize + atomically publish one snapshot; returns the
        manifest path."""
        os.makedirs(self.out_dir, exist_ok=True)
        with self._lock:
            self._seq += 1
            seq = self._seq
        stem = os.path.join(
            self.out_dir,
            f"ckpt-{self._boot}-r{snap.round_num:08d}-{seq:04d}",
        )
        if self.crash_hook is not None:
            self.crash_hook("pre-write")
        blobs: dict[str, np.ndarray] = {}
        if snap.warm_seed is not None:
            for name, arr in zip(("asg", "lvl", "floor"),
                                 snap.warm_seed):
                blobs[f"warm/{name}"] = np.asarray(arr)
        for store, pre in ((snap.knowledge["machines"], "know_m"),
                           (snap.knowledge["tasks"], "know_t")):
            for k in ("buf", "sum", "count"):
                blobs[f"{pre}/{k}"] = store[k]
        if snap.cols is not None:
            for k in _COLS_NUMERIC:
                blobs[f"cols/{k}"] = getattr(snap.cols, k)
        npz_tmp = stem + ".npz.tmp"
        with open(npz_tmp, "wb") as fh:
            np.savez_compressed(fh, **blobs)
            fh.flush()
            if self.fsync:
                os.fsync(fh.fileno())
        if self.crash_hook is not None:
            self.crash_hook("mid-write")  # npz staged, nothing published
        os.replace(npz_tmp, stem + ".npz")
        sha = hashlib.sha256()
        with open(stem + ".npz", "rb") as fh:
            for chunk in iter(lambda: fh.read(1 << 20), b""):
                sha.update(chunk)
        nbytes = os.path.getsize(stem + ".npz")
        manifest = {
            "format": CKPT_FORMAT,
            "round_num": snap.round_num,
            "cost_model": snap.cost_model,
            "flags": snap.flags,
            "rv": snap.rv,
            "pad_floors": snap.pad_floors,
            "has_warm_seed": snap.warm_seed is not None,
            "created_unix": snap.created_unix,
            "npz_sha256": sha.hexdigest(),
            "npz_bytes": nbytes,
            "tasks": [_task_doc(t) for t in snap.tasks],
            "machines": [_machine_doc(m) for m in snap.machines],
            "knowledge": {
                "queue_size": snap.knowledge["queue_size"],
                "m_idx": snap.knowledge["machines"]["idx"],
                "m_free": snap.knowledge["machines"]["free"],
                "t_idx": snap.knowledge["tasks"]["idx"],
                "t_free": snap.knowledge["tasks"]["free"],
            },
            "cols": (
                None if snap.cols is None else {
                    "machine_names": list(snap.cols.machine_names),
                    "racks": list(snap.cols.racks),
                    **{
                        k: getattr(snap.cols, k).tolist()
                        for k in _COLS_OBJECT
                    },
                }
            ),
        }
        json_tmp = stem + ".json.tmp"
        with open(json_tmp, "w") as fh:
            json.dump(manifest, fh)
            fh.flush()
            if self.fsync:
                os.fsync(fh.fileno())
        if self.crash_hook is not None:
            self.crash_hook("pre-manifest")  # npz live, manifest staged
        os.replace(json_tmp, stem + ".json")
        total = nbytes + os.path.getsize(stem + ".json")
        with self._lock:
            self.writes_total += 1
            self.last_path = stem + ".json"
            self.last_bytes = total
            self.last_unix = time.time()
        if self.metrics is not None:
            self.metrics.record_checkpoint(total)
        self._prune()
        log.info(
            "checkpoint round %d written to %s (%d bytes)",
            snap.round_num, stem + ".json", total,
        )
        return stem + ".json"

    def _prune(self) -> None:
        """Keep the newest ``keep`` complete checkpoints + drop stale
        tmp files (a crashed writer's leftovers)."""
        try:
            names = sorted(os.listdir(self.out_dir))
        except OSError:
            return
        manifests = [n for n in names if n.startswith("ckpt-")
                     and n.endswith(".json")]
        for stale in manifests[:-self.keep]:
            stem = os.path.join(
                self.out_dir, stale[: -len(".json")]
            )
            for suffix in (".json", ".npz"):
                try:
                    os.remove(stem + suffix)
                except OSError:
                    pass
        for n in names:
            if n.startswith("ckpt-") and n.endswith(".tmp"):
                try:
                    os.remove(os.path.join(self.out_dir, n))
                except OSError:
                    pass

    # ---- age bookkeeping (driver thread, per round) --------------------

    def record_age(self) -> float:
        """Update the checkpoint-age gauge from the last completed
        write; called per round from the driver (host floats only)."""
        with self._lock:
            last = self.last_unix
        age = (time.time() - last) if last else -1.0
        if self.metrics is not None and last:
            self.metrics.record_checkpoint_age(age)
        return age

    def load_latest(self) -> CheckpointSnapshot | None:
        return load_latest(self.out_dir)


# ---------------------------------------------------------------------------
# loading + restore
# ---------------------------------------------------------------------------


def _load_one(manifest_path: str) -> CheckpointSnapshot:
    with open(manifest_path) as fh:
        m = json.load(fh)
    if m.get("format") != CKPT_FORMAT:
        raise ValueError(
            f"checkpoint format {m.get('format')!r} != supported "
            f"{CKPT_FORMAT}"
        )
    npz_path = manifest_path[: -len(".json")] + ".npz"
    sha = hashlib.sha256()
    with open(npz_path, "rb") as fh:
        for chunk in iter(lambda: fh.read(1 << 20), b""):
            sha.update(chunk)
    if sha.hexdigest() != m["npz_sha256"]:
        raise ValueError(f"{npz_path}: checksum mismatch (torn write?)")
    with np.load(npz_path) as z:
        blobs = {k: z[k] for k in z.files}
    warm_seed = None
    if m.get("has_warm_seed"):
        warm_seed = tuple(
            blobs[f"warm/{name}"] for name in ("asg", "lvl", "floor")
        )
    km = m["knowledge"]
    knowledge = {
        "queue_size": int(km["queue_size"]),
        "machines": {
            "buf": blobs["know_m/buf"], "sum": blobs["know_m/sum"],
            "count": blobs["know_m/count"],
            "idx": km["m_idx"], "free": km["m_free"],
            "queue_size": int(km["queue_size"]),
        },
        "tasks": {
            "buf": blobs["know_t/buf"], "sum": blobs["know_t/sum"],
            "count": blobs["know_t/count"],
            "idx": km["t_idx"], "free": km["t_free"],
            "queue_size": int(km["queue_size"]),
        },
    }
    cols = None
    if m.get("cols") is not None:
        cm = m["cols"]
        machine_names = list(cm["machine_names"])
        cols = BuilderColumns(
            machine_names=machine_names,
            midx={n: i for i, n in enumerate(machine_names)},
            racks=list(cm["racks"]),
            **{
                k: np.array(cm[k], dtype=object)
                for k in _COLS_OBJECT
            },
            **{k: blobs[f"cols/{k}"] for k in _COLS_NUMERIC},
        )
    return CheckpointSnapshot(
        round_num=int(m["round_num"]),
        cost_model=m["cost_model"],
        flags=dict(m.get("flags", {})),
        tasks=[_task_from_doc(d) for d in m["tasks"]],
        machines=[_machine_from_doc(d) for d in m["machines"]],
        knowledge=knowledge,
        pad_floors={k: int(v) for k, v in m["pad_floors"].items()},
        warm_seed=warm_seed,
        cols=cols,
        rv={k: int(v) for k, v in m.get("rv", {}).items()},
        created_unix=float(m.get("created_unix", 0.0)),
    )


def load_latest(out_dir: str) -> CheckpointSnapshot | None:
    """Newest loadable checkpoint in ``out_dir``, or None.

    Torn-write tolerant: manifests are tried newest-first; a damaged
    one (missing/corrupt npz, checksum mismatch, unparseable JSON)
    logs a warning and falls back to the previous complete checkpoint
    instead of failing the restore outright.
    """
    try:
        names = sorted(os.listdir(out_dir), reverse=True)
    except OSError:
        return None
    for name in names:
        if not (name.startswith("ckpt-") and name.endswith(".json")):
            continue
        path = os.path.join(out_dir, name)
        try:
            return _load_one(path)
        except (OSError, ValueError, KeyError) as e:
            log.warning(
                "checkpoint %s unloadable (%s); trying the previous "
                "one", path, e,
            )
    return None


def restore_bridge(bridge, snap: CheckpointSnapshot) -> dict[str, int]:
    """Rehydrate a freshly-constructed bridge from a snapshot; returns
    the checkpointed watch rv map (for ``ClusterWatcher.resume``).

    The warm solve seed is only adopted when the snapshot's cost model
    matches the bridge's — a seed priced by a different model would
    warm-start the auction from prices the first round never computed.
    Pad floors restore regardless (they are shape state, not prices).
    Mismatched preemption mode drops the builder columns the same way
    (the running block exists only in rebalancing mode).
    """
    bridge.restore_state(
        machines=snap.machines,
        tasks=snap.tasks,
        round_num=snap.round_num,
        knowledge_state=snap.knowledge,
        builder_cols=(
            snap.cols
            if bool(snap.flags.get("enable_preemption"))
            == bridge.enable_preemption
            else None
        ),
    )
    warm = snap.warm_seed
    if warm is not None and snap.cost_model != str(bridge.cost_model):
        log.warning(
            "checkpoint cost model %s != configured %s; dropping the "
            "warm solve seed (floors still restore)",
            snap.cost_model, bridge.cost_model,
        )
        warm = None
    bridge.solver.restore_for_replay(snap.pad_floors or None, warm)
    return dict(snap.rv)
