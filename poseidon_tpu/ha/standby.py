"""Leader election + the warm standby driver (``cli --standby``).

HA model: N scheduler processes share one checkpoint directory and one
k8s Lease-style lock on the apiserver (the fake apiserver implements
the coordination arbitration: grant when free, expired, or renewing
holder; 409 otherwise). Exactly one holds the lease and schedules; the
others are **warm followers** — they poll the lease AND keep the
latest checkpoint parsed in memory, so when the leader dies (stops
renewing) the winner of the next acquire restores bridge + solver +
watch position from the followed checkpoint and serves its first
round warm: no cold LIST, no cold solve, no migration storm
(tests/test_ha.py proves the takeover round is warm and
migration-free).

The leader renews the lease every tick from inside ``run_loop``; a
failed renewal (partition, a faster standby after an apiserver-side
expiry) steps down loudly — exit code 1, the supervisor restarts the
process as a follower. Split-brain is excluded by the apiserver being
the single arbiter, exactly like kube-scheduler's own HA.
"""

from __future__ import annotations

import logging
import os
import socket
import time

from poseidon_tpu.apiclient.client import ApiError, K8sApiClient
from poseidon_tpu.ha.checkpoint import CheckpointSnapshot, load_latest

log = logging.getLogger(__name__)

DEFAULT_LEASE_NAME = "poseidon-scheduler"
DEFAULT_LEASE_NAMESPACE = "kube-system"


class LeaderElector:
    """One participant's view of the Lease lock."""

    def __init__(
        self,
        client: K8sApiClient,
        *,
        name: str = DEFAULT_LEASE_NAME,
        namespace: str = DEFAULT_LEASE_NAMESPACE,
        identity: str = "",
        duration_s: float = 15.0,
    ):
        self.client = client
        self.name = name
        self.namespace = namespace
        self.identity = identity or (
            f"{socket.gethostname()}-{os.getpid()}"
        )
        self.duration_s = duration_s
        self.held = False

    def try_acquire(self) -> bool:
        """One acquisition attempt; True = this process now leads.
        The server grants when the lease is free, expired, or already
        ours (an acquire doubles as a renew)."""
        try:
            self.held = self.client.acquire_lease(
                self.name, self.identity, self.duration_s,
                namespace=self.namespace,
            )
        except ApiError as e:
            log.warning("lease acquire failed: %s", e)
            self.held = False
        return self.held

    def renew(self) -> bool:
        """Heartbeat; False = leadership LOST (step down, don't
        schedule another round)."""
        ok = self.try_acquire()
        if not ok:
            log.error(
                "lease renewal failed for %s; stepping down",
                self.identity,
            )
        return ok

    def release(self) -> None:
        """Hand the lease back (clean shutdown: the standby takes over
        after one poll instead of a full expiry window)."""
        if not self.held:
            return
        try:
            self.client.release_lease(
                self.name, self.identity, namespace=self.namespace
            )
        except ApiError as e:
            log.warning("lease release failed: %s", e)
        self.held = False


def follow_checkpoints(
    checkpoint_dir: str,
    current: CheckpointSnapshot | None,
    last_mtime: float,
) -> tuple[CheckpointSnapshot | None, float]:
    """One follower poll: reload the newest checkpoint iff a newer
    manifest appeared (mtime probe first, so the idle-follow loop costs
    a directory listing, not a full parse)."""
    newest = 0.0
    try:
        for name in os.listdir(checkpoint_dir):
            if name.startswith("ckpt-") and name.endswith(".json"):
                p = os.path.join(checkpoint_dir, name)
                try:
                    newest = max(newest, os.path.getmtime(p))
                except OSError:
                    pass
    except OSError:
        return current, last_mtime
    if newest <= last_mtime:
        return current, last_mtime
    snap = load_latest(checkpoint_dir)
    if snap is not None:
        log.info(
            "standby: following checkpoint round %d", snap.round_num
        )
        return snap, newest
    return current, last_mtime


def run_standby(args) -> int:
    """The ``--standby`` driver: follow checkpoints until the lease is
    ours, then run the normal loop warm."""
    from poseidon_tpu.cli import run_loop  # deferred: cli imports us lazily

    client = K8sApiClient(
        args.k8s_apiserver_host,
        args.k8s_apiserver_port,
        args.k8s_api_version,
        timeout_s=10.0,
    )
    elector = LeaderElector(
        client, duration_s=args.standby_lease_s
    )
    poll_s = max(args.standby_lease_s / 3.0, 0.05)
    follower: CheckpointSnapshot | None = None
    last_mtime = 0.0
    while True:
        if elector.try_acquire():
            # refresh AFTER winning: a gracefully-exiting leader
            # writes its final checkpoint and releases the lease in
            # the same breath, so the followed snapshot (last poll,
            # lease/3 ago) is nearly always one handover behind —
            # taking over on it would discard exactly the warm state
            # the final checkpoint exists to pass on
            if args.checkpoint_dir:
                fresh = load_latest(args.checkpoint_dir)
                if fresh is not None:
                    follower = fresh
            log.info(
                "standby %s acquired the lease; taking over (%s)",
                elector.identity,
                "warm from followed checkpoint" if follower is not None
                else "no checkpoint followed yet",
            )
            try:
                return run_loop(
                    args, lease=elector, preloaded=follower
                )
            finally:
                elector.release()
        if args.checkpoint_dir:
            follower, last_mtime = follow_checkpoints(
                args.checkpoint_dir, follower, last_mtime
            )
        time.sleep(poll_s)
