"""Write-ahead actuation journal: crash-consistent bind/evict POSTs.

The failure the journal closes: the driver decides a round's deltas,
starts POSTing them, and dies. Without a record, a restarted scheduler
cannot tell which placements the apiserver already accepted — it
either re-binds pods that are already Running (double-actuation) or
silently forgets placements it optimistically confirmed (a pod the
bridge believes Running that the apiserver still lists Pending is
stranded forever by the confirm-outlives-poll-latency guard).

Protocol (one JSONL file, one lock):

- ``intents(ops)`` journals EVERY delta of the batch — bind, evict,
  migrate — as ``phase="intent"`` lines and fsyncs ONCE **before the
  first byte goes on the wire**. From that point a crash anywhere
  leaves a durable record of the full intended actuation;
- ``posted(seq)`` marks the HTTP success (the apiserver has durably
  accepted the op); ``confirmed(seq)`` marks the driver having applied
  the result to bridge state; ``failed(seq)`` marks a POST the driver
  saw fail and re-queued (terminal: the pod is re-offered normally).
  These are flushed but not fsync'd — losing one costs exactly one
  idempotent replay, never a lost or doubled actuation;
- on restart, ``incomplete()`` folds the file into entries with an
  intent but no terminal record, and ``replay_journal`` re-issues each
  one **idempotently**: the current pod state is read first
  (``client.get_pod``), an op whose effect is already visible counts
  as applied, and a re-POSTed bind that answers 409-Conflict-on-the-
  same-target counts as success (apiclient/client.py) — so replay
  after any kill point yields exactly-once actuation.

Torn tails: a crash mid-write leaves a truncated FINAL line; the
reader drops it with a warning (the same contract as ``read_trace``).
An intent line torn mid-write means the POST it would have preceded
never went out — dropping it is correct, not lossy.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import threading
import time

log = logging.getLogger(__name__)

# ops vocabulary (entry "op")
OPS = ("bind", "evict", "migrate")

# replay outcome vocabulary (the poseidon_journal_replays_total label)
REPLAY_OUTCOMES = (
    "replayed",         # the op was re-issued and landed
    "already-applied",  # the apiserver already shows the op's effect
    "stale",            # the pod no longer exists; nothing to do
    "failed",           # the re-issue failed (surfaced, not retried)
    "conflict",         # pod state matches neither side; left alone
)


@dataclasses.dataclass
class JournalEntry:
    """One folded actuation: the intent plus its latest phase."""

    seq: int
    op: str                  # bind | evict | migrate
    uid: str
    machine: str = ""        # bind/migrate target
    from_machine: str = ""   # evict/migrate source
    round_num: int = 0
    phase: str = "intent"    # intent | posted | confirmed | failed
    # lifecycle seed (obs/lifecycle.py): WALL µs of the pod's event
    # receipt, journaled with the intent so a restart-replayed bind
    # closes its PRE-CRASH timeline instead of minting a new one
    # (monotonic clocks do not survive the process). 0 = not stamped.
    t_event_us: int = 0


class ActuationJournal:
    """Append-only JSONL journal with batched fsync'd intents.

    Thread-safe by one internal lock: intents and confirms come from
    the driver thread, ``posted`` marks come from the bounded binding
    POST pool (cli ``_post_bindings``).
    """

    def __init__(self, path: str, *, fsync: bool = True,
                 crash_hook=None):
        self.path = path
        self.fsync = fsync
        # fault-injection seam (crash fuzz): raising at a named point
        # simulates a process death exactly there. None in production.
        self.crash_hook = crash_hook
        self._lock = threading.Lock()
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        # repair a torn tail BEFORE reopening in append mode: a crash
        # mid-write leaves a truncated final line, and appending the
        # next record after it would merge the two into one garbage
        # line MID-file — which read_journal treats as real corruption
        # (only a torn FINAL line is forgiven). One crash must never
        # become a crash loop.
        _truncate_torn_tail(path)
        self._seq = 0
        for e in read_journal(path):
            self._seq = max(self._seq, e["seq"])
        self._fh = open(path, "a")

    def close(self) -> None:
        with self._lock:
            if not self._fh.closed:
                self._fh.close()

    # ---- the write-ahead protocol --------------------------------------

    def intents(
        self, ops: list[dict], round_num: int = 0
    ) -> dict[tuple[str, str], int]:
        """Journal a batch of intended actuations with ONE fsync;
        returns ``(op, uid) -> seq`` for the phase marks. Each op dict:
        ``{"op": "bind"|"evict"|"migrate", "uid": ..., "machine": ...,
        "from": ...}``. MUST be called before any of the POSTs go on
        the wire — that ordering is the whole crash-consistency
        contract."""
        seqs: dict[tuple[str, str], int] = {}
        if not ops:
            return seqs
        with self._lock:
            for op in ops:
                self._seq += 1
                kind = op["op"]
                if kind not in OPS:
                    raise ValueError(f"unknown journal op {kind!r}")
                seqs[(kind, op["uid"])] = self._seq
                self._fh.write(json.dumps({
                    "seq": self._seq, "phase": "intent", "op": kind,
                    "uid": op["uid"],
                    "machine": op.get("machine", ""),
                    "from": op.get("from", ""),
                    "round": round_num, "t": time.time(),
                    # wall-µs lifecycle event stamp (0 = untracked):
                    # the cross-restart e2c seed
                    "t_event_us": int(op.get("t_event_us", 0)),
                }) + "\n")
            self._fh.flush()
            fd = self._fh.fileno()
        # the fsync BARRIER runs outside the lock: holding it would
        # stall the POST pool's _mark() calls for the disk's full
        # flush latency (the PTA010 no-blocking-under-lock class).
        # Correctness is unchanged: the intent lines are ordered by
        # the buffered writes above, a concurrent _mark that slips in
        # before the barrier merely gets persisted early, and the
        # crash-consistency contract — fsync before the first byte on
        # the wire — holds because we still return only after the
        # barrier. rotate() cannot close this fd concurrently: rotate
        # and intents are both driver-thread ops.
        if self.fsync:
            os.fsync(fd)
        if self.crash_hook is not None:
            self.crash_hook("after-intent")
        return seqs

    def _mark(self, seq: int, phase: str) -> None:
        if seq <= 0:
            return
        with self._lock:
            if self._fh.closed:
                return
            self._fh.write(
                json.dumps({"seq": seq, "phase": phase}) + "\n"
            )
            self._fh.flush()

    def posted(self, seq: int) -> None:
        """The op's POST returned success (apiserver-durable)."""
        self._mark(seq, "posted")
        if self.crash_hook is not None:
            self.crash_hook("after-posted")

    def confirmed(self, seq: int) -> None:
        """The driver applied the op's result to bridge state."""
        self._mark(seq, "confirmed")

    def failed(self, seq: int) -> None:
        """The driver saw the POST fail and re-queued the pod."""
        self._mark(seq, "failed")

    # ---- restart-side reads -------------------------------------------

    def incomplete(self) -> list[JournalEntry]:
        with self._lock:
            self._fh.flush()
        return incomplete_entries(self.path)

    def discard(self) -> int:
        """Drop the journal wholesale (the ``--restore=false`` cold
        start: the operator disowned the previous boot's state, and a
        stale intent replayed against a cluster that moved on could
        evict a healthy pod days later). Returns the number of
        incomplete entries discarded — logged loudly, never silent."""
        dropped = incomplete_entries(self.path)
        with self._lock:
            self._fh.close()
            self._fh = open(self.path, "w")
        if dropped:
            log.warning(
                "journal %s: discarding %d incomplete actuation "
                "intent(s) on cold start (--restore=false): %s",
                self.path, len(dropped),
                [(e.op, e.uid) for e in dropped],
            )
        return len(dropped)

    def rotate(self) -> int:
        """Drop terminal entries (their effects live in bridge state /
        the latest checkpoint); keep incomplete ones. Called at
        checkpoint cadence so a forever-running daemon's journal stays
        bounded. Returns the number of entries kept."""
        keep = incomplete_entries(self.path)
        tmp = self.path + ".tmp"
        with self._lock:
            self._fh.flush()
            with open(tmp, "w") as fh:
                for e in keep:
                    fh.write(json.dumps({
                        "seq": e.seq, "phase": "intent", "op": e.op,
                        "uid": e.uid, "machine": e.machine,
                        "from": e.from_machine, "round": e.round_num,
                        "t_event_us": e.t_event_us,
                    }) + "\n")
                    if e.phase == "posted":
                        fh.write(json.dumps({
                            "seq": e.seq, "phase": "posted",
                        }) + "\n")
                fh.flush()
                if self.fsync:
                    # the tmp-file fsync must stay inside the lock:
                    # the lock covers the whole tmp-write -> fsync ->
                    # os.replace swap, or a _mark() landing between
                    # barrier and swap would be written to the file
                    # the replace is about to discard. rotate runs at
                    # checkpoint cadence (seconds apart), so the
                    # bounded stall is rare and sized by the journal's
                    # incomplete tail, not its full history.
                    os.fsync(fh.fileno())  # noqa: PTA010 -- lock must cover the tmp->live swap; see comment above
            self._fh.close()
            os.replace(tmp, self.path)
            self._fh = open(self.path, "a")
        return len(keep)


def _truncate_torn_tail(path: str) -> None:
    """Physically drop a crash-truncated final line so the file can be
    safely appended to. A torn write is a line prefix without its
    terminating newline (each record is one ``write`` of line+\\n), but
    a newline-terminated-yet-unparseable final line is cut the same
    way — the intent it would have preceded never went on the wire, so
    dropping it is correct, never lossy."""
    try:
        size = os.path.getsize(path)
    except OSError:
        return
    if size == 0:
        return
    with open(path, "rb+") as fh:
        data = fh.read()
        cut = size
        if not data.endswith(b"\n"):
            # torn tail: drop the unterminated prefix
            cut = data.rfind(b"\n") + 1
        else:
            # a terminated-but-unparseable FINAL line is cut the same
            # way; mid-file garbage is NOT repaired here — it cannot
            # arise from append semantics, so read_journal raising on
            # it is the honest outcome
            last_start = data.rfind(b"\n", 0, size - 1) + 1
            try:
                json.loads(data[last_start:])
            except json.JSONDecodeError:
                cut = last_start
        if cut != size:
            log.warning(
                "journal %s: truncating torn tail (%d of %d bytes "
                "kept; crash mid-write?)", path, cut, size,
            )
            fh.truncate(cut)


def read_journal(path: str) -> list[dict]:
    """Raw journal lines, torn-final-line tolerant."""
    if not os.path.exists(path):
        return []
    out: list[dict] = []
    pending_error = None
    with open(path) as fh:
        for line in fh:
            if not line.strip():
                continue
            if pending_error is not None:
                raise pending_error
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError as e:
                pending_error = e
                continue
    if pending_error is not None:
        log.warning(
            "journal %s: dropping truncated final line (crash "
            "mid-write?)", path,
        )
    return out


def incomplete_entries(path: str) -> list[JournalEntry]:
    """Fold the journal; entries with an intent but no terminal
    (confirmed/failed) record, in seq order."""
    entries: dict[int, JournalEntry] = {}
    for doc in read_journal(path):
        seq = int(doc.get("seq", 0))
        phase = doc.get("phase", "")
        if phase == "intent":
            entries[seq] = JournalEntry(
                seq=seq, op=doc.get("op", ""), uid=doc.get("uid", ""),
                machine=doc.get("machine", ""),
                from_machine=doc.get("from", ""),
                round_num=int(doc.get("round", 0)),
                t_event_us=int(doc.get("t_event_us", 0)),
            )
        elif seq in entries:
            entries[seq].phase = phase
    return [
        e for _, e in sorted(entries.items())
        if e.phase in ("intent", "posted")
    ]


def replay_journal(
    client, entries: list[JournalEntry], *, journal=None,
    trace=None, metrics=None, lifecycle=None,
) -> dict[str, int]:
    """Re-issue incomplete actuations idempotently (restart path).

    For each entry the pod's CURRENT apiserver state decides:

    - effect already visible (bound to the target / already off the
      source) -> ``already-applied``, nothing sent;
    - pod still in the pre-op state -> the op is re-POSTed
      (``replayed``); a concurrent duplicate collapses to success via
      the 409-same-target rule in ``bind_pod_to_node``;
    - pod gone -> ``stale``; pod in a third state (another writer) ->
      ``conflict``, left alone for the observe path to reconcile.

    Returns outcome counts; each entry also emits a JOURNAL_REPLAY
    trace event and ticks ``poseidon_journal_replays_total{outcome}``.
    When the live ``journal`` rides along, settled entries (replayed /
    already-applied / stale) are marked terminal so the NEXT restart
    does not replay them again; failed/conflict entries stay
    incomplete on purpose — they retry at the next boot.
    """
    counts = dict.fromkeys(REPLAY_OUTCOMES, 0)
    for e in entries:
        outcome = _replay_one(client, e)
        counts[outcome] += 1
        if journal is not None and outcome in (
            "replayed", "already-applied", "stale"
        ):
            journal.confirmed(e.seq)
        if (
            lifecycle is not None and e.op == "bind"
            and outcome in ("replayed", "already-applied")
        ):
            # the pre-crash timeline closes here: e2c measured from
            # the journaled wall stamp under lane="restart"
            # (obs/lifecycle.py's documented clock-contract exception)
            lifecycle.close_replayed(e.uid, e.t_event_us)
        if trace is not None:
            trace.emit(
                "JOURNAL_REPLAY", task=e.uid, machine=e.machine,
                round_num=e.round_num,
                detail={"op": e.op, "outcome": outcome,
                        "from": e.from_machine},
            )
        if metrics is not None:
            metrics.record_journal_replay(outcome)
        log.info(
            "journal replay: %s %s -> %s: %s",
            e.op, e.uid, e.machine or e.from_machine, outcome,
        )
    if trace is not None:
        trace.flush()
    return counts


def _replay_one(client, e: JournalEntry) -> str:
    pod = client.get_pod(e.uid)
    if pod is None:
        return "stale"
    if e.op == "bind":
        if pod.machine == e.machine:
            return "already-applied"
        if pod.machine:
            return "conflict"  # bound elsewhere: not ours to undo
        return "replayed" if client.bind_pod_to_node(
            e.uid, e.machine, namespace=pod.namespace
        ) else "failed"
    if e.op == "evict":
        if not pod.machine:
            return "already-applied"
        if e.from_machine and pod.machine != e.from_machine:
            return "conflict"
        return "replayed" if client.evict_pod(
            e.uid, namespace=pod.namespace
        ) else "failed"
    if e.op == "migrate":
        if pod.machine == e.machine:
            return "already-applied"
        if pod.machine and pod.machine != e.from_machine:
            return "conflict"
        ok = True
        if pod.machine == e.from_machine:
            ok = client.evict_pod(e.uid, namespace=pod.namespace)
        ok = ok and client.bind_pod_to_node(
            e.uid, e.machine, namespace=pod.namespace
        )
        return "replayed" if ok else "failed"
    return "conflict"
