"""Scheduler bridge: pod/node state machine driving the TPU solver.

The first-party core of the reference (src/firmament/scheduler_bridge.cc)
re-expressed around ``solve_scheduling``: nodes/pods observed from the
apiserver become the ``ClusterState`` the graph builder prices, one flow
solve per round turns into placement deltas, and per-round statistics are
collected instead of dropped (the reference requests ``SchedulerStats``
and never reads it, scheduler_bridge.cc:170-172).

Deliberate fixes over the reference's semantics:

- **Restart reconcile.** The reference CHECK-crashes when it restarts and
  meets an already-Running pod it has no binding for
  (scheduler_bridge.cc:146-147, pod_to_node_map_ lookup). Here a Running
  pod observed with a node binding is adopted as state (the apiserver is
  the source of truth, SURVEY §5.4) and its machine's capacity is
  discounted.
- **Node removal.** The reference only ever adds resources
  (scheduler_bridge.cc:81-111). Here a node that disappears from a poll
  releases its machine; its Running pods flip back to Pending (they will
  be re-placed) and are logged as evictions.
- **Succeeded/Failed handling.** The reference TODO-stubs Succeeded and
  ignores Failed (scheduler_bridge.cc:151-157). Here both retire the
  task and free its slot.
- **Starvation pressure.** ``wait_rounds`` grows for every pod that a
  round leaves unscheduled, feeding the Quincy/CoCo unscheduled-cost
  terms so parked pods eventually win a slot (the aging input the
  round-2 advisor found dead, ADVICE.md item 4).
- **Mass-eviction guard.** A poll whose snapshot would remove more than
  half of the known nodes or pods is held (upserts still apply, the
  disappearances don't) until the shrink persists for
  ``SHRINK_STRIKES`` consecutive polls. A truncated list response —
  an apiserver bug, a dropped page, a mid-rollover partial cache —
  otherwise reads as mass deletion and wipes scheduler state in one
  tick. The reference trusts every snapshot blindly
  (k8s_api_client.cc:100-160).
"""

from __future__ import annotations

import collections
import dataclasses
import logging
import time

import numpy as np

from poseidon_tpu.cluster import ClusterState, Machine, Task, TaskPhase
from poseidon_tpu.graph.builder import FlowGraphBuilder
from poseidon_tpu.models.knowledge import (
    KnowledgeBase,
    MachineSample,
    TaskSample,
)
from poseidon_tpu.ops.resident import ResidentSolver
from poseidon_tpu.trace import TraceGenerator

log = logging.getLogger(__name__)

# Mass-eviction guard: hold a >50% disappearance (of at least
# SHRINK_MIN_KNOWN known entities) unless it repeats this many polls.
SHRINK_STRIKES = 3
SHRINK_MIN_KNOWN = 8


@dataclasses.dataclass
class SchedulerStats:
    """Per-round statistics (the reference collects these and drops
    them; here they are the observability surface, SURVEY §5.1/§5.5)."""

    round_num: int = 0
    pods_total: int = 0
    pods_pending: int = 0
    pods_placed: int = 0
    pods_unscheduled: int = 0
    evictions: int = 0
    cost: int = 0
    backend: str = ""
    build_ms: float = 0.0
    price_ms: float = 0.0
    solve_ms: float = 0.0
    decompose_ms: float = 0.0
    total_ms: float = 0.0


@dataclasses.dataclass
class RoundResult:
    """One scheduling round's output: bindings to POST + stats."""

    bindings: dict[str, str]          # pod uid -> machine name (new PLACEs)
    stats: SchedulerStats
    unscheduled: list[str]            # pods left pending this round


class SchedulerBridge:
    """Owns cluster state across rounds and runs the scheduling loop."""

    def __init__(
        self,
        cost_model: str = "quincy",
        *,
        max_tasks_per_machine: int = 10,
        sample_queue_size: int = 100,
        trace: TraceGenerator | None = None,
        solver_timeout_s: float = 1000.0,
        small_to_oracle: bool = True,
    ):
        self.cost_model = cost_model
        self.max_tasks_per_machine = max_tasks_per_machine
        self.trace = trace or TraceGenerator()
        self.knowledge = KnowledgeBase(queue_size=sample_queue_size)
        self.machines: dict[str, Machine] = {}
        self.tasks: dict[str, Task] = {}
        self.pod_to_machine: dict[str, str] = {}
        self.round_num = 0
        # device-resident solve chain; its warm DenseState lives on HBM
        # across rounds (the reference's --run_incremental_scheduler seam)
        self.solver = ResidentSolver(
            oracle_timeout_s=solver_timeout_s,
            small_to_oracle=small_to_oracle,
        )
        # bounded: a daemon running forever must not grow without bound
        # (full history goes to the trace stream when a sink is set)
        self.decision_log: collections.deque[tuple[int, str, str]] = (
            collections.deque(maxlen=100_000)
        )
        self._evictions_this_round = 0
        # consecutive implausible-shrink polls (mass-eviction guard)
        self._node_shrink_strikes = 0
        self._pod_shrink_strikes = 0

    def _hold_shrink(self, counter: str, kind: str, known: int,
                     gone: int) -> bool:
        """Mass-eviction guard: True = hold this poll's disappearances.

        ``known`` is the entity count BEFORE the poll's upserts — a
        truncated snapshot that also carries new names must not inflate
        the denominator and slip past the threshold.
        """
        if known < SHRINK_MIN_KNOWN or gone * 2 <= known:
            setattr(self, counter, 0)
            return False
        strikes = getattr(self, counter) + 1
        setattr(self, counter, strikes)
        if strikes < SHRINK_STRIKES:
            log.warning(
                "%s snapshot lost %d of %d known; holding (strike "
                "%d/%d) — truncated list response?",
                kind, gone, known, strikes, SHRINK_STRIKES,
            )
            return True
        log.warning(
            "%s shrink persisted %d polls; accepting it as real",
            kind, strikes,
        )
        setattr(self, counter, 0)
        return False

    # ---- observation (the poll side) -----------------------------------

    def observe_nodes(self, nodes: list[Machine]) -> None:
        """Upsert machines; release the ones that disappeared."""
        known_before = len(self.machines)
        known_names = set(self.machines)
        seen = set()
        for node in nodes:
            if node.max_tasks <= 0:
                node = dataclasses.replace(
                    node, max_tasks=self.max_tasks_per_machine
                )
            seen.add(node.name)
            if node.name not in self.machines:
                log.info("new node %s (rack=%s)", node.name, node.rack)
            self.machines[node.name] = node
            cap = max(node.cpu_capacity, 1e-9)
            mem_cap = max(node.memory_capacity_kb, 1)
            self.knowledge.add_machine_sample(
                node.name,
                MachineSample(
                    cpu_idle=min(node.cpu_allocatable / cap, 1.0),
                    mem_free_frac=min(
                        node.memory_allocatable_kb / mem_cap, 1.0
                    ),
                ),
            )
        gone = known_names - seen
        if self._hold_shrink(
            "_node_shrink_strikes", "node", known_before, len(gone)
        ):
            return
        for name in gone:
            log.warning("node %s removed; evicting its tasks", name)
            del self.machines[name]
            self.knowledge.retire_machine(name)
            for uid, task in list(self.tasks.items()):
                if task.machine == name:
                    self.tasks[uid] = dataclasses.replace(
                        task, phase=TaskPhase.PENDING, machine=""
                    )
                    self.pod_to_machine.pop(uid, None)
                    self.trace.emit("EVICT", task=uid, machine=name,
                                    round_num=self.round_num)
                    self._evictions_this_round += 1

    def observe_pods(self, pods: list[Task]) -> None:
        """The reference's per-pod dispatch (scheduler_bridge.cc:132-162),
        with restart reconcile and terminal-state retirement."""
        known_before = len(self.tasks)
        known_uids = set(self.tasks)
        seen = set()
        for pod in pods:
            seen.add(pod.uid)
            known = self.tasks.get(pod.uid)
            if pod.phase == TaskPhase.PENDING:
                if known is None:
                    log.info("new pending pod %s", pod.uid)
                    self.trace.emit("SUBMIT", task=pod.uid,
                                    round_num=self.round_num)
                    self.tasks[pod.uid] = pod
                elif (
                    known.phase == TaskPhase.RUNNING and known.machine
                ):
                    # a locally-confirmed binding outlives apiserver
                    # poll latency: the pod still reads Pending until
                    # the watch cache catches up, and downgrading here
                    # would re-schedule it (double-binding + the slot
                    # discount lost)
                    pass
                else:
                    # keep our aging counter across polls
                    self.tasks[pod.uid] = dataclasses.replace(
                        pod, wait_rounds=known.wait_rounds
                    )
            elif pod.phase == TaskPhase.RUNNING:
                if pod.machine and pod.machine not in self.machines:
                    # The apiserver still reports a binding to a node we
                    # no longer know (removed in observe_nodes). Adopting
                    # it would silently undo the eviction and park the
                    # pod on a ghost machine forever; keep it Pending
                    # (aging preserved) so the next round re-places it.
                    log.warning(
                        "pod %s bound to unknown node %s; keeping it "
                        "Pending for re-placement", pod.uid, pod.machine,
                    )
                    wait = known.wait_rounds if known is not None else 0
                    self.tasks[pod.uid] = dataclasses.replace(
                        pod, phase=TaskPhase.PENDING, machine="",
                        wait_rounds=wait,
                    )
                    self.pod_to_machine.pop(pod.uid, None)
                    continue
                if known is None or known.machine != pod.machine:
                    # restart reconcile: adopt the apiserver's binding
                    # instead of the reference's CHECK-crash
                    # (scheduler_bridge.cc:146-147)
                    log.info(
                        "adopting running pod %s on %s",
                        pod.uid, pod.machine,
                    )
                self.tasks[pod.uid] = pod
                if pod.machine:
                    self.pod_to_machine[pod.uid] = pod.machine
                self.knowledge.add_task_sample(
                    pod.uid,
                    TaskSample(
                        cpu_usage=pod.cpu_request,
                        mem_usage_kb=pod.memory_request_kb,
                    ),
                )
            else:  # Succeeded / Failed / Unknown: retire, free the slot
                if known is not None:
                    log.info("retiring pod %s (%s)", pod.uid, pod.phase)
                    self.trace.emit("FINISH", task=pod.uid,
                                    machine=known.machine,
                                    round_num=self.round_num,
                                    detail={"phase": str(pod.phase.value)})
                    self.tasks.pop(pod.uid, None)
                    self.pod_to_machine.pop(pod.uid, None)
                    self.knowledge.retire_task(pod.uid)
        gone = known_uids - seen
        if self._hold_shrink(
            "_pod_shrink_strikes", "pod", known_before, len(gone)
        ):
            return
        for uid in gone:
            self.tasks.pop(uid, None)
            self.pod_to_machine.pop(uid, None)
            self.knowledge.retire_task(uid)

    # ---- the scheduling round ------------------------------------------

    def cluster_state(self) -> ClusterState:
        return ClusterState(
            machines=list(self.machines.values()),
            tasks=list(self.tasks.values()),
        )

    def run_scheduler(self) -> RoundResult:
        """One round: build -> price -> solve -> deltas (the reference's
        RunScheduler + ScheduleAllJobs, scheduler_bridge.cc:129-192)."""
        self.round_num += 1
        stats = SchedulerStats(round_num=self.round_num)
        stats.evictions = self._evictions_this_round
        self._evictions_this_round = 0
        t_start = time.perf_counter()

        cluster = self.cluster_state()
        pending = cluster.pending()
        stats.pods_total = len(cluster.tasks)
        stats.pods_pending = len(pending)
        if not self.machines or not pending:
            stats.total_ms = (time.perf_counter() - t_start) * 1000
            self.trace.emit(
                "ROUND", round_num=self.round_num,
                detail=dataclasses.asdict(stats),
            )
            self.trace.flush()
            return RoundResult(bindings={}, stats=stats, unscheduled=[])

        t0 = time.perf_counter()
        arrays, meta = FlowGraphBuilder().build_arrays(cluster)
        stats.build_ms = (time.perf_counter() - t0) * 1000

        machine_names = [m.name for m in cluster.machines]
        outcome = self.solver.run_round(
            arrays, meta,
            cost_model=self.cost_model,
            cost_input_kwargs=dict(
                task_cpu_milli=np.array(
                    [int(t.cpu_request * 1000) for t in pending]
                ),
                task_mem_kb=np.array(
                    [t.memory_request_kb for t in pending]
                ),
                task_usage=self.knowledge.task_cpu_usage(
                    [t.uid for t in pending]
                ),
                machine_load=self.knowledge.machine_load(machine_names),
                machine_mem_free=self.knowledge.machine_mem_free(
                    machine_names
                ),
            ),
        )
        # phase accounting: prep+upload feed the price column, the pure
        # device compute is the solve column, the result download the
        # decompose column (transfer vs compute stays distinguishable)
        stats.price_ms = (
            outcome.timings.get("prep_ms", 0.0)
            + outcome.timings.get("upload_ms", 0.0)
        )
        stats.solve_ms = outcome.timings.get("solve_ms", 0.0)
        stats.decompose_ms = (
            outcome.timings.get("fetch_ms", 0.0)
            + outcome.timings.get("oracle_ms", 0.0)
        )
        stats.backend = outcome.backend
        stats.cost = outcome.cost

        names = meta.machine_names
        placements = {
            uid: (names[m] if m >= 0 else None)
            for uid, m in zip(meta.task_uids, outcome.assignment)
        }

        bindings: dict[str, str] = {}
        unscheduled: list[str] = []
        for uid, machine in placements.items():
            task = self.tasks.get(uid)
            if task is None:
                continue
            if machine is None:
                # aging: parked pods push harder next round (the
                # Quincy/CoCo unscheduled-cost input)
                self.tasks[uid] = dataclasses.replace(
                    task, wait_rounds=task.wait_rounds + 1
                )
                unscheduled.append(uid)
            else:
                bindings[uid] = machine
                self.decision_log.append((self.round_num, uid, machine))
                self.trace.emit("SCHEDULE", task=uid, machine=machine,
                                round_num=self.round_num)
                log.info(
                    "round %d: PLACE %s -> %s",
                    self.round_num, uid, machine,
                )
        stats.pods_placed = len(bindings)
        stats.pods_unscheduled = len(unscheduled)
        stats.total_ms = (time.perf_counter() - t_start) * 1000
        self.trace.emit(
            "ROUND", round_num=self.round_num,
            detail=dataclasses.asdict(stats),
        )
        self.trace.flush()
        return RoundResult(
            bindings=bindings, stats=stats, unscheduled=unscheduled
        )

    @property
    def solver_timeout_s(self) -> float:
        """Oracle-fallback budget; delegates to the live solver (the
        reference's --max_solver_runtime, poseidon.cfg:14-15)."""
        return self.solver.oracle_timeout_s

    @solver_timeout_s.setter
    def solver_timeout_s(self, value: float) -> None:
        self.solver.oracle_timeout_s = value

    @property
    def warm_state(self):
        """The solver's on-HBM warm handle (assign None to force cold)."""
        return self.solver.warm

    @warm_state.setter
    def warm_state(self, value) -> None:
        if value is not None:
            raise ValueError(
                "warm_state is device-owned; only None (reset) is "
                "assignable"
            )
        self.solver.reset()

    def confirm_binding(self, uid: str, machine: str) -> None:
        """Caller reports a successful bindings POST: mark Running so the
        next build discounts the slot even before the poll reflects it."""
        task = self.tasks.get(uid)
        if task is not None:
            self.tasks[uid] = dataclasses.replace(
                task, phase=TaskPhase.RUNNING, machine=machine
            )
            self.pod_to_machine[uid] = machine
