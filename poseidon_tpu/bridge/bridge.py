"""Scheduler bridge: pod/machine state machine driving the TPU solver.

The first-party core of the reference (src/firmament/scheduler_bridge.cc)
re-expressed around ``solve_scheduling``: nodes/pods observed from the
apiserver become the ``ClusterState`` the graph builder prices, one flow
solve per round turns into placement deltas, and per-round statistics are
collected instead of dropped (the reference requests ``SchedulerStats``
and never reads it, scheduler_bridge.cc:170-172).

Deliberate fixes over the reference's semantics:

- **Restart reconcile.** The reference CHECK-crashes when it restarts and
  meets an already-Running pod it has no binding for
  (scheduler_bridge.cc:146-147, pod_to_node_map_ lookup). Here a Running
  pod observed with a node binding is adopted as state (the apiserver is
  the source of truth, SURVEY §5.4) and its machine's capacity is
  discounted.
- **Node removal.** The reference only ever adds resources
  (scheduler_bridge.cc:81-111). Here a node that disappears from a poll
  releases its machine; its Running pods flip back to Pending (they will
  be re-placed) and are logged as evictions.
- **Succeeded/Failed handling.** The reference TODO-stubs Succeeded and
  ignores Failed (scheduler_bridge.cc:151-157). Here both retire the
  task and free its slot.
- **Starvation pressure.** ``wait_rounds`` grows for every pod that a
  round leaves unscheduled, feeding the Quincy/CoCo unscheduled-cost
  terms so parked pods eventually win a slot (the aging input the
  round-2 advisor found dead, ADVICE.md item 4).
- **Mass-eviction guard.** A poll whose snapshot would remove more than
  half of the known nodes or pods is held (upserts still apply, the
  disappearances don't) until the shrink persists for
  ``SHRINK_STRIKES`` consecutive polls. A truncated list response —
  an apiserver bug, a dropped page, a mid-rollover partial cache —
  otherwise reads as mass deletion and wipes scheduler state in one
  tick. The reference trusts every snapshot blindly
  (k8s_api_client.cc:100-160).

Round pipeline (PERF.md "Round pipeline"): the round is split into
``begin_round`` (graph build + cost-input prep + async solve dispatch;
the placement download starts immediately on a background thread) and
``finish_round`` (join the fetch, apply placement deltas). Serial
callers use ``run_scheduler`` — exactly ``finish_round(begin_round())``
— while pipelined drivers (cli.py, bench.py config 4) do next-round
host work (poll parse, observe, KnowledgeBase feed, binding POSTs of
the previous round) between the two calls, so this environment's flat
~100 ms sync floor elapses under host work instead of serializing
after it. State mutations keep the serial order — observations commute
with the previous round's placement deltas (verified by the
pipelined-vs-serial equivalence test in tests/test_bridge.py), and
``finish_round`` drops placements whose pod the overlap window's poll
already moved (retired, or adopted Running elsewhere) rather than
clobbering observed truth — so pipelining changes round latency, never
placements or costs.

Graph builds are O(churn), not O(cluster): every pod/machine state
transition the bridge applies is mirrored into an
``IncrementalFlowGraphBuilder`` note, and ``begin_round`` patches the
previous round's builder columns instead of re-walking every task
object (``incremental_build=False`` restores the legacy full rebuild).

Event-driven observe (the watch path, apiclient/watch.py): instead of
re-diffing a full poll snapshot every tick, drivers may feed typed
``ADDED | MODIFIED | DELETED`` events through ``observe_node_event`` /
``observe_pod_event``. Both paths share the same per-entity upsert and
removal helpers, so an event drives the exact same state transitions
and incremental-builder churn notes as the poll diff would — a
watch-driven round is bit-identical to a poll-driven one over the same
event history (tests/test_watch.py differential). The mass-eviction
guard is a *snapshot* defense (an explicit DELETED event is not a
truncated list), so events bypass it; a watch resync replays the full
snapshot through ``observe_nodes`` / ``observe_pods`` and gets the
guard back. Observe host time is accumulated into the next round's
``SchedulerStats.observe_ms``, and the watcher's degradation counters
land in ``watch_resyncs`` / ``watch_reconnects`` via
``note_watch_activity``.

Rebalancing (``enable_preemption=True``): running tasks enter the flow
graph with a hysteresis-discounted continuation arc and a priced
unscheduled arc (graph/builder.py rebalancing mode), and each round's
solved assignment is diffed against current placements into typed
``PLACE | MIGRATE | PREEMPT | NOOP`` deltas (graph/deltas.py) under a
per-round ``max_migrations_per_round`` churn budget. The bridge emits
the decisions (``RoundResult.migrations`` / ``.preemptions``); the
driver actuates them against the apiserver (MIGRATE = eviction POST +
re-bind, PREEMPT = eviction POST) and reports back through
``confirm_migration`` / ``confirm_preemption`` / ``restore_running``,
mirroring the existing ``confirm_binding`` / ``revoke_binding``
contract for PLACE. With the flag off, behavior is byte-identical to
place-only scheduling.
"""

from __future__ import annotations

import collections
import dataclasses
import logging
import time

import numpy as np

from poseidon_tpu.cluster import ClusterState, Machine, Task, TaskPhase
from poseidon_tpu.graph.builder import (
    FlowGraphBuilder,
    IncrementalFlowGraphBuilder,
)
from poseidon_tpu.graph.deltas import extract_deltas
from poseidon_tpu.models.knowledge import (
    KnowledgeBase,
    MachineSample,
    TaskSample,
)
from poseidon_tpu.guards import FetchTimeout
from poseidon_tpu.ops.resident import (
    ExpressArrival,
    ExpressBatch,
    ExpressDegrade,
    InflightSolve,
    ResidentSolver,
)
from poseidon_tpu.obs.metrics import STORM_RESYNCS, STORM_WINDOW
from poseidon_tpu.obs.spans import (
    emit_span,
    express_span_tree,
    round_span_tree,
    stream_span_tree,
)
from poseidon_tpu.ops.transport import topology_from_columns
from poseidon_tpu.trace import TraceGenerator

log = logging.getLogger(__name__)

# Mass-eviction guard: hold a >50% disappearance (of at least
# SHRINK_MIN_KNOWN known entities) unless it repeats this many polls.
SHRINK_STRIKES = 3
SHRINK_MIN_KNOWN = 8
# The guard's time-based exit (the NotReady grace window): a held
# shrink that persists this many seconds is accepted as true death
# even before SHRINK_STRIKES polls land — watch-mode daemons resync
# rarely, so a purely poll-counted guard could hold a real rack loss
# for as long as the streams stay healthy. 0 disables the time exit.
SHRINK_GRACE_S = 45.0


@dataclasses.dataclass
class SchedulerStats:
    """Per-round statistics (the reference collects these and drops
    them; here they are the observability surface, SURVEY §5.1/§5.5).

    ``total_ms`` is the round's HOST critical path: time spent inside
    ``begin_round`` plus time spent inside ``finish_round`` — for a
    serial round that is the whole round, for a pipelined round it
    excludes the overlap window where the host was doing other work.
    The overlap-model companions: ``build_mode`` ("delta" | "full" |
    "legacy"), ``dispatch_ms`` (prep + upload + async dispatch inside
    the solver), ``fetch_wait_ms`` (the part of the placement download
    the host actually blocked on — the sync floor minus whatever the
    overlap already absorbed), ``overlap_ms`` (wall time between
    begin and finish, i.e. how much host work was hidden), and
    ``wall_ms`` (begin start to finish end, the round's wall span).
    """

    round_num: int = 0
    pods_total: int = 0
    pods_pending: int = 0
    pods_placed: int = 0
    pods_unscheduled: int = 0
    evictions: int = 0
    # rebalancing delta counts (graph/deltas.py vocabulary; all zero in
    # place-only mode except deltas_place == pods_placed)
    deltas_place: int = 0
    deltas_migrate: int = 0
    deltas_preempt: int = 0
    deltas_noop: int = 0
    deltas_deferred: int = 0
    # placement/migration POSTs the driver reported failed since the
    # previous round (the pods were re-queued, not silently believed
    # placed). During a declared apiserver outage, unreachable POSTs
    # park in the actuation outbox instead (ha/outbox.py) and do NOT
    # count here until they dead-letter — so an outage reads as one
    # episode, not a failure per pod per round.
    bind_failures: int = 0
    # staged node-death re-queue (the mass-eviction guard's exit):
    # displaced RUNNING pods admitted into this round's schedulable
    # set, and the backlog still parked awaiting a later wave
    requeue_admitted: int = 0
    displaced_parked: int = 0
    # actuations parked in the driver's outbox when this round was
    # logged (cli stamps it; 0 without an outbox) — the chaos
    # harness's time-to-recovered clock includes the drain
    outbox_pending: int = 0
    # watch-mode degradation counters since the previous round: full
    # LIST resyncs (410 Gone / decode error / staleness) and error-path
    # stream reconnects (apiclient/watch.py; zero in poll mode)
    watch_resyncs: int = 0
    watch_reconnects: int = 0
    # pipelined placement fetches that missed their
    # --max_solver_runtime deadline since the previous round (each one
    # abandoned its round loudly: FETCH_TIMEOUT trace event + this)
    fetch_timeouts: int = 0
    # lifetime count of dense-lane degrades to the CPU oracle
    # (memory-envelope / cost-domain / uncertified — NOT the deliberate
    # small-instance routing); each one also emits a DEGRADE trace
    # event, so oversize rounds are observable, not just logged
    degrades_total: int = 0
    # express-lane activity: batches dispatched, pods placed between
    # ticks, batches that degraded to the round path (EXPRESS_DEGRADE),
    # and the event-to-bind-decision latency accumulator's p50/p99
    # over the window (ms) — all counted since the previous round —
    # plus the express placements THIS round's correction pass moved
    # (EXPRESS_CORRECTED, counted by the round that corrects them)
    express_batches: int = 0
    express_places: int = 0
    express_corrected: int = 0
    express_degrades: int = 0
    express_e2b_p50_ms: float = 0.0
    express_e2b_p99_ms: float = 0.0
    cost: int = 0
    backend: str = ""
    # which driver lane produced this round (set by the driver via
    # ``SchedulerBridge.lane``: poll / watch / +pipelined / express /
    # +sharded / +agg compositions) — the metrics/report grouping key
    lane: str = ""
    # host time spent in observe_* (poll snapshot diff or watch event
    # application) since the previous round — the observe phase the
    # per-phase timers were missing (build/price/solve/decompose never
    # covered the snapshot walk)
    observe_ms: float = 0.0
    build_ms: float = 0.0
    price_ms: float = 0.0
    solve_ms: float = 0.0
    decompose_ms: float = 0.0
    total_ms: float = 0.0
    build_mode: str = ""
    dispatch_ms: float = 0.0
    fetch_wait_ms: float = 0.0
    overlap_ms: float = 0.0
    wall_ms: float = 0.0


@dataclasses.dataclass
class RoundResult:
    """One scheduling round's output: deltas to actuate + stats."""

    bindings: dict[str, str]          # pod uid -> machine name (new PLACEs)
    stats: SchedulerStats
    unscheduled: list[str]            # pods left pending this round
    # rebalancing decisions (empty in place-only mode): the driver
    # actuates these against the apiserver and confirms back
    migrations: dict[str, tuple[str, str]] = dataclasses.field(
        default_factory=dict)         # uid -> (from_machine, to_machine)
    preemptions: dict[str, str] = dataclasses.field(
        default_factory=dict)         # uid -> from_machine


@dataclasses.dataclass
class ExpressResult:
    """One express batch's actuatable output (the fast-path analog of
    ``RoundResult``): bindings to POST now, plus the batch's exact cost
    and repair-round count for observability. Stats ride on the NEXT
    full round's ``SchedulerStats`` (express counters + the
    event-to-bind accumulator)."""

    bindings: dict[str, str]
    cost: int = 0
    rounds: int = 0
    latency_ms: float = 0.0
    timings: dict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class InflightRound:
    """A begun-but-not-finished scheduling round (solve in flight)."""

    stats: SchedulerStats
    result: RoundResult | None = None   # set when the round completed
                                        # synchronously (nothing to do)
    solve: InflightSolve | None = None
    meta: object = None                 # GraphMeta of this round's build
    t_begin_start: float = 0.0
    t_begin_end: float = 0.0
    begin_ms: float = 0.0
    # the flight recorder's begin-time record of this round's inputs
    # (obs/flightrec.py); finish_round attaches the outcome to it
    flight: object = None


class SchedulerBridge:
    """Owns cluster state across rounds and runs the scheduling loop."""

    def __init__(
        self,
        cost_model: str = "quincy",
        *,
        max_tasks_per_machine: int = 10,
        sample_queue_size: int = 100,
        trace: TraceGenerator | None = None,
        solver_timeout_s: float = 1000.0,
        small_to_oracle: bool = True,
        incremental_build: bool = True,
        enable_preemption: bool = False,
        migration_hysteresis: int = 20,
        max_migrations_per_round: int = 64,
        mesh_width: int = 0,
        aggregate_classes: bool = False,
        topk_prefs: int = 0,
        express_lane: bool = False,
        express_max_batch: int = 16,
        stream_windows: int = 0,
        shrink_grace_s: float = SHRINK_GRACE_S,
        metrics=None,
        profile_spans: bool = False,
        solver=None,
        flightrec=None,
        lifecycle=None,
        auditor=None,
    ):
        self.cost_model = cost_model
        self.max_tasks_per_machine = max_tasks_per_machine
        self.enable_preemption = enable_preemption
        self.migration_hysteresis = migration_hysteresis
        self.max_migrations_per_round = max_migrations_per_round
        self.express_lane = express_lane
        self.stream_windows = stream_windows
        self.trace = trace or TraceGenerator()
        # observability: ``metrics`` is an obs.SchedulerMetrics (or
        # None); recording happens ONLY at finish/actuate time from
        # host values this class already holds — no new device syncs
        # (the recording helpers are PTA001/PTA002 scopes).
        # ``profile_spans`` emits a SPAN trace event per round/express
        # batch (--trace_profile); ``lane`` is a driver-set label
        # (poll / watch / +pipelined / express ...) stamped onto each
        # round's stats for the metrics/report grouping.
        self.metrics = metrics
        self.profile_spans = profile_spans
        # the anomaly flight recorder (obs/flightrec.py, --flight_
        # recorder): captures each round's full host-side inputs at
        # begin time and dumps the ring on DEGRADE / EXPRESS_DEGRADE /
        # FETCH_TIMEOUT / resync-storm or on demand. None = off, zero
        # cost.
        self.flightrec = flightrec
        # the quality observatory (obs/lifecycle.py, obs/audit.py):
        # ``lifecycle`` stamps per-pod timelines at every stage the
        # bridge drives (event/decided/confirmed — the cli stamps the
        # journal/POST stages it owns); ``auditor`` captures a sampled
        # cluster snapshot per cadence for the background shadow
        # re-solve. Both None = off, zero cost.
        self.lifecycle = lifecycle
        self.auditor = auditor
        # trace-ring overwrites already mirrored into the metrics
        # counter (per-round delta against trace.dropped_total)
        self._trace_drops_seen = 0
        # the watch stream position recorded with each round's flight
        # record (driver-set: cli stamps ClusterWatcher.applied_rv per
        # tick; "" = poll mode / no driver stamp)
        self.flight_rv = ""
        self.lane = ""
        self.knowledge = KnowledgeBase(queue_size=sample_queue_size)
        self.machines: dict[str, Machine] = {}
        self.tasks: dict[str, Task] = {}
        self.pod_to_machine: dict[str, str] = {}
        self.round_num = 0
        # device-resident solve chain; its warm DenseState lives on HBM
        # across rounds (the reference's --run_incremental_scheduler
        # seam). The scale lane rides here too: mesh_width shards the
        # round's task axis over a device mesh, aggregate_classes/
        # topk_prefs shrink the machine/pref axes (graph/aggregate.py).
        # ``solver`` injects a different implementation of the same
        # begin/finish seam — the multi-tenant service routes every
        # tenant bridge through a shared batching dispatcher this way
        # (service/dispatch.TenantSolver); None = own ResidentSolver.
        self.solver = solver if solver is not None else ResidentSolver(
            oracle_timeout_s=solver_timeout_s,
            small_to_oracle=small_to_oracle,
            mesh_width=mesh_width,
            aggregate_classes=aggregate_classes,
            topk_prefs=topk_prefs,
            express_lane=express_lane,
            express_max_batch=express_max_batch,
            stream_windows=stream_windows,
            metrics=metrics,
        )
        # O(churn) graph maintenance: every state transition below is
        # mirrored as a note; begin_round patches instead of rebuilding
        self.incremental_build = incremental_build
        self._graph = (
            IncrementalFlowGraphBuilder(
                preemption=enable_preemption,
                migration_hysteresis=migration_hysteresis,
            )
            if incremental_build else None
        )
        # bounded: a daemon running forever must not grow without bound
        # (full history goes to the trace stream when a sink is set).
        # Entries are (round_num, kind, uid, detail) where detail is
        # the machine (PLACE), "from->to" (MIGRATE), or the evicted-
        # from machine (PREEMPT).
        self.decision_log: collections.deque[
            tuple[int, str, str, str]
        ] = collections.deque(maxlen=100_000)
        self._evictions_this_round = 0
        self._bind_failures = 0
        self._fetch_timeouts = 0
        self._degrades_total = 0
        # per-round accumulators surfaced in SchedulerStats: observe
        # host time and watch degradation counts since the last round
        self._observe_ms = 0.0
        self._watch_resyncs = 0
        self._watch_reconnects = 0
        # consecutive implausible-shrink polls (mass-eviction guard),
        # plus the monotonic stamp of each hold's first strike (the
        # NotReady grace window's clock; 0.0 = not holding)
        self._node_shrink_strikes = 0
        self._pod_shrink_strikes = 0
        self._node_shrink_strikes_first = 0.0
        self._pod_shrink_strikes_first = 0.0
        self.shrink_grace_s = shrink_grace_s
        # staged re-queue of node-death displacement: RUNNING pods on
        # a dead node flip to Pending but only ``max_migrations_per_
        # round`` of them become SCHEDULABLE per round — the rest park
        # here (ordered, FIFO admission at begin_round) so a rack loss
        # drains as bounded waves, not one migration-storm round.
        # Parked pods stay in self.tasks (state truth) but are
        # excluded from cluster_state()/the graph until admitted.
        self._displaced_parked: dict[str, None] = {}
        self._requeue_budget_left: int | None = (
            max_migrations_per_round
            if max_migrations_per_round > 0 else None
        )  # None = unlimited; refreshed every begin_round
        # resync-storm trip for the flight recorder: a sliding window
        # of per-round resync counts (the obs/metrics.py storm gauge's
        # twin), latched so a persisting storm dumps once, not every
        # round
        self._resync_window: collections.deque[int] = collections.deque(
            maxlen=STORM_WINDOW
        )
        self._storm_dumped = False
        self._inflight: InflightRound | None = None
        # ---- express-lane bookkeeping (all empty with the flag off) ----
        # bound pods whose on-HBM rows the next express dispatch
        # retires (uid, machine); cleared when a round replaces the
        # context
        self._express_retire: list[tuple[str, str]] = []
        # express placements awaiting the driver's confirm_binding —
        # a second batch before confirmation would re-solve rows whose
        # POST is already on the wire, so express refuses until drained
        self._express_unconfirmed: set[str] = set()
        # placements since the last full round, for the correction
        # pass's differential verify (uid -> machine)
        self._express_placed: dict[str, str] = {}
        # per-round-window counters + the event-to-bind accumulator
        self._express_batches = 0
        self._express_places = 0
        self._express_degrades = 0
        self._express_e2b: list[float] = []
        # ---- stream-lane bookkeeping (--stream_windows K) ----
        # per-uid watch receipt stamps of the windows accumulated since
        # the last flush/finish: each stream placement's e2b is ITS
        # latency measured at finish time, spanning the whole K-window
        # accumulation (the sync amortization's honest cost)
        self._stream_uid_t: dict[str, float] = {}
        self._stream_t0: float | None = None
        self._stream_flushes = 0

    def _guard_release(self, kind: str, outcome: str, *,
                       gone: int = 0, known: int = 0,
                       strikes: int = 0, held_s: float = 0.0) -> None:
        """One guard release: trace event + metrics (outcome is
        "accepted" — the shrink was honored as true death — or
        "recovered" — the snapshot healed before the bound)."""
        self.trace.emit(
            "EVICTION_GUARD_RELEASE", round_num=self.round_num,
            detail={"kind": kind, "outcome": outcome, "gone": gone,
                    "known": known, "strikes": strikes,
                    "held_s": round(held_s, 3)},
        )
        if self.metrics is not None:
            self.metrics.record_guard_release(kind, outcome)

    def _hold_shrink(self, counter: str, kind: str, known: int,
                     gone: int) -> bool:
        """Mass-eviction guard: True = hold this poll's disappearances.

        ``known`` is the entity count BEFORE the poll's upserts — a
        truncated snapshot that also carries new names must not inflate
        the denominator and slip past the threshold.

        Two exits (both loud — EVICTION_GUARD_RELEASE + metrics):
        the shrink persists ``SHRINK_STRIKES`` consecutive polls, or
        it persists past the ``shrink_grace_s`` NotReady grace window
        (``--node_grace_s``) — after either, the disappearances are
        accepted as TRUE death and the displaced RUNNING pods drain
        through the staged-requeue budget instead of one storm round.
        A snapshot that heals mid-hold releases with
        ``outcome="recovered"`` and nothing is evicted.
        """
        first_attr = counter + "_first"
        if known < SHRINK_MIN_KNOWN or gone * 2 <= known:
            if getattr(self, counter):
                # the hold healed: the disappearance was transient
                self._guard_release(
                    kind, "recovered", gone=gone, known=known,
                    strikes=getattr(self, counter),
                    held_s=time.monotonic() - getattr(self, first_attr),
                )
            setattr(self, counter, 0)
            setattr(self, first_attr, 0.0)
            return False
        strikes = getattr(self, counter) + 1
        setattr(self, counter, strikes)
        now = time.monotonic()
        if strikes == 1:
            setattr(self, first_attr, now)
        held_s = now - getattr(self, first_attr)
        grace_hit = (
            self.shrink_grace_s > 0 and held_s >= self.shrink_grace_s
        )
        if strikes < SHRINK_STRIKES and not grace_hit:
            log.warning(
                "%s snapshot lost %d of %d known; holding (strike "
                "%d/%d, held %.1fs of %.1fs grace) — truncated list "
                "response?",
                kind, gone, known, strikes, SHRINK_STRIKES, held_s,
                self.shrink_grace_s,
            )
            self.trace.emit(
                "EVICTION_GUARD_HOLD", round_num=self.round_num,
                detail={"kind": kind, "gone": gone, "known": known,
                        "strike": strikes,
                        "held_s": round(held_s, 3)},
            )
            if self.metrics is not None:
                self.metrics.record_guard_hold(kind)
            return True
        log.warning(
            "%s shrink persisted (%d polls, %.1fs); accepting it as "
            "true death", kind, strikes, held_s,
        )
        self._guard_release(
            kind, "accepted", gone=gone, known=known,
            strikes=strikes, held_s=held_s,
        )
        setattr(self, counter, 0)
        setattr(self, first_attr, 0.0)
        return False

    # ---- observation (the poll side) -----------------------------------

    def _upsert_node(self, node: Machine) -> str:
        """One node's upsert: state, churn notes, knowledge sample.
        Shared by the poll snapshot diff and the watch event path so
        both drive identical transitions. Returns the node name."""
        g = self._graph
        if node.max_tasks <= 0:
            node = dataclasses.replace(
                node, max_tasks=self.max_tasks_per_machine
            )
        prev = self.machines.get(node.name)
        if prev is None:
            log.info("new node %s (rack=%s)", node.name, node.rack)
            if g:
                g.note_full_rebuild("node added")
        elif g and (prev.rack != node.rack
                    or prev.max_tasks != node.max_tasks):
            # graph-shaping attributes changed under us
            g.note_full_rebuild("node reshaped")
        self.machines[node.name] = node
        cap = max(node.cpu_capacity, 1e-9)
        mem_cap = max(node.memory_capacity_kb, 1)
        self.knowledge.add_machine_sample(
            node.name,
            MachineSample(
                cpu_idle=min(node.cpu_allocatable / cap, 1.0),
                mem_free_frac=min(
                    node.memory_allocatable_kb / mem_cap, 1.0
                ),
            ),
        )
        return node.name

    def _requeue_take(self) -> bool:
        """Consume one unit of the per-round staged-requeue budget
        (True = schedulable now, False = park for a later wave)."""
        if self._requeue_budget_left is None:
            return True
        if self._requeue_budget_left > 0:
            self._requeue_budget_left -= 1
            return True
        return False

    def _remove_node(self, name: str) -> None:
        """Release a machine: its Running tasks flip back to Pending
        (they will be re-placed) and are logged as evictions.

        Displacement is budget-staged: every displaced pod parks in
        ``_displaced_parked`` and re-enters the schedulable set in
        FIFO waves of at most ``max_migrations_per_round`` per
        ``begin_round`` (shared across every node death — a rack loss
        via N watch DELETED events drains exactly like one mass poll
        shrink). Observe precedes begin in the tick, so a small
        removal's pods are admitted the SAME tick they were displaced
        — behavior is unchanged below the budget; above it, the storm
        drains as bounded waves instead of one re-placement storm.
        State truth is immediate (the pod IS Pending, the machine IS
        gone); only the *re-placement rate* is bounded."""
        if name not in self.machines:
            return
        log.warning("node %s removed; evicting its tasks", name)
        if self._graph:
            self._graph.note_full_rebuild("node removed")
        del self.machines[name]
        self.knowledge.retire_machine(name)
        for uid, task in list(self.tasks.items()):
            if task.machine == name:
                self.tasks[uid] = dataclasses.replace(
                    task, phase=TaskPhase.PENDING, machine=""
                )
                self.pod_to_machine.pop(uid, None)
                self._displaced_parked[uid] = None
                self.trace.emit("EVICT", task=uid, machine=name,
                                round_num=self.round_num,
                                detail={"parked": True})
                self._evictions_this_round += 1

    def observe_nodes(self, nodes: list[Machine]) -> None:
        """Upsert machines; release the ones that disappeared."""
        t0 = time.perf_counter()
        # a snapshot diff can move anything (and node changes reshape
        # the machine axis): the on-HBM express context cannot follow
        if self.express_lane:
            self.solver.invalidate_express()
        try:
            known_before = len(self.machines)
            known_names = set(self.machines)
            seen = set()
            for node in nodes:
                seen.add(self._upsert_node(node))
            gone = known_names - seen
            if self._hold_shrink(
                "_node_shrink_strikes", "node", known_before, len(gone)
            ):
                return
            for name in gone:
                self._remove_node(name)
        finally:
            self._observe_ms += (time.perf_counter() - t0) * 1000

    def observe_node_event(
        self, type_: str, node: Machine
    ) -> None:
        """Event-driven observe: one typed node event from the watch
        stream (ADDED | MODIFIED upsert, DELETED release). Drives the
        same transitions and churn notes as the poll diff; an explicit
        DELETED bypasses the mass-eviction guard on purpose — the guard
        defends against *truncated snapshots*, and an event stream
        never infers deletion from absence (resyncs go back through
        ``observe_nodes`` and get the guard)."""
        t0 = time.perf_counter()
        if self.express_lane:
            self.solver.invalidate_express()
        try:
            if type_ == "DELETED":
                self._remove_node(node.name)
            else:
                self._upsert_node(node)
        finally:
            self._observe_ms += (time.perf_counter() - t0) * 1000

    def _pending_reobserved(
        self, known: Task, pod: Task, stored: Task
    ) -> None:
        """Graph notes for a pending pod re-observed as pending (the
        stored object is swapped; only cpu/mem changes are patchable —
        job/pref reshapes change arc structure mid-order)."""
        g = self._graph
        if not g:
            return
        if pod.uid in self._displaced_parked:
            # parked displacement: the builder never saw this task
            # (cluster_state excludes it), so no targeted note can
            # apply — the admitted task carries its current shape
            return
        if known.job != pod.job or not (
            known.data_prefs is pod.data_prefs
            or known.data_prefs == pod.data_prefs
        ):
            g.note_full_rebuild("pending pod reshaped")
        elif (known.cpu_request != pod.cpu_request
              or known.memory_request_kb != pod.memory_request_kb):
            g.note_task_updated(stored)

    def _upsert_pod(self, pod: Task) -> str:
        """One pod's state-machine dispatch (the reference's per-pod
        switch, scheduler_bridge.cc:132-162). Shared by the poll
        snapshot diff and the watch event path so both drive identical
        transitions and churn notes. Returns the uid."""
        g = self._graph
        known = self.tasks.get(pod.uid)
        if pod.phase == TaskPhase.PENDING:
            if known is None:
                log.info("new pending pod %s", pod.uid)
                self.trace.emit("SUBMIT", task=pod.uid,
                                round_num=self.round_num)
                if self.lifecycle is not None:
                    # timeline zero: first sight of schedulable work
                    # (the express path backdates to the watch
                    # dequeue stamp when the driver has one)
                    self.lifecycle.stamp_event(pod.uid)
                self.tasks[pod.uid] = pod
                if g:
                    g.note_task_added(pod)
            elif (
                known.phase == TaskPhase.RUNNING and known.machine
            ):
                # a locally-confirmed binding outlives apiserver
                # poll latency: the pod still reads Pending until
                # the watch cache catches up, and downgrading here
                # would re-schedule it (double-binding + the slot
                # discount lost)
                pass
            else:
                # keep our aging counter across polls
                stored = dataclasses.replace(
                    pod, wait_rounds=known.wait_rounds
                )
                if known.phase != TaskPhase.PENDING:
                    if g:
                        g.note_full_rebuild("pod re-entered pending")
                else:
                    self._pending_reobserved(known, pod, stored)
                self.tasks[pod.uid] = stored
        elif pod.phase == TaskPhase.RUNNING:
            if pod.machine and pod.machine not in self.machines:
                # The apiserver still reports a binding to a node we
                # no longer know (removed in observe_nodes). Adopting
                # it would silently undo the eviction and park the
                # pod on a ghost machine forever; keep it Pending
                # (aging preserved) so the next round re-places it.
                log.warning(
                    "pod %s bound to unknown node %s; keeping it "
                    "Pending for re-placement", pod.uid, pod.machine,
                )
                wait = known.wait_rounds if known is not None else 0
                stored = dataclasses.replace(
                    pod, phase=TaskPhase.PENDING, machine="",
                    wait_rounds=wait,
                )
                if known is None:
                    if g:
                        g.note_task_added(stored)
                elif known.phase == TaskPhase.PENDING:
                    self._pending_reobserved(known, pod, stored)
                elif g:
                    g.note_full_rebuild("pod re-entered pending")
                self.tasks[pod.uid] = stored
                self.pod_to_machine.pop(pod.uid, None)
                return pod.uid
            if known is None or known.machine != pod.machine:
                # restart reconcile: adopt the apiserver's binding
                # instead of the reference's CHECK-crash
                # (scheduler_bridge.cc:146-147)
                log.info(
                    "adopting running pod %s on %s",
                    pod.uid, pod.machine,
                )
            # the poll carries no aging (wait_rounds is bridge-
            # internal): preserve it so a later preemption parks
            # the pod with its starvation pressure intact
            stored = (
                dataclasses.replace(
                    pod, wait_rounds=known.wait_rounds
                )
                if known is not None else pod
            )
            if pod.uid in self._displaced_parked:
                # a parked displacement adopted Running on a live
                # machine (external writer / node resurrection):
                # unpark; the builder never saw the parked task, so
                # targeted notes below cannot apply — one full
                # rebuild covers the transition
                del self._displaced_parked[pod.uid]
                if g:
                    g.note_full_rebuild("parked pod adopted running")
                g = None
            if g:
                if known is not None and known.phase == TaskPhase.PENDING:
                    g.note_task_removed(pod.uid)
                was_on = (
                    known.machine
                    if known is not None
                    and known.phase == TaskPhase.RUNNING else ""
                )
                if self.enable_preemption:
                    self._running_reobserved(
                        known, pod, stored, was_on
                    )
                elif was_on != pod.machine:
                    if was_on and was_on in self.machines:
                        g.note_slots_changed(was_on, -1)
                    if pod.machine:
                        g.note_slots_changed(pod.machine, +1)
            self.tasks[pod.uid] = stored
            if pod.machine:
                self.pod_to_machine[pod.uid] = pod.machine
            self.knowledge.add_task_sample(
                pod.uid,
                TaskSample(
                    cpu_usage=pod.cpu_request,
                    mem_usage_kb=pod.memory_request_kb,
                ),
            )
        else:  # Succeeded / Failed / Unknown: retire, free the slot
            if known is not None:
                log.info("retiring pod %s (%s)", pod.uid, pod.phase)
                self.trace.emit("FINISH", task=pod.uid,
                                machine=known.machine,
                                round_num=self.round_num,
                                detail={"phase": str(pod.phase.value)})
                self._retire_notes(known)
                self.tasks.pop(pod.uid, None)
                self.pod_to_machine.pop(pod.uid, None)
                self.knowledge.retire_task(pod.uid)
                if self.lifecycle is not None:
                    self.lifecycle.drop(pod.uid)
        return pod.uid

    def _remove_pod(self, uid: str) -> None:
        """A pod left the cluster without a terminal phase (poll: gone
        from the snapshot; watch: an explicit DELETED event): retire it
        silently — no FINISH event, matching the poll diff."""
        task = self.tasks.pop(uid, None)
        if task is not None:
            self._retire_notes(task)
        self.pod_to_machine.pop(uid, None)
        self.knowledge.retire_task(uid)
        if self.lifecycle is not None:
            self.lifecycle.drop(uid)

    def observe_pods(self, pods: list[Task]) -> None:
        """The reference's per-pod dispatch (scheduler_bridge.cc:132-162),
        with restart reconcile and terminal-state retirement."""
        t0 = time.perf_counter()
        if self.express_lane:
            self.solver.invalidate_express()
        try:
            known_before = len(self.tasks)
            known_uids = set(self.tasks)
            seen = set()
            for pod in pods:
                seen.add(self._upsert_pod(pod))
            gone = known_uids - seen
            if self._hold_shrink(
                "_pod_shrink_strikes", "pod", known_before, len(gone)
            ):
                return
            for uid in gone:
                self._remove_pod(uid)
        finally:
            self._observe_ms += (time.perf_counter() - t0) * 1000

    def observe_pod_event(self, type_: str, pod: Task) -> None:
        """Event-driven observe: one typed pod event from the watch
        stream. ADDED | MODIFIED run the normal per-pod dispatch
        (which already handles every phase, including terminal ones);
        DELETED retires the pod like a poll disappearance. Explicit
        deletions bypass the mass-eviction guard by design — see
        ``observe_node_event``."""
        t0 = time.perf_counter()
        try:
            if type_ == "DELETED":
                self._remove_pod(pod.uid)
            else:
                self._upsert_pod(pod)
        finally:
            self._observe_ms += (time.perf_counter() - t0) * 1000

    def note_watch_activity(
        self, resyncs: int = 0, reconnects: int = 0
    ) -> None:
        """Driver reports the watcher's degradation counts for this
        tick; they surface in the next round's ``SchedulerStats``."""
        self._watch_resyncs += resyncs
        self._watch_reconnects += reconnects

    def _note_trace_drops(self) -> None:
        """Mirror the trace ring's overwrite count into the metrics
        counter (per-round delta; zero increments are free)."""
        drops = self.trace.dropped_total
        if drops != self._trace_drops_seen:
            self.metrics.record_trace_dropped(
                drops - self._trace_drops_seen
            )
            self._trace_drops_seen = drops

    def flight_dump(
        self, reason: str = "manual", label: str = ""
    ) -> str | None:
        """Dump the flight-recorder ring (anomaly sites call this with
        their bounded reason; operators/drivers call it on demand).
        Returns the manifest path, or None when the recorder is off or
        the ring is empty. Every dump is loud: a FLIGHTREC_DUMP trace
        event plus ``poseidon_flightrec_dumps_total{reason}``."""
        if self.flightrec is None:
            return None
        path = self.flightrec.dump(reason, label=label)
        if path is not None:
            self.trace.emit(
                "FLIGHTREC_DUMP", round_num=self.round_num,
                detail={"reason": reason, "label": label,
                        "path": path},
            )
            self.trace.flush()
        return path

    # ---- the express lane (between-ticks fast path) --------------------

    def _express_invalidate(self, count_degrade: bool = False,
                            why: str = "") -> None:
        if self.solver.express_ready:
            self.solver.invalidate_express()
            if count_degrade:
                self._express_degrades += 1
                self.trace.emit(
                    "EXPRESS_DEGRADE", round_num=self.round_num,
                    detail={"why": why},
                )
                self.trace.flush()
                if self.metrics is not None:
                    self.metrics.record_express_degrade(why)
                self.flight_dump("express-degrade", label=why)

    def _express_transitions(
        self, before: dict[str, Task | None]
    ) -> tuple[list[Task], list[str], list[tuple[str, int]]]:
        """Net per-uid effect of one applied event batch: arrivals,
        pending removals, and slot restores. Duplicate watch events for
        one uid (stream replays) coalesce here BY CONSTRUCTION — the
        diff is before-state vs after-state, so a double ADDED or an
        ADDED+DELETED pair within one batch can never double-apply at
        the device patch. Raises ``ValueError`` (caught by the caller
        into a degrade) for any transition outside the express
        vocabulary."""
        arrivals: list[Task] = []
        removals: list[str] = []
        slot_deltas: list[tuple[str, int]] = []
        for uid, b in before.items():
            a = self.tasks.get(uid)
            if b is None and a is None:
                continue  # arrived and left inside the batch: net noop
            if b is None:
                if a.phase == TaskPhase.PENDING:
                    arrivals.append(a)
                else:
                    raise ValueError(
                        f"{uid} entered as {a.phase.value} (adoption)"
                    )
            elif a is None:
                if b.phase == TaskPhase.PENDING:
                    removals.append(uid)
                elif b.phase == TaskPhase.RUNNING and b.machine:
                    slot_deltas.append((b.machine, +1))
                else:
                    raise ValueError(
                        f"{uid} left from phase {b.phase.value}"
                    )
            elif (b.phase == TaskPhase.PENDING
                  and a.phase == TaskPhase.PENDING):
                if (b.cpu_request != a.cpu_request
                        or b.memory_request_kb != a.memory_request_kb
                        or b.job != a.job
                        or b.data_prefs != a.data_prefs):
                    raise ValueError(f"{uid} reshaped while pending")
                # identical re-observation (replayed event): noop
            elif (b.phase == TaskPhase.RUNNING
                  and a.phase == TaskPhase.RUNNING):
                if b.machine != a.machine:
                    raise ValueError(f"{uid} moved machines externally")
            else:
                raise ValueError(
                    f"{uid} transitioned {b.phase.value} -> "
                    f"{a.phase.value}"
                )
        return arrivals, removals, slot_deltas

    def express_batch(
        self,
        pod_events: list[tuple[str, Task]],
        *,
        t_event: float | None = None,
        t_events: list[float] | None = None,
    ) -> ExpressResult | None:
        """The express fast path: apply a small watch-event batch and —
        when the on-HBM context can represent its net effect — turn it
        into bindings NOW, without waiting for the round tick.

        The events are ALWAYS applied to bridge state (via the same
        ``observe_pod_event`` transitions and incremental-builder notes
        as the tick path, so the periodic correction round sees an
        identical graph). Returns ``None`` when the express lane is
        off, no warm context exists, or the batch degrades — the pods
        then simply wait for the next full round. ``t_event`` (a
        ``perf_counter`` stamp of the earliest event's receipt) feeds
        the event-to-bind latency accumulator; ``t_events`` (parallel
        to ``pod_events``, watch ``ExpressEvents.t_events``) gives each
        placement a real per-event sample — without it every placement
        reports the batch latency measured from ``t_event``.
        """
        t0 = time.perf_counter()
        before: dict[str, Task | None] = {}
        for _typ, pod in pod_events:
            if pod.uid not in before:
                before[pod.uid] = self.tasks.get(pod.uid)
        for typ, pod in pod_events:
            self.observe_pod_event(typ, pod)
        if self.lifecycle is not None and t_events is not None:
            # per-event watch receipt stamps precede the observe that
            # minted the timelines: backdate (earliest wins)
            for (_typ, pod), ts in zip(pod_events, t_events):
                self.lifecycle.backdate_event(pod.uid, ts)
        if not self.express_lane:
            return None
        if not self.solver.express_ready or self._inflight is not None:
            # no warm context (or a round owns the device): the events
            # wait for the round path; nothing to invalidate beyond
            # what observe already did
            self.solver.invalidate_express()
            return None
        if self._express_unconfirmed:
            # a prior batch's placements were never confirmed: their
            # rows are still live on device and a re-solve could move
            # pods whose POSTs are on the wire
            self._express_invalidate(
                count_degrade=True, why="unconfirmed placements"
            )
            return None
        try:
            arrivals, removals, slot_deltas = (
                self._express_transitions(before)
            )
        except ValueError as e:
            self._express_invalidate(count_degrade=True, why=str(e))
            return None
        if not (arrivals or removals or slot_deltas
                or self._express_retire):
            return None  # pure replay noise: nothing to do
        try:
            maps = self.solver.express_maps()
        except ExpressDegrade as e:
            self._express_invalidate(count_degrade=True, why=str(e))
            return None
        if maps is None:
            return None
        midx, rack_idx = maps
        builder = (
            self._graph.builder if self._graph is not None
            else FlowGraphBuilder(preemption=self.enable_preemption)
        )
        batch = ExpressBatch(
            arrivals=[
                ExpressArrival(
                    uid=t.uid,
                    wait_rounds=t.wait_rounds,
                    cpu_milli=int(t.cpu_request * 1000),
                    mem_kb=t.memory_request_kb,
                    prefs=tuple(
                        builder.task_arc_rows(t, midx, rack_idx)
                    ),
                )
                for t in arrivals
            ],
            retires=self._express_retire,
            removals=removals,
            slot_deltas=slot_deltas,
        )
        self._express_retire = []
        outcome = self.solver.express_round(batch)
        if not outcome.ok:
            self._express_degrades += 1
            self.trace.emit(
                "EXPRESS_DEGRADE", round_num=self.round_num,
                detail={"why": outcome.reason},
            )
            self.trace.flush()
            if self.metrics is not None:
                self.metrics.record_express_degrade(outcome.reason)
            if self.flightrec is not None:
                # record the degraded batch's inputs, then dump: "what
                # exactly did the express lane choke on" survives
                self.flightrec.capture_express(
                    self.round_num, batch, outcome
                )
                self.flight_dump(
                    "express-degrade", label=outcome.reason
                )
            return None
        if outcome.degrade_reason:
            # a CERTIFIED batch that degraded loudly mid-flight (the
            # change-cap overflow's full placement fetch): every
            # placement below still binds and the context stays warm —
            # trace + count the degrade WITHOUT invalidating
            self._express_degrades += 1
            self.trace.emit(
                "EXPRESS_DEGRADE", round_num=self.round_num,
                detail={"why": outcome.degrade_reason},
            )
            if self.metrics is not None:
                self.metrics.record_express_degrade(
                    outcome.degrade_reason
                )
        self._express_batches += 1
        bindings: dict[str, str] = {}
        t_done = time.perf_counter()
        latency = (t_done - (t_event if t_event is not None else t0)) \
            * 1000
        # per-uid receipt stamps (earliest wins across coalesced
        # duplicates) so each placement's e2b is ITS latency, not the
        # batch's replicated onto every event
        uid_t: dict[str, float] = {}
        if t_events is not None:
            for (_typ, pod), ts in zip(pod_events, t_events):
                uid_t.setdefault(pod.uid, ts)
        e2b_samples: list[float] = []
        for uid, machine in outcome.placements:
            task = self.tasks.get(uid)
            if (task is None or task.phase != TaskPhase.PENDING
                    or machine not in self.machines):
                # should be unreachable (express_batch owns the window
                # between observe and bind): degrade rather than bind
                # against moved state
                self._express_invalidate(
                    count_degrade=True,
                    why=f"placement target moved for {uid}",
                )
                return None
            bindings[uid] = machine
            self._express_placed[uid] = machine
            self._express_unconfirmed.add(uid)
            if self.lifecycle is not None:
                self.lifecycle.stamp_decided(uid, "express")
            self.decision_log.append((
                self.round_num, "PLACE", uid,
                {"machine": machine, "express": True},
            ))
            e2b = (
                (t_done - uid_t[uid]) * 1000 if uid in uid_t
                else latency
            )
            self.trace.emit(
                "EXPRESS_PLACE", task=uid, machine=machine,
                round_num=self.round_num,
                # per-placement event-to-bind-decision latency (ms,
                # monotonic-clock difference from the event's OWN
                # receipt stamp when the driver supplied one)
                detail={"e2b_ms": round(e2b, 3)},
            )
            self._express_e2b.append(e2b)
            e2b_samples.append(e2b)
        self._express_places += len(bindings)
        if self.profile_spans:
            emit_span(
                self.trace,
                express_span_tree(latency, outcome.timings),
                self.round_num,
            )
        self.trace.flush()
        if self.metrics is not None:
            self.metrics.record_express_batch(e2b_samples)
        if self.flightrec is not None:
            self.flightrec.capture_express(
                self.round_num, batch, outcome, placements=bindings
            )
        return ExpressResult(
            bindings=bindings,
            cost=outcome.cost,
            rounds=outcome.rounds,
            latency_ms=latency,
            timings=outcome.timings,
        )

    # ---- the streaming lane (--stream_windows K) -----------------------

    def stream_window(
        self,
        pod_events: list[tuple[str, Task]],
        *,
        t_event: float | None = None,
        t_events: list[float] | None = None,
    ) -> bool:
        """Accumulate one watch-event window into the pending stream
        batch (``express_batch``'s head — the SAME observe transitions,
        coalescing, and degrade gates — but the solve is deferred:
        ``stream_flush`` scans K accumulated windows as one device
        program with ONE fetch). Returns True when the window was
        accumulated (or was pure replay noise); False means the stream
        degraded and the events wait for the next full round. The
        events are ALWAYS applied to bridge state either way."""
        t0 = time.perf_counter()
        before: dict[str, Task | None] = {}
        for _typ, pod in pod_events:
            if pod.uid not in before:
                before[pod.uid] = self.tasks.get(pod.uid)
        for typ, pod in pod_events:
            self.observe_pod_event(typ, pod)
        if self.lifecycle is not None and t_events is not None:
            for (_typ, pod), ts in zip(pod_events, t_events):
                self.lifecycle.backdate_event(pod.uid, ts)
        if not self.express_lane:
            return False
        if not self.solver.express_ready or self._inflight is not None:
            self.solver.invalidate_express()
            return False
        if self._express_unconfirmed:
            self._express_invalidate(
                count_degrade=True, why="unconfirmed placements"
            )
            return False
        try:
            arrivals, removals, slot_deltas = (
                self._express_transitions(before)
            )
        except ValueError as e:
            self._express_invalidate(count_degrade=True, why=str(e))
            return False
        if not (arrivals or removals or slot_deltas
                or self._express_retire):
            return True  # pure replay noise: nothing to accumulate
        try:
            maps = self.solver.express_maps()
        except ExpressDegrade as e:
            self._express_invalidate(count_degrade=True, why=str(e))
            return False
        if maps is None:
            return False
        midx, rack_idx = maps
        builder = (
            self._graph.builder if self._graph is not None
            else FlowGraphBuilder(preemption=self.enable_preemption)
        )
        batch = ExpressBatch(
            arrivals=[
                ExpressArrival(
                    uid=t.uid,
                    wait_rounds=t.wait_rounds,
                    cpu_milli=int(t.cpu_request * 1000),
                    mem_kb=t.memory_request_kb,
                    prefs=tuple(
                        builder.task_arc_rows(t, midx, rack_idx)
                    ),
                )
                for t in arrivals
            ],
            retires=self._express_retire,
            removals=removals,
            slot_deltas=slot_deltas,
        )
        self._express_retire = []
        outcome = self.solver.stream_window(batch)
        if not outcome.ok:
            self._express_degrades += 1
            self.trace.emit(
                "EXPRESS_DEGRADE", round_num=self.round_num,
                detail={"why": outcome.reason},
            )
            self.trace.flush()
            if self.metrics is not None:
                self.metrics.record_express_degrade(outcome.reason)
            if self.flightrec is not None:
                self.flightrec.capture_express(
                    self.round_num, batch, outcome
                )
                self.flight_dump(
                    "express-degrade", label=outcome.reason
                )
            return False
        # receipt stamps for the finish-side per-placement e2b
        # (earliest wins across coalesced duplicates)
        if self._stream_t0 is None:
            self._stream_t0 = t_event if t_event is not None else t0
        if t_events is not None:
            for (_typ, pod), ts in zip(pod_events, t_events):
                self._stream_uid_t.setdefault(pod.uid, ts)
        elif t_event is not None:
            for _typ, pod in pod_events:
                self._stream_uid_t.setdefault(pod.uid, t_event)
        return True

    def stream_flush(self) -> None:
        """Dispatch the accumulated windows as one scanned device
        program (ONE fetch for all of them). Never blocks: the decision
        log downloads in the background while the NEXT batch's windows
        accumulate; ``stream_finish`` joins it."""
        self.solver.stream_flush()

    def stream_finish(self) -> ExpressResult | None:
        """Join the in-flight stream batch and bind every GOOD
        window's placements (a mid-stream certificate failure still
        binds the windows the scan's latch proved before freezing —
        the degrade is traced and the failed window's events onward
        wait for the next full round). Returns ``None`` when nothing
        was in flight or nothing could bind."""
        out = self.solver.stream_finish()
        if out is None:
            return None
        t_done = time.perf_counter()
        t0 = self._stream_t0
        self._stream_t0 = None
        latency = (t_done - t0) * 1000 if t0 is not None else 0.0
        bindings: dict[str, str] = {}
        e2b_samples: list[float] = []
        window_of: dict[str, int] = {}
        for uid, machine, wdx in out.placements:
            task = self.tasks.get(uid)
            if task is None or task.phase != TaskPhase.PENDING:
                # the pod left (or bound elsewhere) in a LATER window
                # of the same stream batch — the deletion was already
                # applied to bridge state at accumulate time, so the
                # placement is simply stale, not an invariant breach
                self._stream_uid_t.pop(uid, None)
                continue
            if machine not in self.machines:
                self._express_invalidate(
                    count_degrade=True,
                    why=f"placement target moved for {uid}",
                )
                return None
            bindings[uid] = machine
            window_of[uid] = wdx
            self._express_placed[uid] = machine
            self._express_unconfirmed.add(uid)
            if self.lifecycle is not None:
                self.lifecycle.stamp_decided(uid, "stream")
            self.decision_log.append((
                self.round_num, "PLACE", uid,
                {"machine": machine, "express": True,
                 "stream_window": wdx},
            ))
            ts = self._stream_uid_t.pop(uid, None)
            e2b = (t_done - ts) * 1000 if ts is not None else latency
            self.trace.emit(
                "EXPRESS_PLACE", task=uid, machine=machine,
                round_num=self.round_num,
                detail={"e2b_ms": round(e2b, 3),
                        "stream_window": wdx},
            )
            self._express_e2b.append(e2b)
            e2b_samples.append(e2b)
        good = len(out.window_costs)
        self._express_batches += good
        self._express_places += len(bindings)
        self._stream_flushes += 1
        self.trace.emit(
            "STREAM_FLUSH", round_num=self.round_num,
            detail={
                "windows": out.windows,
                "placements": len(bindings),
                "fetches": out.fetches,
                "failed_window": out.failed_window,
            },
        )
        if self.profile_spans:
            emit_span(
                self.trace,
                stream_span_tree(
                    latency, out.timings, windows=out.windows,
                ),
                self.round_num,
            )
        if not out.ok:
            # the solver already invalidated the context; the good
            # windows above are bound, the failed window's events
            # onward wait for the round path
            self._express_degrades += 1
            self.trace.emit(
                "EXPRESS_DEGRADE", round_num=self.round_num,
                detail={"why": out.reason},
            )
            if self.metrics is not None:
                self.metrics.record_express_degrade(out.reason)
            self.flight_dump("express-degrade", label=out.reason)
        self.trace.flush()
        if self.metrics is not None:
            self.metrics.record_express_batch(e2b_samples)
            self.metrics.record_stream_flush(
                out.windows, len(bindings)
            )
        if not bindings and not out.ok:
            return None
        return ExpressResult(
            bindings=bindings,
            cost=sum(out.window_costs),
            rounds=max(out.window_rounds, default=0),
            latency_ms=latency,
            timings=out.timings,
        )

    def _running_reobserved(
        self, known: Task | None, pod: Task, stored: Task, was_on: str
    ) -> None:
        """Rebalancing-mode graph notes for a pod observed RUNNING.

        The running block keys on (uid, machine, job, prefs): machine
        changes patch as moves, cpu/mem as updates, job/pref reshapes
        force a rebuild (they change arc structure mid-order).
        """
        g = self._graph
        if known is None or known.phase != TaskPhase.RUNNING \
                or not was_on:
            # entering the running block (adoption, pending->running,
            # or a Running pod that previously lacked a nodeName)
            if pod.machine:
                g.note_running_added(stored)
            return
        if not pod.machine:
            g.note_running_removed(pod.uid)
            return
        if known.job != pod.job or not (
            known.data_prefs is pod.data_prefs
            or known.data_prefs == pod.data_prefs
        ):
            g.note_full_rebuild("running pod reshaped")
            return
        if was_on != pod.machine:
            g.note_running_moved(pod.uid, pod.machine)
        if (known.cpu_request != pod.cpu_request
                or known.memory_request_kb != pod.memory_request_kb):
            g.note_running_updated(stored)

    def _retire_notes(self, task: Task) -> None:
        """Graph notes for a task leaving the cluster entirely."""
        if task.uid in self._displaced_parked:
            # retired while parked: the builder never saw it — no
            # note; just release the parking slot
            del self._displaced_parked[task.uid]
            return
        g = self._graph
        if not g:
            return
        if task.phase == TaskPhase.PENDING:
            g.note_task_removed(task.uid)
        elif (task.phase == TaskPhase.RUNNING
              and task.machine in self.machines):
            if self.enable_preemption:
                g.note_running_removed(task.uid)
            else:
                g.note_slots_changed(task.machine, -1)

    # ---- the scheduling round ------------------------------------------

    def cluster_state(self) -> ClusterState:
        tasks = list(self.tasks.values())
        if self._displaced_parked:
            # parked node-death displacement waits for its staged-
            # requeue wave: excluded from the schedulable view (state
            # truth — self.tasks — keeps them as Pending throughout)
            parked = self._displaced_parked
            tasks = [t for t in tasks if t.uid not in parked]
        return ClusterState(
            machines=list(self.machines.values()),
            tasks=tasks,
        )

    def run_scheduler(self) -> RoundResult:
        """One serial round: build -> price -> solve -> deltas (the
        reference's RunScheduler + ScheduleAllJobs,
        scheduler_bridge.cc:129-192). Exactly ``begin_round`` +
        ``finish_round`` with no overlapped work between."""
        return self.finish_round(self.begin_round())

    def begin_round(self) -> InflightRound:
        """Build the graph and dispatch the solve asynchronously.

        Returns an ``InflightRound``; the caller may do unrelated host
        work (next poll, binding POSTs) before ``finish_round``. One
        round in flight at a time.
        """
        if self._inflight is not None:
            raise RuntimeError(
                "a scheduling round is already in flight; call "
                "finish_round() first"
            )
        self.round_num += 1
        stats = SchedulerStats(round_num=self.round_num)
        stats.lane = self.lane
        stats.evictions = self._evictions_this_round
        self._evictions_this_round = 0
        stats.bind_failures = self._bind_failures
        self._bind_failures = 0
        stats.fetch_timeouts = self._fetch_timeouts
        self._fetch_timeouts = 0
        stats.degrades_total = self._degrades_total
        stats.observe_ms = round(self._observe_ms, 3)
        self._observe_ms = 0.0
        stats.watch_resyncs = self._watch_resyncs
        self._watch_resyncs = 0
        stats.watch_reconnects = self._watch_reconnects
        self._watch_reconnects = 0
        if self.flightrec is not None:
            # resync-storm trip (the obs storm gauge's recorder twin):
            # a flapping watch stream re-listing the cluster every tick
            # is exactly the incident whose inputs should survive
            self._resync_window.append(stats.watch_resyncs)
            if sum(self._resync_window) >= STORM_RESYNCS:
                if not self._storm_dumped:
                    self._storm_dumped = True
                    self.flight_dump(
                        "resync-storm",
                        label=f"{sum(self._resync_window)} resyncs "
                              f"in the last {STORM_WINDOW} rounds",
                    )
            else:
                self._storm_dumped = False
        stats.express_batches = self._express_batches
        self._express_batches = 0
        stats.express_places = self._express_places
        self._express_places = 0
        stats.express_degrades = self._express_degrades
        self._express_degrades = 0
        if self._express_e2b:
            lat = np.asarray(self._express_e2b)  # noqa: PTA001 -- host list of perf_counter floats, never a device array
            stats.express_e2b_p50_ms = round(
                float(np.percentile(lat, 50)), 3
            )
            stats.express_e2b_p99_ms = round(
                float(np.percentile(lat, 99)), 3
            )
            self._express_e2b = []
        if (
            self.auditor is not None
            and self.machines and self.tasks
            and self.auditor.due(self.round_num)
        ):
            # the shadow audit's sampled capture: post-observe cluster
            # state handed to the background re-solve (PTA001 hot
            # scope on the auditor side; the O(cluster) list copy
            # amortizes over the sampling cadence like the checkpoint
            # capture). Captured BEFORE the build so empty rounds —
            # a drifted place-only cluster with nothing pending rounds
            # empty forever — still get audited.
            self.auditor.capture(
                round_num=self.round_num,
                cost_model=self.cost_model,
                hysteresis=self.migration_hysteresis,
                machines=self.machines,
                tasks=self.tasks,
                knowledge=self.knowledge,
            )
        # staged-requeue wave: refresh the per-round displacement
        # budget and admit the next FIFO wave of parked node-death
        # displacement into the schedulable set (note_task_added —
        # from the builder's view these ARE new pending arrivals)
        self._requeue_budget_left = (
            self.max_migrations_per_round
            if self.max_migrations_per_round > 0 else None
        )
        admitted = 0
        while self._displaced_parked:
            uid = next(iter(self._displaced_parked))
            task = self.tasks.get(uid)
            if task is None or task.phase != TaskPhase.PENDING:
                # moved on while parked (retired/adopted): discard
                # WITHOUT burning a budget unit — a wave peppered
                # with stale entries must still admit a full budget
                # of real pods
                del self._displaced_parked[uid]
                continue
            if not self._requeue_take():
                break
            del self._displaced_parked[uid]
            # re-enter at the END of the insertion order: the builder
            # appends admitted tasks to its pending order, and the
            # cluster view must agree or the self-heal verify would
            # force a full rebuild every admission wave
            del self.tasks[uid]
            self.tasks[uid] = task
            if self._graph:
                self._graph.note_task_added(task)
            admitted += 1
        stats.requeue_admitted = admitted
        stats.displaced_parked = len(self._displaced_parked)
        t_start = time.perf_counter()

        cluster = self.cluster_state()
        pending = cluster.pending()
        stats.pods_total = len(cluster.tasks)
        stats.pods_pending = len(pending)
        # rebalancing rounds run on running tasks alone — correcting a
        # drifted packing needs no pending arrivals. pod_to_machine
        # holds exactly the RUNNING-on-a-known-machine set (every
        # transition that breaks that pops the entry), so this is the
        # O(1) form of the old O(cluster) any()-walk the contract
        # linter flagged (PTA002).
        has_rebal = self.enable_preemption and bool(self.pod_to_machine)
        if not self.machines or (not pending and not has_rebal):
            # an empty round leaves the express context warm (nothing
            # to rebuild) but closes the verify window: place-only
            # placements have no correction pass to wait for
            self._express_placed.clear()
            stats.total_ms = (time.perf_counter() - t_start) * 1000
            stats.wall_ms = stats.total_ms
            self.trace.emit(
                "ROUND", round_num=self.round_num,
                detail=dataclasses.asdict(stats),
            )
            self.trace.flush()
            if self.metrics is not None:
                # empty rounds still carry the window's counters
                # (evictions, watch resyncs, express activity)
                self.metrics.record_round(stats)
                self._note_trace_drops()
            return InflightRound(
                stats=stats,
                result=RoundResult(bindings={}, stats=stats,
                                   unscheduled=[]),
            )

        t0 = time.perf_counter()
        topology = None
        if self._graph is not None:
            arrays, meta = self._graph.build_arrays(cluster, pending)
            stats.build_mode = self._graph.last_build_mode
            cols = self._graph.columns
            topology = topology_from_columns(cols)
            cpu_col, mem_col = self._graph.cost_columns()
        else:
            fb = FlowGraphBuilder(
                preemption=self.enable_preemption,
                migration_hysteresis=self.migration_hysteresis,
            )
            cols = fb.merge_columns(fb.extract_columns(cluster))
            arrays, meta = fb.assemble(cols)
            stats.build_mode = "legacy"
            cpu_col, mem_col = cols.cpu_milli, cols.mem_kb
        stats.build_ms = (time.perf_counter() - t0) * 1000

        machine_names = meta.machine_names
        cost_kwargs = dict(
            task_cpu_milli=cpu_col,
            task_mem_kb=mem_col,
            task_usage=self.knowledge.task_cpu_usage(
                meta.task_uids
            ),
            machine_load=self.knowledge.machine_load(machine_names),
            machine_mem_free=self.knowledge.machine_mem_free(
                machine_names
            ),
        )
        if self.enable_preemption:
            # rebalancing needs the models to see the CURRENT packing:
            # occupancy (running tasks per machine) is what makes a
            # drifted machine expensive and a migration worth its
            # hysteresis. Gated on the flag so place-only pricing stays
            # byte-identical to the pre-rebalancing scheduler. Derived
            # from the merged builder columns (current_m), not a Python
            # walk of cluster.tasks — this path is O(churn) + numpy.
            cur = cols.current_m
            cost_kwargs["machine_used_slots"] = (
                np.bincount(
                    cur[cur >= 0], minlength=len(machine_names)
                ).astype(np.int32)
                if cur is not None
                else np.zeros(len(machine_names), np.int32)
            )
        t0 = time.perf_counter()
        solve = self.solver.begin_round(
            arrays, meta,
            cost_model=self.cost_model,
            topology=topology,
            cost_input_kwargs=cost_kwargs,
        )
        t_end = time.perf_counter()
        stats.dispatch_ms = (t_end - t0) * 1000
        ir = InflightRound(
            stats=stats,
            solve=solve,
            meta=meta,
            t_begin_start=t_start,
            t_begin_end=t_end,
            begin_ms=(t_end - t_start) * 1000,
        )
        if self.flightrec is not None:
            # capture AFTER the dispatch: the arrays are exactly what
            # the solve consumed, the solver's padding floors/dims are
            # this round's, and the warm seed (when clean) is the host
            # mirror the LAST round's fetch already downloaded — no
            # device sync, vectorized copies only (PTA001/PTA002
            # registered scopes in obs/flightrec.py)
            ir.flight = self.flightrec.capture_begin(
                round_num=self.round_num,
                cost_model=self.cost_model,
                flags={
                    "enable_preemption": self.enable_preemption,
                    "migration_hysteresis": self.migration_hysteresis,
                    "max_migrations_per_round":
                        self.max_migrations_per_round,
                    "express_lane": self.express_lane,
                    "express_max_batch": getattr(
                        self.solver, "express_max_batch", 16
                    ),
                    "small_to_oracle": getattr(
                        self.solver, "small_to_oracle", True
                    ),
                    "mesh_width": getattr(self.solver, "mesh_width", 0),
                    "aggregate_classes": getattr(
                        self.solver, "aggregate_classes", False
                    ),
                    "topk_prefs": getattr(self.solver, "topk_prefs", 0),
                    "lane": self.lane,
                    "build_mode": stats.build_mode,
                },
                arrays=arrays,
                meta=meta,
                cost_kwargs=cost_kwargs,
                pad_floors=getattr(self.solver, "pad_floors", {}),
                dims={
                    "Tp": getattr(solve, "Tp", 0),
                    "Mp": getattr(solve, "Mp", 0),
                    "n_prefs": getattr(solve, "n_prefs", 0),
                    "smax": getattr(solve, "smax", 0),
                },
                warm_used=getattr(solve, "warm_used", False),
                warm_seed=(
                    getattr(self.solver, "warm_seed_host", None)
                    if getattr(solve, "warm_used", False) else None
                ),
                rv=self.flight_rv,
            )
        self._inflight = ir
        return ir

    def finish_round(self, ir: InflightRound) -> RoundResult:
        """Join the in-flight solve and apply this round's deltas
        (bindings, aging, stats, trace)."""
        if ir.result is not None:
            return ir.result
        if self._inflight is not ir:
            raise RuntimeError("finish_round() got a stale round")
        self._inflight = None
        stats = ir.stats
        t_fin = time.perf_counter()
        stats.overlap_ms = (t_fin - ir.t_begin_end) * 1000

        # span stamps on the monotonic clock (trace.py clock contract:
        # wall time is for timestamps only, never durations)
        t_join0 = time.monotonic()
        try:
            outcome = self.solver.finish_round(ir.solve)
        except FetchTimeout as e:
            # the pipelined fetch missed its --max_solver_runtime
            # deadline: degrade LOUDLY (trace event + counter surfaced
            # in the NEXT round's stats, since this one is abandoned)
            # and let the driver's round-failure path skip the tick.
            # The flight recorder dumps the abandoned round's inputs —
            # "what was the round doing at the timeout" is exactly the
            # post-mortem question.
            self._fetch_timeouts += 1
            self.trace.emit(
                "FETCH_TIMEOUT", round_num=ir.stats.round_num,
                detail={"error": str(e)},
            )
            self.trace.flush()
            self.flight_dump("fetch-timeout", label=str(e))
            raise
        t_join1 = time.monotonic()
        meta = ir.meta
        # a finished round replaces the express context: whatever
        # retire backlog / unconfirmed set the OLD window accumulated
        # is stale against the new round's rows (stream stamps too —
        # the solver abandoned any pending/in-flight stream batch at
        # begin_round)
        self._express_retire = []
        self._express_unconfirmed.clear()
        self._stream_uid_t.clear()
        self._stream_t0 = None
        # phase accounting: prep+upload feed the price column, the pure
        # device compute is the solve column, the result download the
        # decompose column (transfer vs compute stays distinguishable)
        stats.price_ms = (
            outcome.timings.get("prep_ms", 0.0)
            + outcome.timings.get("upload_ms", 0.0)
        )
        stats.solve_ms = outcome.timings.get("solve_ms", 0.0)
        stats.decompose_ms = (
            outcome.timings.get("fetch_ms", 0.0)
            + outcome.timings.get("oracle_ms", 0.0)
        )
        stats.fetch_wait_ms = outcome.timings.get("fetch_wait_ms", 0.0)
        stats.backend = outcome.backend
        stats.cost = outcome.cost
        # oversize/uncertified degrades are OBSERVABLE, not just
        # logged: a DEGRADE trace event + the lifetime counter in
        # stats. Deliberate routing (small-instance, non-taxonomy
        # graphs) is dispatch, not degradation, and stays uncounted.
        flight_dump_why = ""
        if outcome.backend.startswith("oracle:"):
            why = outcome.backend.split(":", 1)[1]
            if why not in ("small-instance", "not-scheduling-shaped"):
                self._degrades_total += 1
                self.trace.emit(
                    "DEGRADE", round_num=ir.stats.round_num,
                    detail={"why": why, "backend": outcome.backend},
                )
                if self.metrics is not None:
                    self.metrics.record_degrade(why)
                # dumped AFTER the outcome is attached to the record
                # below, so the dump carries this round's result too
                flight_dump_why = why
        stats.degrades_total = self._degrades_total

        # the decision layer: diff the solved assignment against current
        # placements into typed PLACE | MIGRATE | PREEMPT | NOOP records
        # (graph/deltas.py), budget-bounded in rebalancing mode. In
        # place-only mode every task is pending, so this reduces to the
        # old place-or-age classification exactly. Each delta carries
        # its exact route cost + runner-up margin (the attribution pair
        # the solver's one fetch brought back) into the decision log,
        # the trace events, and the explainer.
        dset = extract_deltas(
            meta, outcome.assignment,
            max_migrations=(
                self.max_migrations_per_round
                if self.enable_preemption else 0
            ),
            task_cost=outcome.task_cost,
            task_margin=outcome.task_margin,
        )

        bindings: dict[str, str] = {}
        unscheduled: list[str] = []
        unsched_ages: list[int] = []
        migrations: dict[str, tuple[str, str]] = {}
        preemptions: dict[str, str] = {}
        g = self._graph
        # lifecycle lane for round-path decisions: the service lane's
        # per-tenant sessions stamp "service", everything else "tick"
        lc_lane = "service" if self.lane == "service" else "tick"

        def _age(uid: str, task: Task) -> None:
            # aging: parked pods push harder next round (the
            # Quincy/CoCo unscheduled-cost input)
            self.tasks[uid] = dataclasses.replace(
                task, wait_rounds=task.wait_rounds + 1
            )
            if g:
                g.note_task_aged(uid)
            unscheduled.append(uid)
            unsched_ages.append(task.wait_rounds + 1)

        def _live_pending(uid: str) -> Task | None:
            task = self.tasks.get(uid)
            if task is None or task.phase != TaskPhase.PENDING:
                # the overlap window's poll already moved this pod —
                # retired, or adopted as Running elsewhere (another
                # scheduler / watch catch-up). The in-flight decision
                # is stale for it: binding it would clobber observed
                # truth with a conflicting POST, aging it would age a
                # pod that is not waiting. Skip; a still-pending pod
                # is simply re-offered next round.
                return None
            return task

        for d in dset.place:
            task = _live_pending(d.task)
            if task is None:
                continue
            if d.machine not in self.machines:
                # the target machine disappeared during the overlap
                # window (node removal): confirming would park the pod
                # Running on a ghost. Treat the pod as unplaced — it
                # ages and is reported unscheduled like any other
                # pending pod this round left behind (the node removal
                # already forced a full rebuild).
                _age(d.task, task)
                continue
            bindings[d.task] = d.machine
            if self.lifecycle is not None:
                self.lifecycle.stamp_decided(d.task, lc_lane)
            self.decision_log.append((
                self.round_num, "PLACE", d.task,
                {"machine": d.machine, "cost": d.cost,
                 "margin": d.margin},
            ))
            self.trace.emit("SCHEDULE", task=d.task, machine=d.machine,
                            round_num=ir.stats.round_num,
                            detail={"cost": d.cost,
                                    "margin": d.margin})
            log.info(
                "round %d: PLACE %s -> %s",
                ir.stats.round_num, d.task, d.machine,
            )
        for uid in dset.unscheduled:
            task = _live_pending(uid)
            if task is not None:
                _age(uid, task)
        for d in dset.migrate:
            task = self.tasks.get(d.task)
            if (task is None or task.phase != TaskPhase.RUNNING
                    or task.machine != d.from_machine
                    or d.machine not in self.machines):
                # stale: the pod moved/retired during the overlap
                # window, or the target node vanished — re-proposed
                # next round if still worthwhile
                continue
            migrations[d.task] = (d.from_machine, d.machine)
            self.decision_log.append((
                self.round_num, "MIGRATE", d.task,
                {"from": d.from_machine, "to": d.machine,
                 "cost": d.cost, "margin": d.margin},
            ))
            self.trace.emit(
                "MIGRATE", task=d.task, machine=d.machine,
                round_num=ir.stats.round_num,
                detail={"from": d.from_machine, "cost": d.cost,
                        "margin": d.margin},
            )
            log.info(
                "round %d: MIGRATE %s %s -> %s", ir.stats.round_num,
                d.task, d.from_machine, d.machine,
            )
        for d in dset.preempt:
            task = self.tasks.get(d.task)
            if (task is None or task.phase != TaskPhase.RUNNING
                    or task.machine != d.from_machine):
                continue
            preemptions[d.task] = d.from_machine
            self.decision_log.append((
                self.round_num, "PREEMPT", d.task,
                {"from": d.from_machine, "cost": d.cost,
                 "margin": d.margin},
            ))
            self.trace.emit(
                "PREEMPT", task=d.task, machine=d.from_machine,
                round_num=ir.stats.round_num,
                detail={"cost": d.cost, "margin": d.margin},
            )
            log.info(
                "round %d: PREEMPT %s off %s", ir.stats.round_num,
                d.task, d.from_machine,
            )
        stats.pods_placed = len(bindings)
        stats.pods_unscheduled = len(unscheduled)
        stats.deltas_place = len(bindings)
        stats.deltas_migrate = len(migrations)
        stats.deltas_preempt = len(preemptions)
        stats.deltas_noop = len(dset.noop)
        stats.deltas_deferred = len(dset.deferred)
        if self.express_lane:
            # the correction pass's differential verify: an express
            # placement this round moves (MIGRATE) or parks (PREEMPT)
            # was provably improvable by more than the hysteresis —
            # corrected, counted, traced. Everything else the round
            # left in place is verified final under the stated bound
            # (any remaining per-pod gap is < migration_hysteresis, or
            # the round would have moved it).
            for uid, m in self._express_placed.items():
                if uid in migrations or uid in preemptions:
                    stats.express_corrected += 1
                    self.trace.emit(
                        "EXPRESS_CORRECTED", task=uid, machine=m,
                        round_num=ir.stats.round_num,
                    )
            self._express_placed.clear()
            if self.enable_preemption and (
                preemptions or dset.deferred
            ):
                # the on-HBM seats disagree with reality after a
                # preemption (pod re-enters pending) or a deferred
                # migration (seated at the solve's target, running at
                # the old machine): express sits this window out
                self.solver.invalidate_express()
        t_now = time.perf_counter()
        stats.total_ms = ir.begin_ms + (t_now - t_fin) * 1000
        stats.wall_ms = (t_now - ir.t_begin_start) * 1000
        if self.profile_spans:
            emit_span(
                self.trace,
                round_span_tree(
                    stats,
                    join_ms=(t_join1 - t_join0) * 1000,
                    actuate_ms=(time.monotonic() - t_join1) * 1000,
                ),
                ir.stats.round_num,
            )
        self.trace.emit(
            "ROUND", round_num=ir.stats.round_num,
            detail=dataclasses.asdict(stats),
        )
        self.trace.flush()
        if self.lifecycle is not None:
            # the standing-unscheduled wait-age surface (the ages the
            # _age walk above already collected — no second walk)
            self.lifecycle.note_unscheduled(unsched_ages)
        if self.metrics is not None:
            self.metrics.record_round(stats)
            self._note_trace_drops()
        if self.flightrec is not None:
            self.flightrec.capture_finish(
                ir.flight, outcome, dataclasses.asdict(stats),
                extra={
                    "unscheduled": list(unscheduled),
                    "deferred": [d.task for d in dset.deferred],
                },
            )
            if flight_dump_why:
                self.flight_dump("degrade", label=flight_dump_why)
        return RoundResult(
            bindings=bindings, stats=stats, unscheduled=unscheduled,
            migrations=migrations, preemptions=preemptions,
        )

    def cancel_round(self, ir: InflightRound | None = None) -> None:
        """Abandon an in-flight round (driver error path): join and
        discard the solve so the next ``begin_round`` starts clean."""
        ir = ir if ir is not None else self._inflight
        if ir is None:
            return
        if self._inflight is ir:
            self._inflight = None
        if ir.solve is not None:
            # drain-only: certificate checks / oracle fallback would
            # block the error-recovery path (up to the full oracle
            # timeout) for a result being thrown away. A fetch that
            # misses its deadline here is still surfaced (counter +
            # trace event) like a finish_round miss — discard_round
            # swallows the exception, so diff its counter.
            before = self.solver.fetch_timeouts
            self.solver.discard_round(ir.solve)
            missed = self.solver.fetch_timeouts - before
            if missed:
                self._fetch_timeouts += missed
                self.trace.emit(
                    "FETCH_TIMEOUT", round_num=ir.stats.round_num,
                    detail={"error": "fetch abandoned in cancel_round"},
                )
                self.trace.flush()
                self.flight_dump(
                    "fetch-timeout",
                    label="fetch abandoned in cancel_round",
                )

    def restore_state(
        self,
        *,
        machines: list[Machine],
        tasks: list[Task],
        round_num: int,
        knowledge_state: dict | None = None,
        builder_cols=None,
    ) -> None:
        """Warm-restore rehydration (ha/checkpoint.py): adopt a
        checkpointed cluster image wholesale on a freshly-constructed
        bridge — tasks/machines in their checkpointed insertion order
        (the pending order every graph build depends on), the derived
        ``pod_to_machine`` set, the knowledge sample rings, and
        (optionally) the incremental builder's patchable columns so the
        first post-restore build patches instead of re-extracting.
        The on-HBM express context did not survive the process, so the
        express bookkeeping starts clean; mass-eviction-guard strikes
        reset (a restore begins a fresh observation history — the
        guard itself stays armed across the boundary)."""
        self.machines = {m.name: m for m in machines}
        self.tasks = {t.uid: t for t in tasks}
        self.pod_to_machine = {
            t.uid: t.machine for t in tasks
            if t.phase == TaskPhase.RUNNING
            and t.machine in self.machines
        }
        self.round_num = int(round_num)
        if knowledge_state is not None:
            self.knowledge.restore_state(knowledge_state)
        if self._graph is not None:
            if builder_cols is not None:
                self._graph.restore_columns(builder_cols)
            else:
                self._graph.note_full_rebuild("restore")
        self._express_retire = []
        self._express_unconfirmed.clear()
        self._express_placed.clear()
        self._stream_uid_t.clear()
        self._stream_t0 = None
        if self.express_lane:
            self.solver.invalidate_express()
        self._node_shrink_strikes = 0
        self._pod_shrink_strikes = 0
        self._node_shrink_strikes_first = 0.0
        self._pod_shrink_strikes_first = 0.0
        # parking does not survive the process: restored pods are all
        # schedulable at once (documented — at worst one placement
        # burst after a crash mid-drain, bounded by what was parked)
        self._displaced_parked = {}

    @property
    def solver_timeout_s(self) -> float:
        """Oracle-fallback budget; delegates to the live solver (the
        reference's --max_solver_runtime, poseidon.cfg:14-15)."""
        return self.solver.oracle_timeout_s

    @solver_timeout_s.setter
    def solver_timeout_s(self, value: float) -> None:
        self.solver.oracle_timeout_s = value

    @property
    def warm_state(self):
        """The solver's on-HBM warm handle (assign None to force cold)."""
        return self.solver.warm

    @warm_state.setter
    def warm_state(self, value) -> None:
        if value is not None:
            raise ValueError(
                "warm_state is device-owned; only None (reset) is "
                "assignable"
            )
        self.solver.reset()

    def confirm_binding(self, uid: str, machine: str) -> None:
        """Caller reports a successful bindings POST: mark Running so the
        next build discounts the slot even before the poll reflects it."""
        task = self.tasks.get(uid)
        if task is None:
            return
        stored = dataclasses.replace(
            task, phase=TaskPhase.RUNNING, machine=machine
        )
        g = self._graph
        if g:
            if task.phase == TaskPhase.PENDING:
                g.note_task_removed(uid)
                if self.enable_preemption:
                    g.note_running_added(stored)
                else:
                    g.note_slots_changed(machine, +1)
            elif task.phase == TaskPhase.RUNNING and \
                    task.machine != machine:
                if self.enable_preemption:
                    g.note_running_moved(uid, machine)
                else:
                    if task.machine and task.machine in self.machines:
                        g.note_slots_changed(task.machine, -1)
                    g.note_slots_changed(machine, +1)
        self.tasks[uid] = stored
        self.pod_to_machine[uid] = machine
        if self.lifecycle is not None:
            # the lifecycle close: event -> confirmed, recorded under
            # the lane stamped at decision time
            self.lifecycle.close_confirmed(uid)
        if self.express_lane:
            # the bound pod leaves the pending set: queue the on-HBM
            # retire (row deactivates, seat becomes used capacity) for
            # the next express dispatch
            self._express_unconfirmed.discard(uid)
            if self.solver.express_ready:
                self._express_retire.append((uid, machine))

    def revoke_binding(self, uid: str) -> None:
        """A bindings POST failed after an optimistic ``confirm_binding``
        (the pipelined loop confirms before POSTing, cli.py): flip the
        pod back to Pending so the next round re-offers it. The pod
        re-enters the pending order mid-sequence, so the next graph
        build is a full rebuild."""
        task = self.tasks.get(uid)
        if task is None:
            return
        self.tasks[uid] = dataclasses.replace(
            task, phase=TaskPhase.PENDING, machine=""
        )
        self.pod_to_machine.pop(uid, None)
        if self.lifecycle is not None:
            # the optimistic confirm already closed the timeline:
            # reopen it from its ORIGINAL event stamp so the pod's
            # real end-to-end wait is measured when it finally binds
            self.lifecycle.reopen(uid)
        if self._graph:
            self._graph.note_full_rebuild("binding revoked")
        if self.express_lane:
            # a revoked pod re-enters pending mid-window: outside the
            # express patch vocabulary, wait for the next full round
            self._express_unconfirmed.discard(uid)
            self.solver.invalidate_express()

    def confirm_migration(self, uid: str, machine: str) -> None:
        """Driver reports a MIGRATE actuated (eviction + re-bind POSTs
        landed): move the running task to its new machine."""
        task = self.tasks.get(uid)
        if task is None:
            return
        g = self._graph
        if g:
            if task.phase == TaskPhase.RUNNING:
                if task.machine != machine:
                    g.note_running_moved(uid, machine)
            else:
                g.note_full_rebuild("migration of non-running pod")
        self.tasks[uid] = dataclasses.replace(
            task, phase=TaskPhase.RUNNING, machine=machine
        )
        self.pod_to_machine[uid] = machine

    def confirm_preemption(self, uid: str) -> None:
        """Driver reports a PREEMPT actuated (eviction POST landed):
        park the pod Pending with its aging preserved. The pod re-enters
        the pending order mid-sequence, so the next graph build is a
        full rebuild."""
        task = self.tasks.get(uid)
        if task is None:
            return
        self.tasks[uid] = dataclasses.replace(
            task, phase=TaskPhase.PENDING, machine=""
        )
        self.pod_to_machine.pop(uid, None)
        if self._graph:
            self._graph.note_full_rebuild("preempted back to pending")
        if self.express_lane:
            self.solver.invalidate_express()

    def restore_running(self, uid: str, machine: str) -> None:
        """An eviction/re-bind POST failed (possibly after an optimistic
        ``confirm_migration``/``confirm_preemption``): restore the pod
        to RUNNING on ``machine`` — the apiserver's last-known truth —
        count the failure, and force a full rebuild. If the eviction
        half of a migration did land, the next poll re-observes the true
        state and reconciles."""
        self._bind_failures += 1
        task = self.tasks.get(uid)
        if task is None:
            return
        self.tasks[uid] = dataclasses.replace(
            task, phase=TaskPhase.RUNNING, machine=machine
        )
        self.pod_to_machine[uid] = machine
        if self._graph:
            self._graph.note_full_rebuild("actuation failed")
        if self.express_lane:
            # reality no longer matches the on-HBM seats
            self.solver.invalidate_express()

    def binding_failed(self, uid: str) -> None:
        """A bindings POST for a PLACE failed: count it and re-queue the
        pod as unscheduled — aging preserved and bumped like any other
        round it sat waiting — instead of silently believing the
        placement landed. Handles both the serial path (pod still
        Pending, never confirmed) and the optimistic pipelined path
        (pod confirmed Running first: revoked, then aged)."""
        self._bind_failures += 1
        if self.express_lane:
            # whether revoked or never confirmed, the pod's on-HBM row
            # no longer matches reality (seated but unbound, or aged)
            self._express_unconfirmed.discard(uid)
            self.solver.invalidate_express()
        task = self.tasks.get(uid)
        if task is None:
            return
        if task.phase == TaskPhase.RUNNING:
            self.revoke_binding(uid)
            task = self.tasks[uid]
        if task.phase == TaskPhase.PENDING:
            self.tasks[uid] = dataclasses.replace(
                task, wait_rounds=task.wait_rounds + 1
            )
            if self._graph:
                self._graph.note_task_aged(uid)
