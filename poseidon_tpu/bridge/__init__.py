"""L4' — the scheduler bridge (cluster state <-> solver)."""

from poseidon_tpu.bridge.bridge import (
    ExpressResult,
    RoundResult,
    SchedulerBridge,
    SchedulerStats,
)

__all__ = [
    "SchedulerBridge",
    "SchedulerStats",
    "RoundResult",
    "ExpressResult",
]
